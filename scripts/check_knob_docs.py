#!/usr/bin/env python3
"""Static check: every ``RAFIKI_TPU_*`` NodeConfig env knob is
documented in ``docs/ops.md``.

Run as a tier-1 test (tests/test_config.py invokes it) and standalone:

    python scripts/check_knob_docs.py [repo_root]

The knob surface grows one field at a time (r6 added five serving
knobs, r7 two observability knobs, r9 three trial-lifecycle knobs) and
nothing used to force the ops documentation to keep up. This check
derives the authoritative env-name list from ``NodeConfig`` itself —
every dataclass field's ``env_name()`` (including the ``_ENV_MAP``
back-compat names) must appear verbatim in ``docs/ops.md``, so a new
knob cannot silently go undocumented.

``config.py`` is loaded by file path, NOT via the package import: the
check must run without jax (and without triggering the package's
heavier imports) in any environment that can run pytest.

Exit code 0 = clean; 1 = missing knobs (printed one per line).
"""

from __future__ import annotations

import dataclasses
import importlib.util
import os
import re
import sys


def load_node_config(root: str):
    path = os.path.join(root, "rafiki_tpu", "config.py")
    spec = importlib.util.spec_from_file_location("_rafiki_tpu_config",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves field types through sys.modules[__module__];
    # an unregistered module would break the @dataclass decorator.
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod.NodeConfig


def main(root: str) -> int:
    NodeConfig = load_node_config(root)
    doc_path = os.path.join(root, "docs", "ops.md")
    if not os.path.exists(doc_path):
        print(f"{doc_path}: missing (the knob table lives here)")
        return 1
    with open(doc_path, encoding="utf-8") as f:
        text = f.read()
    missing = []
    fields = dataclasses.fields(NodeConfig)
    for f_ in fields:
        env = NodeConfig.env_name(f_.name)
        # Delimited-token match, not substring: RAFIKI_TPU_METRICS must
        # not count as documented just because RAFIKI_TPU_METRICS_PORT
        # appears somewhere.
        if not re.search(re.escape(env) + r"(?![A-Z0-9_])", text):
            missing.append(
                f"docs/ops.md: NodeConfig.{f_.name} ({env}) is "
                f"undocumented — add it to the knob table")
    for p in missing:
        print(p)
    if not missing:
        print(f"ok: all {len(fields)} NodeConfig knobs documented in "
              f"docs/ops.md")
    return 1 if missing else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else
                  os.path.dirname(os.path.dirname(
                      os.path.abspath(__file__)))))
