#!/usr/bin/env python3
"""Static check: every ``RAFIKI_TPU_*`` NodeConfig env knob is
documented in ``docs/ops.md``. **Thin shim** since the static-analysis
suite landed — the real checker is
``rafiki_tpu.analysis.checkers.drift`` (RTA503); run the whole suite
with

    python -m rafiki_tpu.analysis

This entrypoint keeps the historical contract (tests/test_config.py
and docs reference it, and it still works against an arbitrary tree
whose ``rafiki_tpu/config.py`` is loaded by file path — no jax, no
package import):

    python scripts/check_knob_docs.py [repo_root]

Exit code 0 = clean; 1 = missing knobs (printed one per line).
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from rafiki_tpu.analysis.checkers import drift  # noqa: E402


def main(root: str) -> int:
    findings, n_fields = drift.check_knob_docs(root)
    for f in findings:
        print(f"{f.path}: {f.message}")
    if not findings:
        print(f"ok: all {n_fields} NodeConfig knobs documented in "
              f"docs/ops.md")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else _REPO))
