#!/usr/bin/env python3
"""Static check: every metric registered in the tree follows the
``rafiki_tpu_<subsystem>_<name>_<unit>`` naming convention.

Run as a tier-1 test (tests/test_metrics.py invokes it) and standalone:

    python scripts/check_metrics_names.py [repo_root]

The check is intentionally dumb and fast: it greps every ``.py`` file
under ``rafiki_tpu/`` for string literals starting with ``rafiki_tpu_``
that appear as the first argument of a ``counter(`` / ``gauge(`` /
``histogram(`` call (however the registry is aliased), and validates:

- full name matches ``rafiki_tpu_[a-z0-9]+(_[a-z0-9]+)+``
- the SUBSYSTEM (token after the prefix) is in the known set
- the UNIT (last token) is in the known set, and counters end in
  ``_total``

It ALSO cross-checks the Grafana dashboard JSONs under
``docs/grafana/``: every ``rafiki_tpu_*`` metric a panel expression
references (histogram ``_bucket``/``_sum``/``_count`` suffixes
stripped) must be a name actually registered somewhere in the tree —
so a renamed metric breaks this check instead of silently blanking a
dashboard panel.

Exit code 0 = clean; 1 = violations (printed one per line).
Extending the subsystem/unit vocabulary is a deliberate edit HERE, so
a typo'd metric name can't silently fork the namespace.
"""

from __future__ import annotations

import os
import re
import sys

PREFIX = "rafiki_tpu_"

SUBSYSTEMS = {"bus", "serving", "http", "train", "trial", "trace",
              "node"}

# _total marks counters (Prometheus convention); everything else is the
# physical unit of a gauge/histogram.
UNITS = {"total", "seconds", "ratio", "bytes", "queries", "batches",
         "info"}

NAME_RE = re.compile(r"^rafiki_tpu_[a-z0-9]+(?:_[a-z0-9]+)+$")

# First string argument of a registry call, e.g.:
#   reg.counter(\n    "rafiki_tpu_x_y_total", ...)
CALL_RE = re.compile(
    r"\b(counter|gauge|histogram)\(\s*\n?\s*"
    r"[\"'](" + PREFIX + r"[a-zA-Z0-9_]*)[\"']")


#: Any rafiki_tpu_* token inside a dashboard JSON (panel exprs,
#: label_values templating queries, ...).
DASH_TOKEN_RE = re.compile(r"\brafiki_tpu_[a-z0-9_]+\b")

#: Exposition-level suffixes a histogram's series carry beyond its
#: registered name.
HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def check_file(path: str, registered=None) -> list:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    problems = []
    for match in CALL_RE.finditer(text):
        kind, name = match.group(1), match.group(2)
        if registered is not None:
            registered.add(name)
        line = text[:match.start()].count("\n") + 1
        where = f"{path}:{line}"
        if not NAME_RE.match(name):
            problems.append(f"{where}: {name!r} is not "
                            f"rafiki_tpu_<subsystem>_<name>_<unit>")
            continue
        tokens = name[len(PREFIX):].split("_")
        if tokens[0] not in SUBSYSTEMS:
            problems.append(
                f"{where}: {name!r} subsystem {tokens[0]!r} not in "
                f"{sorted(SUBSYSTEMS)} (extend the set in "
                f"scripts/check_metrics_names.py if intentional)")
        unit = tokens[-1]
        if unit not in UNITS:
            problems.append(
                f"{where}: {name!r} unit {unit!r} not in "
                f"{sorted(UNITS)}")
        if kind == "counter" and unit != "total":
            problems.append(
                f"{where}: counter {name!r} must end in _total")
        if kind != "counter" and unit == "total":
            problems.append(
                f"{where}: {kind} {name!r} must not end in _total")
    return problems


def check_dashboard(path: str, registered: set) -> list:
    """Every metric a dashboard references must be a registered name
    (after stripping the histogram exposition suffixes)."""
    import json

    with open(path, encoding="utf-8") as f:
        try:
            text = f.read()
            json.loads(text)  # a broken dashboard import is a failure
        except json.JSONDecodeError as e:
            return [f"{path}: invalid JSON ({e})"]
    problems = []
    for name in sorted(set(DASH_TOKEN_RE.findall(text))):
        base = name
        for suffix in HIST_SUFFIXES:
            if base.endswith(suffix) and base[:-len(suffix)] in registered:
                base = base[:-len(suffix)]
                break
        if base not in registered:
            problems.append(
                f"{path}: references {name!r}, which no code path "
                f"registers (renamed metric? update the dashboard)")
    return problems


def main(root: str) -> int:
    pkg = os.path.join(root, "rafiki_tpu")
    problems = []
    registered: set = set()
    n_files = 0
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                n_files += 1
                problems.extend(check_file(os.path.join(dirpath, fn),
                                           registered))
    grafana = os.path.join(root, "docs", "grafana")
    n_dash = 0
    if os.path.isdir(grafana):
        for fn in sorted(os.listdir(grafana)):
            if fn.endswith(".json"):
                n_dash += 1
                problems.extend(check_dashboard(
                    os.path.join(grafana, fn), registered))
    for p in problems:
        print(p)
    if not problems:
        print(f"ok: {n_files} files + {n_dash} dashboard(s), all "
              f"metric names conform")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else
                  os.path.dirname(os.path.dirname(
                      os.path.abspath(__file__)))))
