#!/usr/bin/env python3
"""Static check: metric naming convention + Grafana dashboard
references. **Thin shim** since the static-analysis suite landed —
the real checkers are ``rafiki_tpu.analysis.checkers.drift`` (RTA501
metric names, RTA502 dashboard refs); run the whole suite with

    python -m rafiki_tpu.analysis

This entrypoint keeps the historical contract (tests/test_metrics.py
and docs reference it, and it still works against an arbitrary tree):

    python scripts/check_metrics_names.py [repo_root]

Exit code 0 = clean; 1 = violations (printed one per line). The
subsystem/unit vocabulary now lives in the drift checker — extending
it remains a deliberate edit there.
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from rafiki_tpu.analysis.checkers import drift  # noqa: E402


def main(root: str) -> int:
    findings, registered, n_files = drift.check_metric_names(root)
    dash_findings, n_dash = drift.check_dashboards(root, registered)
    findings.extend(dash_findings)
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        print(f"{os.path.join(root, f.path)}:{f.line}: {f.message}")
    if not findings:
        print(f"ok: {n_files} files + {n_dash} dashboard(s), all "
              f"metric names conform")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else _REPO))
