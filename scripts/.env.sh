# Deployment configuration for the ops scripts.
# Parity: SURVEY.md §2 "Ops scripts" — upstream .env.sh exported image
# tags, ports, and DB/Redis credentials; the TPU resident-runner node
# needs only these.

export RAFIKI_TPU_WORKDIR="${RAFIKI_TPU_WORKDIR:-$HOME/.rafiki_tpu}"
export RAFIKI_TPU_ADMIN_PORT="${RAFIKI_TPU_ADMIN_PORT:-3000}"
export RAFIKI_TPU_LOG_LEVEL="${RAFIKI_TPU_LOG_LEVEL:-info}"
# '' = in-process bus (single node); 'tcp://host:port' for multi-host.
export RAFIKI_TPU_BUS_URI="${RAFIKI_TPU_BUS_URI:-}"
# Optional: cap the chips this node owns (default: all of jax.devices()).
export RAFIKI_TPU_CHIPS="${RAFIKI_TPU_CHIPS:-}"
# Optional observability toggles (SURVEY.md §5).
#export RAFIKI_TPU_TRACE_DIR="$RAFIKI_TPU_WORKDIR/traces"
#export RAFIKI_TPU_CKPT=1
