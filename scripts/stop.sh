#!/usr/bin/env bash
# Stop the rafiki-tpu platform node started by start.sh.
set -euo pipefail

cd "$(dirname "$0")/.."
source scripts/.env.sh

PID_FILE="$RAFIKI_TPU_WORKDIR/rafiki.pid"
if [[ ! -f "$PID_FILE" ]]; then
  echo "not running (no $PID_FILE)"
  exit 0
fi
PID="$(cat "$PID_FILE")"
if kill -0 "$PID" 2>/dev/null; then
  kill -TERM "$PID"  # SIGTERM → graceful: stops jobs, closes stores
  for _ in $(seq 1 30); do
    kill -0 "$PID" 2>/dev/null || break
    sleep 1
  done
  kill -0 "$PID" 2>/dev/null && kill -KILL "$PID"
fi
rm -f "$PID_FILE"
echo "stopped"
