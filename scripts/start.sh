#!/usr/bin/env bash
# Start a rafiki-tpu platform node in the background.
# Parity: SURVEY.md §2 "Ops scripts" (upstream start.sh brought up
# Postgres/Redis/Admin/Web containers; here one resident-runner process
# serves the Admin REST API + dashboard and owns the host's TPU chips).
set -euo pipefail

cd "$(dirname "$0")/.."
source scripts/.env.sh

mkdir -p "$RAFIKI_TPU_WORKDIR"
PID_FILE="$RAFIKI_TPU_WORKDIR/rafiki.pid"
LOG_FILE="$RAFIKI_TPU_WORKDIR/rafiki.log"

if [[ -f "$PID_FILE" ]] && kill -0 "$(cat "$PID_FILE")" 2>/dev/null; then
  echo "already running (pid $(cat "$PID_FILE"))"
  exit 0
fi

EXTRA=()
[[ -n "$RAFIKI_TPU_CHIPS" ]] && EXTRA+=(--chips "$RAFIKI_TPU_CHIPS")
[[ -n "$RAFIKI_TPU_BUS_URI" ]] && EXTRA+=(--bus "$RAFIKI_TPU_BUS_URI")

nohup python -m rafiki_tpu serve \
  --workdir "$RAFIKI_TPU_WORKDIR" \
  --port "$RAFIKI_TPU_ADMIN_PORT" \
  --log-level "$RAFIKI_TPU_LOG_LEVEL" \
  "${EXTRA[@]}" >> "$LOG_FILE" 2>&1 &
echo $! > "$PID_FILE"

# Wait for the Admin HTTP frontend to come up.
for _ in $(seq 1 60); do
  if curl -fsS "http://127.0.0.1:$RAFIKI_TPU_ADMIN_PORT/" >/dev/null 2>&1; then
    echo "rafiki-tpu up: http://127.0.0.1:$RAFIKI_TPU_ADMIN_PORT (pid $(cat "$PID_FILE"), log $LOG_FILE)"
    exit 0
  fi
  sleep 1
done
echo "timed out waiting for admin; see $LOG_FILE" >&2
exit 1
