"""SLO engine: objective evaluation + burn-rate alerting on the
supervise cadence.

The judgment layer over the r17 attribution ledger and the r7 metrics
plane (vocabulary in ``observe/slo.py``): one ``sweep()`` per
supervise pass scrapes each RUNNING inference job's predictor
``/metrics`` — the exact text production scrapes, parsed with the same
``parse_exposition`` the bench and the autoscaler trust — folds the
per-sweep event deltas into each objective's window ring, publishes
the error-budget and burn-rate gauges, and advances the per-instance
alert state machines.

Every alert transition is an epoch-stamped, traced
(``slo.<transition>`` span), counted
(``rafiki_tpu_slo_alerts_total{objective, state}`` — the fixed
:data:`~rafiki_tpu.observe.slo.TRANSITIONS` vocabulary) event that
lands in a bounded ring (``GET /alerts``), in a best-effort JSONL
alert log under ``<logs>/alerts.jsonl`` (size-capped, one rolled
generation) and, when ``RAFIKI_TPU_SLO_WEBHOOK_URL`` is set, in one
short-timeout POST per transition so an external pager can attach.

Consumers: the autoscaler asks :meth:`SloEngine.slo_pressure` each
sweep — a FIRING latency objective is a scale-up pressure signal for
the violating job (and, for bin-scoped objectives, the violating bin),
prioritized over its queue signals (docs/autoscaling.md).

Disabled (the default — no ``RAFIKI_TPU_SLO_RULES``) means
``ServicesManager.supervise`` pays ONE attribute check, no engine
exists, and a scrape shows ZERO ``rafiki_tpu_slo_*`` series — the r11
disabled-means-free discipline, gated exactly like the autoscaler.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..observe import metrics as _metrics
from ..observe import slo as _slo
from ..observe import trace as _trace

_log = logging.getLogger(__name__)

#: Alert transitions kept for ``GET /alerts`` (a UI/debug surface, not
#: a log — the JSONL sink is the durable record).
_RING_CAP = 256

#: Instances whose source labels vanish (promotion churn, tenant LRU
#: eviction, job stop) are pruned — and their gauges removed — after
#: this many slow windows of silence.
_PRUNE_AFTER_WINDOWS = 2.0

ALERT_LOG_FILE = "alerts.jsonl"


class SloEngine:
    """Scrape → evaluate → alert, one ``sweep()`` per supervise pass.

    Constructed only when ``RAFIKI_TPU_SLO_RULES`` names at least one
    objective (LocalPlatform); ``ServicesManager.supervise`` holds a
    plain ``slo_engine`` attribute that is None otherwise.
    """

    def __init__(self, services, meta,
                 objectives: List[_slo.Objective],
                 webhook_url: str = "",
                 alert_log_mb: float = 16.0):
        self.services = services
        self.meta = meta
        self.objectives = list(objectives)
        self.webhook_url = webhook_url
        self.alert_log_mb = alert_log_mb
        self.epoch = 0
        # (job_id, objective name, instance label tuple) -> Instance
        self._instances: Dict[Tuple, _slo.Instance] = {}
        # job_id -> (serving service label, http service label) memo.
        self._labels: Dict[str, Tuple[str, str]] = {}
        self._lock = threading.Lock()
        self._ring: "collections.deque" = collections.deque(
            maxlen=_RING_CAP)
        # Webhook deliveries ride a single daemon sender thread with a
        # bounded queue (oldest dropped on overflow — best-effort by
        # contract): a slow/unreachable pager must not stall the
        # supervise thread 2 s per transition during exactly the
        # incident window the sweep is supposed to be reacting to.
        self._webhook_q: "collections.deque" = collections.deque(
            maxlen=64)
        self._webhook_wake = threading.Event()
        self._webhook_thread: Optional[threading.Thread] = None
        self._closed = False
        # job_id -> last sweep's worker-scrape coverage accounting
        # (advertised/fetched/failed/silent) — the /status and test
        # surface behind the coverage gauge.
        self.scrape_coverage: Dict[str, Dict[str, int]] = {}
        self._m_budget = self._m_burn = self._m_alerts = None
        self._m_scrape = None
        if _metrics.metrics_enabled():
            reg = _metrics.registry()
            self._m_scrape = reg.gauge(
                "rafiki_tpu_slo_worker_scrape_ratio",
                "Fraction of a job's metrics-advertising workers whose "
                "exposition the SLO sweep actually merged (1 = full "
                "bin-scope visibility; < 1 = objectives are judging "
                "partial data, NOT proof of health)")
            self._m_budget = reg.gauge(
                "rafiki_tpu_slo_budget_remaining_ratio",
                "Error budget left in each objective's rolling window "
                "(1 = untouched, 0 = exhausted), per objective "
                "instance")
            self._m_burn = reg.gauge(
                "rafiki_tpu_slo_burn_rate",
                "Error-budget burn rate per objective instance and "
                "window (fast|slow); 1 = burning the budget exactly "
                "at the window's pace")
            self._m_alerts = reg.counter(
                "rafiki_tpu_slo_alerts_total",
                "Alert state transitions per objective (state="
                "pending|firing|resolved|cleared)")

    @classmethod
    def from_env(cls, services, meta) -> "SloEngine":
        """Build from the env knobs ``NodeConfig.apply_env`` exported
        (the platform composition path; tests construct directly)."""
        objectives = _slo.rules_from_env()
        try:
            log_mb = float(os.environ.get(
                "RAFIKI_TPU_SLO_ALERT_LOG_MB", "16") or 16)
        except ValueError:
            log_mb = 16.0
        return cls(services, meta, objectives,
                   webhook_url=os.environ.get(
                       "RAFIKI_TPU_SLO_WEBHOOK_URL", "").strip(),
                   alert_log_mb=log_mb)

    def close(self) -> None:
        """Drop every SLO series (objective/job/bin/tenant labels churn
        with deployments; a stopped engine must not leak them into
        every future scrape) and stop the webhook sender."""
        # rta: disable=RTA106 monotonic one-way bool (False -> True once) read by the sender loop — the documented benign flag case
        self._closed = True
        self._webhook_wake.set()
        t = self._webhook_thread
        if t is not None and t.is_alive():
            t.join(timeout=5)
        for m in (self._m_budget, self._m_burn, self._m_alerts,
                  self._m_scrape):
            if m is not None:
                m.remove()

    # --- The sweep ----------------------------------------------------

    def sweep(self, scrapes=None) -> List[Dict[str, Any]]:
        """One evaluation pass; returns the alert transitions recorded.
        Runs on the supervise thread — everything here is best-effort
        and must not raise into the sweep. ``scrapes`` is the
        sweep-shared :class:`~rafiki_tpu.admin.scrape.ScrapeCache`
        (the autoscaler consumes the same endpoints right after this
        on the same pass); None fetches directly."""
        self.epoch += 1
        now = time.monotonic()
        transitions: List[Dict[str, Any]] = []
        jobs = self.meta.get_inference_jobs(status="RUNNING")
        live_ids = {j["id"] for j in jobs}
        for job in jobs:
            text = self._job_exposition(job, scrapes=scrapes)
            if text is None:
                continue
            metrics = _metrics.parse_exposition(text)
            for obj in self.objectives:
                if obj.job and not job["id"].startswith(obj.job):
                    continue
                transitions.extend(
                    self._evaluate_objective(job["id"], obj, metrics,
                                             now))
        self._prune(now, live_ids)
        return transitions

    def _job_exposition(self, job: Dict[str, Any],
                        scrapes=None) -> Optional[str]:
        """The job's predictor ``/metrics`` text (+ a one-time
        ``/stats`` label resolve), concatenated with every worker-
        advertised metrics exposition. None = skip this job this sweep.

        The worker scrape closes the r19 bin-scope visibility caveat:
        under subprocess/docker runners the worker-owned families
        (``rafiki_tpu_serving_bin_device_seconds``) live in each worker
        process's registry, not the frontend's — workers that bound a
        metrics server advertise its address in their bus registration
        (``metrics`` key), and the concatenation is safe because
        frontend- and worker-owned families never share a name+label
        set. Worker fetch failures degrade to frontend-only (a dead
        worker must not blind the whole job's objectives)."""
        host = job.get("predictor_host")
        if not host:
            return None
        fetch = scrapes.fetch if scrapes is not None else self._scrape
        try:
            if job["id"] not in self._labels:
                stats = fetch(host, "/stats")
                self._labels[job["id"]] = (
                    stats.get("service") or "",
                    stats.get("http_service") or "")
            text = fetch(host, "/metrics")
        except (OSError, ValueError):
            self._labels.pop(job["id"], None)  # re-resolve on restart
            return None
        from .scrape import merge_worker_expositions, \
            worker_scrape_targets

        by_node, silent = worker_scrape_targets(self.services,
                                                job["id"])
        worker_text, fetched, failed = merge_worker_expositions(
            fetch, by_node)
        if worker_text:
            text += "\n" + worker_text
        advertised = fetched + failed
        self.scrape_coverage[job["id"]] = {
            "advertised": advertised, "fetched": fetched,
            "failed": failed, "silent": silent}
        if self._m_scrape is not None:
            # 1.0 when nothing advertises: resident-runner workers'
            # series already live in this process's registry, so the
            # frontend scrape IS full coverage.
            self._m_scrape.set(
                fetched / advertised if advertised else 1.0,
                job=job["id"])
        if failed:
            _log.warning(
                "slo sweep: job %s worker scrape incomplete (%d/%d "
                "advertised endpoints merged) — bin-scoped objectives "
                "are judging partial data", job["id"][:8], fetched,
                advertised)
        return text

    def _scrape(self, host: str, path: str) -> Any:
        from .scrape import fetch_endpoint

        return fetch_endpoint(host, path)

    # --- Objective evaluation -----------------------------------------

    def _evaluate_objective(self, job_id: str, obj: _slo.Objective,
                            metrics: Dict[str, Any], now: float,
                            ) -> List[Dict[str, Any]]:
        """Fold one job's scrape into every instance this objective
        spawns there (one for job scope; one per observed bin/tenant
        label otherwise) and advance their alert machines."""
        service, http_service = self._labels.get(job_id, ("", ""))
        snapshots = self._instance_snapshots(job_id, obj, metrics,
                                             service, http_service)
        out: List[Dict[str, Any]] = []
        for labels, snapshot in snapshots:
            key = (job_id, obj.name, tuple(sorted(labels.items())))
            with self._lock:
                inst = self._instances.get(key)
                if inst is None:
                    inst = _slo.Instance.create(obj, labels)
                    self._instances[key] = inst
            good, total = self._deltas(obj, inst, snapshot)
            inst.prev = snapshot
            if good is None:
                inst.last_seen = now  # basis sweep: seen, not judged
                continue
            transition = inst.evaluate(now, good, total)
            self._publish(inst)
            if transition is not None:
                out.append(self._record(job_id, inst, transition))
        return out

    def _instance_snapshots(self, job_id: str, obj: _slo.Objective,
                            metrics: Dict[str, Any], service: str,
                            http_service: str,
                            ) -> List[Tuple[Dict[str, str], Any]]:
        """``[(instance labels, cumulative snapshot), ...]`` for one
        objective against one scrape. Latency snapshots are per-le
        cumulative bucket counts; ratio snapshots are (good, bad)
        counter totals."""
        jid = job_id[:8]
        if obj.otype == "ratio":
            good = self._counter_total(
                metrics, _slo.CONSUMED_SERIES[("ratio", "good")],
                service=service)
            bad = self._counter_total(
                metrics, _slo.CONSUMED_SERIES[("ratio", "bad")],
                service=service)
            return [({"job": jid}, (good, bad))]
        name = obj.source_metric() + "_bucket"
        samples = metrics.get(name, [])
        if obj.scope == "job":
            match = {"service": http_service, "route": obj.route}
            return [({"job": jid},
                     self._bucket_cum(samples, match))]
        group_label = "bin" if obj.scope == "bin" else "tenant"
        groups: Dict[str, Dict[float, int]] = {}
        for labels, value in samples:
            if obj.scope == "bin" and \
                    labels.get("job") != job_id[:12]:
                continue
            if obj.scope == "tenant" and \
                    labels.get("service") != service:
                # The tenant histogram carries the frontend's service
                # label precisely so that co-resident frontends of
                # OTHER jobs (one shared process registry) don't fold
                # their tenants into this job's instances — a breach
                # caused by job A must not fire (and scale) job B.
                continue
            gval = labels.get(group_label)
            if gval is None:
                continue
            le = labels.get("le")
            if le is None:
                continue
            bound = float("inf") if le == "+Inf" else float(le)
            cum = groups.setdefault(gval, {})
            cum[bound] = cum.get(bound, 0) + int(value)
        return [({"job": jid, group_label: gval}, cum)
                for gval, cum in sorted(groups.items())]

    @staticmethod
    def _counter_total(metrics: Dict[str, Any], name: str,
                       **match: str) -> float:
        return sum(v for labels, v in metrics.get(name, [])
                   if all(labels.get(k) == str(mv)
                          for k, mv in match.items()))

    @staticmethod
    def _bucket_cum(samples: List[Tuple[Dict[str, str], float]],
                    match: Dict[str, str]) -> Dict[float, int]:
        cum: Dict[float, int] = {}
        for labels, value in samples:
            if any(labels.get(k) != str(v) for k, v in match.items()):
                continue
            le = labels.get("le")
            if le is None:
                continue
            bound = float("inf") if le == "+Inf" else float(le)
            cum[bound] = cum.get(bound, 0) + int(value)
        return cum

    def _deltas(self, obj: _slo.Objective, inst: _slo.Instance,
                snapshot: Any) -> Tuple[Optional[float], float]:
        """One sweep's (good, total) event deltas from the cumulative
        snapshots. ``(None, 0)`` on the basis sweep — a judge must
        never act on totals it cannot attribute to a time window. A
        counter RESET (restarted frontend/worker: any cumulative value
        moved backward) re-bases instead of folding a huge negative."""
        prev = inst.prev
        if prev is None:
            return None, 0.0
        if obj.otype == "ratio":
            good_d = snapshot[0] - prev[0]
            bad_d = snapshot[1] - prev[1]
            if good_d < 0 or bad_d < 0:
                return None, 0.0
            return good_d, good_d + bad_d
        deltas = []
        for bound in sorted(snapshot):
            d = snapshot[bound] - prev.get(bound, 0)
            if d < 0:
                return None, 0.0
            deltas.append((bound, d))
        return _slo.good_total_from_deltas(deltas,
                                           obj.threshold_ms / 1e3)

    # --- Publication ---------------------------------------------------

    def _publish(self, inst: _slo.Instance) -> None:
        if self._m_budget is None:
            return
        labels = {"objective": inst.objective.name, **inst.labels}
        self._m_budget.set(round(inst.budget_remaining, 6), **labels)
        self._m_burn.set(round(inst.burn_fast, 6), window="fast",
                         **labels)
        self._m_burn.set(round(inst.burn_slow, 6), window="slow",
                         **labels)

    def _drop_gauges(self, inst: _slo.Instance) -> None:
        if self._m_budget is None:
            return
        labels = {"objective": inst.objective.name, **inst.labels}
        self._m_budget.remove(**labels)
        self._m_burn.remove(**labels)

    def _record(self, job_id: str, inst: _slo.Instance,
                transition: str) -> Dict[str, Any]:
        wall, t0 = time.time(), time.monotonic()
        entry: Dict[str, Any] = {
            "epoch": self.epoch, "t": round(wall, 3),
            "objective": inst.objective.name,
            "labels": dict(inst.labels),
            "transition": transition,
            "state": inst.machine.state,
            "burn_fast": round(inst.burn_fast, 4),
            "burn_slow": round(inst.burn_slow, 4),
            "budget_remaining": round(inst.budget_remaining, 4),
            "job_id": job_id[:8],
        }
        with self._lock:
            self._ring.append(entry)
        if self._m_alerts is not None:
            # transition is the fixed TRANSITIONS vocabulary; the whole
            # family is dropped by close()'s bare remove().
            self._m_alerts.inc(objective=inst.objective.name,
                               state=transition)
        ctx = _trace.TraceContext(_trace.new_trace_id())
        _trace.record_event(
            f"slo.{transition}", "slo", [ctx], wall,
            time.monotonic() - t0,
            attrs={k: entry[k] for k in
                   ("objective", "labels", "burn_fast", "burn_slow",
                    "budget_remaining", "job_id")})
        entry["trace_id"] = ctx.trace_id
        self._sink(entry)
        return entry

    def _sink(self, entry: Dict[str, Any]) -> None:
        """Best-effort external fan-out: the JSONL alert log (bounded:
        rolls once to ``.1`` at the size cap) and, when configured, one
        short-timeout webhook POST. Neither may fail the sweep."""
        log_dir = getattr(self.services, "log_dir", "")
        if log_dir:
            path = os.path.join(log_dir, ALERT_LOG_FILE)
            try:
                os.makedirs(log_dir, exist_ok=True)
                with open(path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(entry, separators=(",", ":"))
                            + "\n")
                    if f.tell() > self.alert_log_mb * 1024 * 1024:
                        roll = True
                    else:
                        roll = False
                if roll:
                    os.replace(path, path + ".1")
            except OSError:
                _log.warning("alert log write failed", exc_info=True)
        if self.webhook_url and not self._closed:
            # rta: disable=RTA106 deque.append/popleft are GIL-atomic (single producer, single consumer; bounded maxlen drops oldest) — the documented benign case
            self._webhook_q.append(dict(entry))
            self._webhook_wake.set()
            if self._webhook_thread is None or \
                    not self._webhook_thread.is_alive():
                self._webhook_thread = threading.Thread(
                    target=self._webhook_loop, name="slo-webhook",
                    daemon=True)
                self._webhook_thread.start()

    def _webhook_loop(self) -> None:
        """Drain queued alert transitions to the webhook, one POST at
        a time off the supervise thread (2 s timeout each; failures
        logged, never retried — the JSONL sink is the durable
        record)."""
        from urllib.request import Request, urlopen

        while not self._closed:
            try:
                entry = self._webhook_q.popleft()
            except IndexError:
                self._webhook_wake.wait(timeout=1.0)
                self._webhook_wake.clear()
                continue
            try:
                req = Request(
                    self.webhook_url,
                    data=json.dumps(entry).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST")
                with urlopen(req, timeout=2) as resp:
                    resp.read()
            except OSError:
                _log.warning("alert webhook %s failed",
                             self.webhook_url, exc_info=True)

    def _prune(self, now: float, live_job_ids) -> None:
        """Drop instances whose job departed or whose source labels
        went silent (promotion churn, tenant LRU eviction) — and their
        gauges with them, so churn can never grow the scrape."""
        dropped: List[_slo.Instance] = []
        with self._lock:
            for key in list(self._instances):
                job_id, _name, _labels = key
                inst = self._instances[key]
                stale = now - inst.last_seen > \
                    _PRUNE_AFTER_WINDOWS * max(inst.objective.slow_s,
                                               inst.objective.window_s)
                if job_id not in live_job_ids or stale:
                    dropped.append(inst)
                    del self._instances[key]
        for inst in dropped:
            self._drop_gauges(inst)
        for job_id in [j for j in self._labels
                       if j not in live_job_ids]:
            del self._labels[job_id]
        for job_id in [j for j in self.scrape_coverage
                       if j not in live_job_ids]:
            del self.scrape_coverage[job_id]
            if self._m_scrape is not None:
                self._m_scrape.remove(job=job_id)

    # --- Consumers -----------------------------------------------------

    def slo_pressure(self, job_id: str) -> Optional[str]:
        """The autoscaler's pressure signal: the violating BIN label of
        a firing bin-scoped latency objective for this job, ``""`` for
        a firing job/tenant-scoped one, None when nothing fires.
        Deterministic: bin-scoped alerts win (they name a target), then
        objective-name order."""
        with self._lock:
            items = sorted(self._instances.items())
        best: Optional[str] = None
        for (jid, _name, _labels), inst in items:
            if jid != job_id or inst.machine.state != "firing" or \
                    inst.objective.otype != "latency":
                continue
            bin_label = inst.labels.get("bin")
            if bin_label:
                return bin_label
            if best is None:
                best = ""
        return best

    def snapshot(self) -> Dict[str, Any]:
        """The ``GET /slo`` body: every objective with its live
        instances (burn rates, budget bars, alert states)."""
        with self._lock:
            items = sorted(self._instances.items())
        instances: Dict[str, List[Dict[str, Any]]] = {}
        for (_job_id, name, _labels), inst in items:
            instances.setdefault(name, []).append({
                "labels": dict(inst.labels),
                "state": inst.machine.state,
                "burn_fast": round(inst.burn_fast, 4),
                "burn_slow": round(inst.burn_slow, 4),
                "budget_remaining": round(inst.budget_remaining, 4),
                "good": round(inst.good, 1),
                "total": round(inst.total, 1),
            })
        objectives = []
        for obj in self.objectives:
            spec = {"name": obj.name, "type": obj.otype,
                    "target": obj.target, "scope": obj.scope,
                    "window_s": obj.window_s, "fast_s": obj.fast_s,
                    "slow_s": obj.slow_s, "burn": obj.burn,
                    "for_s": obj.for_s, "resolve_s": obj.resolve_s}
            if obj.otype == "latency":
                spec["threshold_ms"] = obj.threshold_ms
            objectives.append({**spec,
                               "instances": instances.get(obj.name,
                                                          [])})
        return {"enabled": True, "epoch": self.epoch,
                "objectives": objectives}

    def alerts_snapshot(self) -> Dict[str, Any]:
        """The ``GET /alerts`` body (transition ring, newest first)."""
        with self._lock:
            ring = list(self._ring)
            firing = sorted({inst.objective.name
                             for inst in self._instances.values()
                             if inst.machine.state == "firing"})
        return {"enabled": True, "epoch": self.epoch,
                "firing": firing, "alerts": ring[::-1]}
