"""Capacity engine: score autoscale policies against workloads, offline.

The top of the trace-replay stack (docs/capacity.md). The recorder
(observe/workload.py) wrote down what arrived; the simulator
(observe/replay.py) can replay it under the real policy + SLO code;
this module packages that into operator-facing verdicts:

- :func:`score` — one simulation run → one report with an ``ok``
  verdict (no SLO objective fired). ``python -m rafiki_tpu.capacity
  score --trace <f> --policy <json>`` is this function as a CLI, and
  ``GET /capacity`` on the admin serves a bounded summary of it.
- **canned traces** (:func:`canned_trace`) — deterministic ``zipf`` /
  ``ramp`` / ``chaos`` workloads, so a policy change can be judged in
  CI with no recorded trace at hand: the tier-1 policy regression gate
  simulates the default policy (must stay green) and a deliberately
  degraded one (must go red) against the same canned ramp.
- **periodicity** (:func:`learn_periodicity` / :func:`load_periodicity`
  / :func:`expected_qps`) — a phase-binned qps table learned from a
  recorded trace (``capacity learn``), consumed by the autoscaler's
  predictive plane (``RAFIKI_TPU_AUTOSCALE_PERIODICITY`` +
  ``RAFIKI_TPU_AUTOSCALE_PREDICT_HORIZON_S``) to emit
  ``scale_up:predicted`` ahead of a recurring ramp.

Everything here is deterministic in its inputs (seeded simulation, no
wall clock in any verdict path) — a capacity report is reviewable
evidence, not a flaky benchmark.
"""

from __future__ import annotations

import json
import math
import random
from dataclasses import asdict
from typing import Any, Dict, List, Optional, Sequence

from ..observe import metrics as _metrics
from ..observe import slo as _slo
from .autoscaler import PolicyKnobs

#: Default gate objectives for canned-trace scoring: a coarse latency
#: ceiling plus admission availability, windowed for the canned traces'
#: 1 s simulated sweep cadence. Deliberately loose — the gate flags
#: policies that CANNOT keep up, not ones that are merely imperfect.
GATE_RULES = ("sim-p95:p95<1000ms,window=60,fast=10,slow=30,burn=2,"
              "for=2,resolve=10;"
              "sim-avail:ratio>=0.99,window=60,fast=10,slow=30,burn=2,"
              "for=2,resolve=10")

#: Canned trace vocabulary (see :func:`canned_trace`).
CANNED_TRACES = ("zipf", "ramp", "chaos")


# --- Canned workloads --------------------------------------------------

def _arrivals(rng: random.Random,
              segments: Sequence[tuple]) -> List[float]:
    """Exponential-gap arrival times for piecewise-linear rate segments
    ``(t0, t1, rate0, rate1)`` (requests/s at each edge)."""
    out: List[float] = []
    for t0, t1, r0, r1 in segments:
        t = float(t0)
        while t < t1:
            frac = (t - t0) / max(t1 - t0, 1e-9)
            rate = r0 + (r1 - r0) * frac
            if rate <= 0:
                t += 1.0
                continue
            t += rng.expovariate(rate)
            if t < t1:
                out.append(t)
    return out


def _zipf_tenant(rng: random.Random, n: int = 8) -> str:
    weights = [1.0 / k for k in range(1, n + 1)]
    total = sum(weights)
    u = rng.random() * total
    acc = 0.0
    for i, w in enumerate(weights):
        acc += w
        if u <= acc:
            return f"tenant{i}"
    return f"tenant{n - 1}"


def canned_trace(name: str, seed: int = 0) -> List[Dict[str, Any]]:
    """A deterministic synthetic workload in the recorder's schema.

    ``zipf``: 120 s of steady 8 req/s with a zipf-skewed tenant mix —
    the attribution-shaped baseline. ``ramp``: 60 s quiet (2 req/s)
    then a 60 s linear climb to 20 req/s — the scale-up stressor the
    regression gate judges policies on. ``chaos``: bursts an order of
    magnitude over base with a dead-quiet gap — the flap stressor.
    """
    rng = random.Random(seed)
    if name == "zipf":
        segments = [(0.0, 120.0, 8.0, 8.0)]
    elif name == "ramp":
        segments = [(0.0, 60.0, 2.0, 2.0), (60.0, 120.0, 2.0, 20.0)]
    elif name == "chaos":
        segments = [(0.0, 30.0, 2.0, 2.0), (30.0, 40.0, 25.0, 25.0),
                    (40.0, 55.0, 0.0, 0.0), (55.0, 70.0, 2.0, 2.0),
                    (70.0, 85.0, 30.0, 30.0), (85.0, 120.0, 2.0, 2.0)]
    else:
        raise ValueError(f"unknown canned trace {name!r} "
                         f"(valid: {', '.join(CANNED_TRACES)})")
    out = []
    for t in _arrivals(rng, segments):
        n = rng.choice((1, 1, 1, 2, 4))
        out.append({"off_s": round(t, 4), "t": round(t, 3),
                    "job": f"sim-{name}"[:12],
                    "tenant": _zipf_tenant(rng), "n": n,
                    "size": 1 << max(0, (n - 1).bit_length()),
                    "status": 200})
    return out


def resolve_trace(source: str) -> List[Dict[str, Any]]:
    """A canned trace name, or a recorded ``workload.jsonl`` file/log
    dir (observe/workload.py's reader)."""
    if source in CANNED_TRACES:
        return canned_trace(source)
    from ..observe import workload as _workload

    trace = _workload.load(source)
    if not trace:
        raise ValueError(f"trace {source!r} holds no workload records")
    return trace


# --- Periodicity -------------------------------------------------------

def learn_periodicity(trace: Sequence[Dict[str, Any]], period_s: float,
                      bin_s: float = 60.0) -> Dict[str, Any]:
    """Phase-binned request-rate table: fold every arrival onto its
    phase within ``period_s`` and average over the cycles the trace
    spans. The table deliberately stores qps (requests/s, matching the
    signal the policy compares against), not query counts."""
    if period_s <= 0 or bin_s <= 0 or bin_s > period_s:
        raise ValueError("periodicity needs 0 < bin_s <= period_s")
    n_bins = max(1, int(math.ceil(period_s / bin_s)))
    counts = [0] * n_bins
    span = 0.0
    for rec in trace:
        off = max(0.0, float(rec.get("off_s") or 0.0))
        span = max(span, off)
        counts[min(n_bins - 1, int((off % period_s) // bin_s))] += 1
    cycles = max(1, int(math.ceil(span / period_s)))
    return {"period_s": float(period_s), "bin_s": float(bin_s),
            "qps": [round(c / (bin_s * cycles), 4) for c in counts]}


def load_periodicity(path: str) -> Dict[str, Any]:
    """Read + validate a learned table. LOUD on any malformation —
    ``NodeConfig.validate`` calls this at startup so a typo'd table
    fails the node, not silently predicts nothing."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except OSError as e:
        raise ValueError(f"periodicity table {path!r}: {e}") from None
    except json.JSONDecodeError as e:
        raise ValueError(f"periodicity table {path!r}: {e}") from None
    if not isinstance(data, dict):
        raise ValueError(f"periodicity table {path!r}: not an object")
    try:
        period_s = float(data["period_s"])
        bin_s = float(data["bin_s"])
        qps = [float(v) for v in data["qps"]]
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"periodicity table {path!r}: needs numeric "
                         f"period_s, bin_s and a qps array ({e})") \
            from None
    if period_s <= 0 or bin_s <= 0 or bin_s > period_s:
        raise ValueError(f"periodicity table {path!r}: needs "
                         f"0 < bin_s <= period_s")
    want = max(1, int(math.ceil(period_s / bin_s)))
    if len(qps) != want:
        raise ValueError(f"periodicity table {path!r}: qps has "
                         f"{len(qps)} bins, period/bin implies {want}")
    if any(v < 0 for v in qps):
        raise ValueError(f"periodicity table {path!r}: negative qps")
    return {"period_s": period_s, "bin_s": bin_s, "qps": qps}


def expected_qps(table: Dict[str, Any], t: float,
                 horizon_s: float) -> float:
    """The table's request rate at phase ``t + horizon_s``."""
    phase = (t + horizon_s) % table["period_s"]
    qps = table["qps"]
    return float(qps[min(len(qps) - 1, int(phase // table["bin_s"]))])


# --- Scoring -----------------------------------------------------------

def make_policy(overrides: Optional[Dict[str, Any]]) -> PolicyKnobs:
    """PolicyKnobs from a candidate-policy mapping (the CLI's
    ``--policy`` JSON). Unknown keys are rejected loudly — a typo'd
    knob must not silently score the default policy."""
    overrides = overrides or {}
    valid = set(asdict(PolicyKnobs()))
    unknown = set(overrides) - valid
    if unknown:
        raise ValueError(f"unknown policy knob(s) {sorted(unknown)} "
                         f"(valid: {sorted(valid)})")
    return PolicyKnobs(**overrides)


def score(trace: Sequence[Dict[str, Any]],
          policy: Optional[PolicyKnobs] = None,
          objectives: Optional[Sequence[_slo.Objective]] = None,
          fleet=None, sim=None,
          periodicity: Optional[Dict[str, Any]] = None,
          ) -> Dict[str, Any]:
    """Simulate ``trace`` under ``policy`` and judge it against
    ``objectives`` (default: :data:`GATE_RULES`). The report's ``ok``
    is the regression-gate verdict: False iff any objective fired.

    When no ``fleet`` is given, a recorded trace's own ``compute_ms``
    column fits the service-time model (canned traces carry none, so
    they keep the synthetic fleet) — scoring a store against a
    fabricated fleet would judge the policy on latencies the edge
    never saw."""
    from ..observe import replay as _replay

    policy = policy or PolicyKnobs()
    if objectives is None:
        objectives = _slo.parse_rules(GATE_RULES)
    if fleet is None:
        fleet = _replay.FleetModel.from_trace(trace)
    report = _replay.simulate(trace, fleet=fleet, sim=sim,
                              policy=policy, objectives=objectives,
                              periodicity=periodicity)
    report["policy"] = asdict(policy)
    report["objectives"] = [o.name for o in objectives]
    return report


def policy_gate(policy: Optional[PolicyKnobs] = None,
                trace_name: str = "ramp", seed: int = 0,
                ) -> Dict[str, Any]:
    """The CI-facing gate: the canned ``trace_name`` trace against
    ``policy`` under :data:`GATE_RULES`. Deterministic in (policy,
    trace_name, seed)."""
    from ..observe import replay as _replay

    return score(canned_trace(trace_name, seed=seed), policy=policy,
                 sim=_replay.SimKnobs(seed=seed))


# --- Admin surface -----------------------------------------------------

def _workload_summary(log_dir: str) -> Dict[str, Any]:
    """Bounded recorded-trace summary for ``GET /capacity``: segment
    and line counts from a cheap scan, never a full parse (the active
    store can hold tens of MB)."""
    from ..observe import workload as _workload

    paths = _workload.segment_paths(log_dir)
    if not paths:
        return {"recorded": False}
    lines = 0
    for p in paths:
        try:
            with open(p, "rb") as f:
                lines += sum(1 for _ in f)
        except OSError:
            continue
    return {"recorded": True, "segments": len(paths),
            "records": lines}


#: Gate runs memoized by policy knobs: the gate is DETERMINISTIC in
#: (policy, trace, seed), so a dashboard polling GET /capacity every
#: few seconds pays one simulation per distinct policy, not per poll.
_gate_memo: Dict[tuple, Dict[str, Any]] = {}


def admin_snapshot(services) -> Dict[str, Any]:
    """The ``GET /capacity`` body: the recorded-workload inventory for
    this node plus a canned-ramp gate run of the policy the node would
    actually apply (the live autoscaler's knobs when the loop is on,
    the defaults otherwise)."""
    scaler = getattr(services, "autoscaler", None)
    policy = scaler.policy.knobs if scaler is not None else None
    key = tuple(sorted(asdict(policy or PolicyKnobs()).items()))
    report = _gate_memo.get(key)
    if report is None:
        report = _gate_memo[key] = policy_gate(policy=policy)
    if _metrics.metrics_enabled() and report["latency_ms"]["p99"] \
            is not None:
        # The dashboard's simulated-vs-live comparison series: the
        # canned-ramp gate's p99 under the node's live policy.
        _metrics.registry().gauge(
            "rafiki_tpu_capacity_sim_p99_seconds",
            "Simulated p99 of the canned-ramp policy gate under the "
            "node's active autoscale policy").set(
            report["latency_ms"]["p99"] / 1e3, trace="ramp")
    return {
        "enabled": True,
        "policy_source": "autoscaler" if scaler is not None
        else "defaults",
        "workload": _workload_summary(
            getattr(services, "log_dir", "") or ""),
        "gate": {
            "trace": "ramp",
            "ok": report["ok"],
            "violations": report["violations"],
            "latency_ms": report["latency_ms"],
            "rejected": report["rejected"],
            "served": report["served"],
            "actions": report["actions"],
            "max_replicas": report["max_replicas"],
            # The ring is bounded for the same reason GET /autoscale's
            # is: a UI surface, not a log.
            "decisions": report["decisions"][-20:],
        },
    }
