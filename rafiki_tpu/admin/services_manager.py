"""ServicesManager: service sizing + TPU chip-range scheduling.

Parity: SURVEY.md §2 "ServicesManager / GPU scheduler" + §3.1/§3.2 — the
upstream manager decides how many worker services each job gets and which
GPUs each sees (``CUDA_VISIBLE_DEVICES``). Here the resource is **chip
ranges**: ``ChipAllocator`` carves ``jax.devices()`` into contiguous
groups, each service env carries ``RAFIKI_TPU_CHIPS``, and workers build
their Mesh from exactly that range (BASELINE north star: "Admin's GPU
scheduler retargeted to allocate TPU chip ranges").

Budget semantics (upstream keys, TPU vocabulary):
- ``MODEL_TRIAL_COUNT``: total trials per model (enforced by TrialRunner).
- ``CHIP_COUNT``: chips to dedicate per model's search. Workers =
  ``ceil(CHIP_COUNT / CHIPS_PER_TRIAL)``; 0 → one worker on one chip.
- ``CHIPS_PER_TRIAL``: chip-group size per worker (intra-trial dp/tp
  parallelism; default 1).
- ``GPU_COUNT`` is accepted as an alias of ``CHIP_COUNT`` so reference
  client scripts run unchanged.

Bookkeeping: every service a job owns — train workers AND the advisor,
inference workers AND the predictor — is recorded in the job's worker
mapping table, so stop/supervise walk one list instead of guessing.
"""

from __future__ import annotations

import logging
import math
import os
from typing import Any, Dict, List, Optional

from .. import faults
from ..constants import (BudgetOption, EnvVars, InferenceJobStatus,
                         ServiceStatus, ServiceType)
from ..container.manager import ContainerManager
from ..observe import metrics as _metrics
from ..parallel.chips import ChipAllocator
from ..store import MetaStore

_log = logging.getLogger(__name__)

CHIPS_PER_TRIAL = "CHIPS_PER_TRIAL"

# trial_id recorded for an inference job's predictor service row
PREDICTOR_TRIAL = "__predictor__"

_ACTIVE = (ServiceStatus.STARTED, ServiceStatus.DEPLOYING,
           ServiceStatus.RUNNING)


def normalize_budget(budget: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    b = dict(budget or {})
    if BudgetOption.GPU_COUNT in b and BudgetOption.CHIP_COUNT not in b:
        b[BudgetOption.CHIP_COUNT] = b.pop(BudgetOption.GPU_COUNT)
    b.pop(BudgetOption.GPU_COUNT, None)
    return b


class ServicesManager:
    def __init__(self, meta: MetaStore, container: ContainerManager,
                 allocator: Optional[ChipAllocator] = None,
                 meta_uri: str = ":memory:", params_dir: str = "",
                 bus_uri: str = "", node_id: str = "",
                 adopt_unowned: bool = True, log_dir: str = ""):
        self.meta = meta
        self.container = container
        self.allocator = allocator or ChipAllocator()
        # URIs injected into service envs (subprocess mode needs them;
        # thread mode ignores them and uses the shared context).
        self.meta_uri = meta_uri
        self.params_dir = params_dir
        self.bus_uri = bus_uri
        # Per-service log files land here (dashboard log view); empty
        # disables capture.
        self.log_dir = log_dir
        # Node identity: services are stamped with their launching node
        # so, with several nodes sharing one meta store (multi-host
        # scale-out), each node supervises/restarts only what IT runs —
        # another node's healthy worker must not look "dead" here.
        if not node_id:
            import socket

            node_id = f"{socket.gethostname()}:{os.getpid()}"
        self.node_id = node_id
        # Only the workdir-owning (primary) node adopts pre-upgrade
        # rows whose node_id is NULL; a join node stopping/sweeping the
        # primary's legacy services would disrupt its running jobs.
        self.adopt_unowned = adopt_unowned
        # Lazy bus connection for reaping dead workers' stale
        # registrations (subprocess/docker modes; thread mode borrows
        # the container's shared bus instead).
        self._reap_bus = None
        # Foreign-node lease window (NodeConfig.node_lease; env is the
        # transport so spawned children agree). Resolved HERE, per
        # instance — the old class-attribute read executed at first
        # import, before NodeConfig.apply_env could export the node's
        # validated value (the RTA601 import-read class).
        self.NODE_LEASE = float(os.environ.get(
            "RAFIKI_TPU_NODE_LEASE", 120.0))
        # Dead inference replicas whose respawn failed for CAPACITY
        # (add_inference_worker -> None while the job was live): the
        # service row is already ERRORED, so the RUNNING scan will
        # never see them again — each sweep retries these until a
        # replica lands or the job stops.
        self._pending_respawns: List[Dict[str, Any]] = []
        # Metrics-driven autoscaler (admin/autoscaler.py), attached by
        # the platform ONLY when RAFIKI_TPU_AUTOSCALE is on. None (the
        # default) keeps supervise byte-identical: one attribute check,
        # zero new series.
        self.autoscaler = None
        # SLO engine (admin/slo_engine.py), attached by the platform
        # ONLY when RAFIKI_TPU_SLO_RULES names objectives — same
        # disabled-means-free contract as the autoscaler.
        self.slo_engine = None
        # Cluster node registry (admin/nodes.py), attached by the
        # platform ONLY when RAFIKI_TPU_CLUSTER_FABRIC is on. None =
        # single-node: heartbeat() pays one attribute check, no
        # rafiki_tpu_node_* series, no registry bus traffic.
        self.node_registry = None
        # Chaos plane (faults.py): node.kill site — whole-node death.
        # None when the fault plane is disarmed.
        self._node_faults = faults.site_hook("node")

    # --- Launch plumbing ---

    def _launch(self, service_type: str, extra_env: Dict[str, str],
                chips: Optional[List[int]] = None) -> Dict[str, Any]:
        svc = self.meta.create_service(service_type,
                                       ServiceStatus.DEPLOYING, chips=chips,
                                       node_id=self.node_id)
        env = self._base_env(svc["id"], service_type)
        if chips is not None:
            env[EnvVars.CHIPS] = ",".join(str(c) for c in chips)
        env.update(extra_env)
        try:
            container_id = self.container.create_service(svc["id"], env)
        except Exception:
            self.meta.update_service(svc["id"], status=ServiceStatus.ERRORED)
            raise
        self.meta.update_service(svc["id"], container_id=container_id)
        return self.meta.get_service(svc["id"])

    def _base_env(self, service_id: str, service_type: str,
                  ) -> Dict[str, str]:
        env = {
            EnvVars.META_URI: self.meta_uri,
            EnvVars.PARAMS_DIR: self.params_dir,
            EnvVars.BUS_URI: self.bus_uri,
            EnvVars.SERVICE_ID: service_id,
            EnvVars.SERVICE_TYPE: service_type,
        }
        if self.log_dir:
            env[EnvVars.LOG_DIR] = self.log_dir
        # Operator tunables that must reach docker children (which do
        # NOT inherit this process's environ) ride the service env.
        if "RAFIKI_TPU_ADVISOR_PREFETCH" in os.environ:
            env["RAFIKI_TPU_ADVISOR_PREFETCH"] = \
                os.environ["RAFIKI_TPU_ADVISOR_PREFETCH"]
        # Cluster fabric (docs/cluster.md): the PLACING node stamps its
        # identity into every child it launches — workers echo it in
        # their bus registration (locality-aware shard planning),
        # frontends use it to route remote scatters through the relay.
        # Identity, not a tunable: children must never invent their
        # own, so it rides the service env like SERVICE_ID.
        from ..config import _parse_bool as _pb

        if _pb(os.environ.get("RAFIKI_TPU_CLUSTER_FABRIC", "0")):
            env[EnvVars.NODE_ID] = self.node_id
        return env

    def _stop_service(self, service_id: str) -> None:
        svc = self.meta.get_service(service_id)
        if svc is None:
            return
        self.container.destroy_service(svc["container_id"] or service_id)
        if svc["status"] in _ACTIVE:
            self.meta.update_service(service_id, status=ServiceStatus.STOPPED)
        self._release_chips_of(svc)

    def _alloc_name(self, service_id: str) -> str:
        return f"svc:{service_id}"

    def _release_chips_of(self, svc: Dict[str, Any]) -> None:
        self.allocator.release(self._alloc_name(svc["id"]))

    def _sharing_ok(self) -> bool:
        """Whether time-sliced chip co-ownership is safe here: only in
        resident-runner (thread) mode, where every worker shares one
        process and one jax backend. Sharing is a LIVENESS fallback —
        used for a job's FIRST worker when exclusive placement fails,
        so a full single-chip box still admits a second tenant
        (BASELINE config[5]) — never for extra capacity.
        RAFIKI_TPU_CHIP_SHARE=0 turns it off."""
        return getattr(self.container, "supports_chip_sharing", False) \
            and os.environ.get("RAFIKI_TPU_CHIP_SHARE", "1") != "0"

    # --- Train services (§3.1) ---

    def create_train_services(self, train_job_id: str) -> List[Dict[str, Any]]:
        job = self.meta.get_train_job(train_job_id)
        budget = normalize_budget(job["budget"])
        chips_per_trial = max(1, int(budget.get(CHIPS_PER_TRIAL, 1)))
        chip_count = int(budget.get(BudgetOption.CHIP_COUNT, 0) or 0)
        n_workers = max(1, math.ceil(chip_count / chips_per_trial))

        services = []
        for sub in self.meta.get_sub_train_jobs(train_job_id):
            advisor_svc = self._launch(
                ServiceType.ADVISOR, {EnvVars.SUB_TRAIN_JOB_ID: sub["id"]})
            self.meta.add_train_job_worker(advisor_svc["id"], sub["id"])
            services.append(advisor_svc)
            launched = 0
            for _ in range(n_workers):
                # Sharing applies to the FIRST worker only: it keeps a
                # new job live on a full slice (time-sliced with the
                # incumbents); workers beyond the first are capacity,
                # and stacking capacity onto co-owned chips would just
                # thrash the device queue.
                svc = self.add_train_worker(
                    sub["id"], chips_per_trial,
                    shared_ok=(launched == 0 and self._sharing_ok()))
                if svc is None:
                    # Slice is full: run with what we got (≥1); trials
                    # queue behind fewer workers rather than failing.
                    _log.warning(
                        "chip allocation exhausted for %s after %d workers",
                        sub["id"], launched)
                    break
                services.append(svc)
                launched += 1
            if launched == 0:
                self._stop_service(advisor_svc["id"])
                raise RuntimeError(
                    f"no chips available for train job {train_job_id}")
        return services

    def add_train_worker(self, sub_id: str, chips_per_trial: int = 1,
                         shared_ok: bool = False,
                         ) -> Optional[Dict[str, Any]]:
        """Attach one train worker for ``sub_id`` on THIS node's chips.

        Public scale-out seam: a second node sharing the meta store /
        params dir / bus calls this (via ``Admin.attach_workers`` or the
        ``join`` CLI) to add elastic capacity to a running job — its
        worker pulls proposals from the same bus-hosted advisor, so the
        search stays coordinated across nodes. Returns None when this
        node's chips are exhausted (``shared_ok`` admits the time-sliced
        fallback — see ``_sharing_ok``).
        """
        svc_row = self.meta.create_service(ServiceType.TRAIN,
                                           ServiceStatus.DEPLOYING,
                                           node_id=self.node_id)
        group = self.allocator.allocate(chips_per_trial,
                                        name=self._alloc_name(svc_row["id"]),
                                        shared_ok=shared_ok)
        if group is None:
            self.meta.update_service(svc_row["id"],
                                     status=ServiceStatus.STOPPED)
            return None
        chips = list(group.indices)
        env = self._base_env(svc_row["id"], ServiceType.TRAIN)
        env[EnvVars.SUB_TRAIN_JOB_ID] = sub_id
        env[EnvVars.CHIPS] = ",".join(str(c) for c in chips)
        try:
            container_id = self.container.create_service(svc_row["id"], env)
        except Exception:
            self.allocator.release(self._alloc_name(svc_row["id"]))
            self.meta.update_service(svc_row["id"],
                                     status=ServiceStatus.ERRORED)
            raise
        self.meta.update_service(svc_row["id"], container_id=container_id,
                                 chips=chips)
        self.meta.add_train_job_worker(svc_row["id"], sub_id)
        return self.meta.get_service(svc_row["id"])

    def stop_train_services(self, train_job_id: str) -> None:
        """Terminal teardown: every stop path for a train job funnels
        here — explicit ``stop_train_job``, natural wind-down
        (``_refresh_train_job_status``), error termination once the
        workers give up — so this is also where the job's scoped rung
        checkpoints are swept. The workers' own budget-exhausted sweep
        (TrialRunner.cleanup_scoped_checkpoints) covers admin-less
        runners, but a stopped or error-terminated job never reaches
        it and would leak one train-state dir per halving
        configuration. Crash-restart is unaffected: supervise recreates
        individual workers mid-job without coming through here."""
        for sub in self.meta.get_sub_train_jobs(train_job_id):
            for w in self.meta.get_train_job_workers(sub["id"]):
                self._stop_service(w["service_id"])
            self._sweep_scoped_checkpoints(sub["id"])

    def _sweep_scoped_checkpoints(self, sub_id: str) -> None:
        if not self.params_dir:
            return
        root = os.path.join(self.params_dir, "ckpt")
        if not os.path.isdir(root):
            return
        import shutil

        for name in os.listdir(root):
            if name.startswith(f"{sub_id}-"):
                shutil.rmtree(os.path.join(root, name), ignore_errors=True)

    # NODE_LEASE (set per instance in __init__): how long a foreign
    # node's RUNNING row stays credible without a heartbeat. Must
    # comfortably exceed the heartbeat cadence (NODE_LEASE/4 in
    # LocalPlatform) PLUS worst-case heartbeat delays: sqlite busy
    # waits (up to 30 s), long GIL-holding XLA traces, and cross-host
    # clock skew (heartbeat_at is the writer's clock, this check is
    # the reader's — nodes sharing a meta store are assumed NTP-synced
    # to within a few seconds). Expiry is detection of a node presumed
    # DEAD, not fencing of a live one: a worker that was merely
    # stalled finishes its trial and writes its rows normally (trial
    # results are idempotent), it just stops counting toward job
    # liveness. NodeConfig.node_lease / RAFIKI_TPU_NODE_LEASE.

    def _ownership(self, svc: Dict[str, Any]) -> str:
        """'local' | 'foreign' | 'unowned-skip'.

        NULL node_id rows (pre-upgrade databases) are adopted as local
        by the primary node only; secondary (join) nodes must neither
        stop nor judge them.
        """
        nid = svc.get("node_id")
        if nid == self.node_id:
            return "local"
        if nid is None:
            return "local" if self.adopt_unowned else "unowned-skip"
        return "foreign"

    @staticmethod
    def last_heartbeat(svc: Dict[str, Any]) -> float:
        """The service's last liveness signal (creation counts as the
        first heartbeat) — the ONE definition shared by lease checks
        and the /status cluster view."""
        return svc.get("heartbeat_at") or svc.get("created_at") or 0.0

    def _lease_fresh(self, svc: Dict[str, Any]) -> bool:
        import time

        return (time.time() - self.last_heartbeat(svc)) <= self.NODE_LEASE

    def heartbeat(self) -> None:
        """Refresh this node's liveness lease (called by the platform's
        supervisor loop). The cluster node registry's announce rides
        the same beat — same cadence, zero extra threads — and is
        isolated so a broker outage cannot starve the meta lease."""
        self.meta.touch_node_services(self.node_id)
        if self.node_registry is not None:
            try:
                self.node_registry.announce()
            except (ConnectionError, OSError, RuntimeError):
                _log.warning("node registry announce failed",
                             exc_info=True)

    def train_services_active(self, train_job_id: str) -> bool:
        """True while any TRAIN worker of the job is alive.

        Local services are liveness-checked against this node's
        container manager; services another node attached (elastic
        scale-out) are judged by their meta-store status, credible only
        while the owning node's heartbeat lease is fresh — a join node
        that died ungracefully stops blocking completion once its lease
        expires.
        """
        for sub in self.meta.get_sub_train_jobs(train_job_id):
            for w in self.meta.get_train_job_workers(sub["id"]):
                svc = self.meta.get_service(w["service_id"])
                if svc["service_type"] != ServiceType.TRAIN:
                    continue
                if svc["status"] not in _ACTIVE:
                    continue
                own = self._ownership(svc)
                if own == "foreign" or own == "unowned-skip":
                    # Not ours to liveness-check; credible while the
                    # lease (or, for unowned legacy rows, creation
                    # time) is fresh.
                    if self._lease_fresh(svc):
                        return True
                    continue
                if self.container.service_alive(
                        svc["container_id"] or svc["id"]):
                    return True
        return False

    # --- Inference services (§3.2) ---

    def create_inference_services(self, inference_job_id: str,
                                  trial_ids: List[str],
                                  chips_per_worker: int = 1,
                                  ) -> List[Dict[str, Any]]:
        # Ensemble packing: with fewer allocatable chip groups than
        # trials, one worker serves several models from its group
        # (round-robin bins) instead of failing the deploy — a v5e-1
        # still serves a real top-k ensemble. Groups are allocated
        # greedily (free-chip math would overestimate under
        # fragmentation: allocate() needs contiguous runs), so the
        # worker count degrades to whatever actually fits.
        grabbed: List[Dict[str, Any]] = []  # service rows with a group
        for _ in trial_ids:
            svc_row = self.meta.create_service(ServiceType.INFERENCE,
                                               ServiceStatus.DEPLOYING,
                                               node_id=self.node_id)
            # The FIRST group may be time-sliced (liveness fallback,
            # mirrors train): a fully-subscribed slice still admits the
            # job's serving as ONE worker on a co-owned group packing
            # the whole ensemble. allocate() tries exclusive placement
            # before sharing, so this changes nothing when chips are
            # free.
            group = self.allocator.allocate(
                chips_per_worker, name=self._alloc_name(svc_row["id"]),
                shared_ok=(not grabbed and self._sharing_ok()))
            if group is None:
                self.meta.update_service(svc_row["id"],
                                         status=ServiceStatus.STOPPED)
                break
            grabbed.append({"row": svc_row, "group": group})
        if not grabbed:
            # A worker without an allocation would fall back to ALL
            # devices and trample running jobs' chip groups; fail the
            # deploy instead.
            raise RuntimeError(
                f"no chips available for inference job "
                f"{inference_job_id} (need {chips_per_worker}/worker; "
                f"{self.allocator.free_chips} free, fragmented)")
        bins: List[List[str]] = [[] for _ in grabbed]
        for i, tid in enumerate(trial_ids):
            bins[i % len(grabbed)].append(tid)

        services = []
        for holder, bin_ids in zip(grabbed, bins):
            trial_id = ",".join(bin_ids)
            svc_row, group = holder["row"], holder["group"]
            try:
                svc = self._launch_inference_worker(
                    svc_row, group, inference_job_id, trial_id)
            except Exception:
                # Roll back the rest: holders not yet launched and
                # workers already launched for this job (the failing
                # holder itself was released/errored by the helper).
                launched_ids = {s["id"] for s in services}
                for h in grabbed:
                    hid = h["row"]["id"]
                    if hid in launched_ids or hid == svc_row["id"]:
                        continue
                    self.allocator.release(self._alloc_name(hid))
                    self.meta.update_service(hid,
                                             status=ServiceStatus.STOPPED)
                for launched in services:
                    self._stop_service(launched["id"])
                raise
            services.append(svc)
        predictor = self._launch(
            ServiceType.PREDICT,
            {EnvVars.INFERENCE_JOB_ID: inference_job_id})
        self.meta.add_inference_job_worker(predictor["id"], inference_job_id,
                                           PREDICTOR_TRIAL)
        services.append(predictor)
        return services

    def _launch_inference_worker(self, svc_row: Dict[str, Any], group,
                                 inference_job_id: str, trial_id: str,
                                 ) -> Dict[str, Any]:
        """Env + container launch + meta wiring for ONE inference
        worker holding an allocated chip group. On container failure:
        releases this worker's chips, marks its row ERRORED, and
        re-raises (callers add any broader rollback)."""
        chips = list(group.indices)
        env = self._base_env(svc_row["id"], ServiceType.INFERENCE)
        env[EnvVars.INFERENCE_JOB_ID] = inference_job_id
        env[EnvVars.TRIAL_ID] = trial_id
        env[EnvVars.CHIPS] = ",".join(str(c) for c in chips)
        try:
            container_id = self.container.create_service(svc_row["id"],
                                                         env)
        except Exception:
            self.allocator.release(self._alloc_name(svc_row["id"]))
            self.meta.update_service(svc_row["id"],
                                     status=ServiceStatus.ERRORED)
            raise
        self.meta.update_service(svc_row["id"], container_id=container_id,
                                 chips=chips)
        self.meta.add_inference_job_worker(svc_row["id"],
                                           inference_job_id, trial_id)
        return self.meta.get_service(svc_row["id"])

    def add_inference_worker(self, inference_job_id: str, trial_id: str,
                             chips_per_worker: int = 1,
                             ) -> Optional[Dict[str, Any]]:
        """Attach one REPLICA worker for an already-served trial bin on
        THIS node's chips (elastic serving capacity: the Predictor
        shards each super-batch across same-bin replicas, so QPS scales
        without changing the ensemble semantics). Exclusive placement
        first; when the slice is full, a resident-runner node falls
        back to a time-sliced group (same tier the first serving group
        may use) so scale-out is still possible on a saturated box.
        Returns None when this node's chips are exhausted."""
        svc_row = self.meta.create_service(ServiceType.INFERENCE,
                                           ServiceStatus.DEPLOYING,
                                           node_id=self.node_id)
        group = self.allocator.allocate(
            chips_per_worker, name=self._alloc_name(svc_row["id"]),
            shared_ok=self._sharing_ok())
        if group is None:
            self.meta.update_service(svc_row["id"],
                                     status=ServiceStatus.STOPPED)
            return None
        return self._launch_inference_worker(svc_row, group,
                                             inference_job_id, trial_id)

    def active_inference_workers(self, inference_job_id: str,
                                 ) -> List[Dict[str, Any]]:
        """The job's ACTIVE (non-predictor) worker mapping rows — what
        is currently served. Mapping rows outlive their services (a
        replaced bin's row stays for history), so liveness is judged by
        each row's SERVICE status; a stale mapping must never read as
        "this trial is served"."""
        rows = []
        for w in self.meta.get_inference_job_workers(inference_job_id):
            if w["trial_id"] == PREDICTOR_TRIAL:
                continue
            svc = self.meta.get_service(w["service_id"])
            if svc is not None and svc["status"] in _ACTIVE:
                rows.append(w)
        return rows

    def swap_inference_worker(self, inference_job_id: str,
                              trial_id: str,
                              replace_service_ids: List[str] = (),
                              register_timeout: float = 180.0,
                              ) -> Dict[str, Any]:
        """Hot-swap primitive behind trial promotion: launch a worker
        for ``trial_id``, WAIT for its bus registration (workers
        register only after model load + warm-up — the moment the
        Predictor can plan shards onto the new bin), and only then stop
        the ``replace_service_ids`` workers, so the swap never drops a
        bin's vote. Public on purpose (carried r12 item): admin.py used
        to hand-roll this against ``_stop_service``/``_ACTIVE``, which
        meant every service-teardown change had to be mirrored there.

        Rollback: a registration timeout or a self-ERRORED launch stops
        the NEW service (releasing its chips — an errored worker never
        reaches the supervise sweep, which scans RUNNING rows only) and
        raises; the replaced workers are untouched. The incoming worker
        re-reads the serving env at model load, so per-bin derived
        state — ``RAFIKI_TPU_SERVING_QUANT`` int8 scales in particular
        — is recomputed for the promoted trial by construction.

        Callers serialize concurrent swaps themselves (the admin's
        ``_promote_lock``): this method deliberately spans a
        registration wait and holds no lock of its own.
        """
        import time as _time

        from ..cache import Cache as _BusCache

        new_svc = self.add_inference_worker(inference_job_id, trial_id)
        if new_svc is None:
            raise RuntimeError(
                "no chips available for the incoming trial's worker")
        bus_cache = _BusCache(self.serving_bus())
        deadline = _time.monotonic() + register_timeout
        while new_svc["id"] not in \
                bus_cache.running_workers(inference_job_id):
            if _time.monotonic() >= deadline:
                self._stop_service(new_svc["id"])
                raise RuntimeError(
                    f"incoming worker {new_svc['id'][:8]} did not "
                    f"register within {register_timeout}s; swap rolled "
                    f"back")
            svc_row = self.meta.get_service(new_svc["id"])
            if svc_row and svc_row["status"] == ServiceStatus.ERRORED:
                self._stop_service(new_svc["id"])
                raise RuntimeError(
                    f"incoming worker {new_svc['id'][:8]} errored "
                    f"during startup")
            _time.sleep(0.2)
        stopped = []
        for sid in replace_service_ids:
            self._stop_service(sid)
            stopped.append(sid)
        return {"new_service": new_svc, "stopped_service_ids": stopped}

    def drain_inference_worker(self, service_id: str,
                               drain_timeout: float = 15.0,
                               ) -> Dict[str, Any]:
        """Gracefully retire ONE inference replica (the autoscaler's
        scale-down primitive): deregister it from the bus so the
        Predictor's next registry scan stops planning shards onto it,
        push a ``__drain__`` marker onto its query queue so the worker
        finishes everything already enqueued and exits its serve loop
        cleanly (re-asserting its registration lease no longer matters
        — the final unregister on exit is authoritative), then stop
        the service and release its chips.

        Shards a still-in-flight plan pushes AFTER the marker go
        unanswered; the Predictor's straggler resubmit covers them
        from a sibling — the exact machinery replica death already
        exercises, minus the death. A worker that does not exit within
        ``drain_timeout`` (wedged on a long burst) is stopped hard;
        either way the row ends STOPPED and the chips come back.
        Returns ``{"drained": bool}`` (False = the hard-stop path).
        """
        import time as _time

        from ..cache import Cache as _BusCache

        rows = self.meta._select(
            "SELECT * FROM inference_job_workers WHERE service_id = ?",
            (service_id,))
        drained = False
        if rows:
            try:
                cache = _BusCache(self.serving_bus())
                cache.unregister_worker(rows[0]["inference_job_id"],
                                        service_id)
                cache.send_drain(service_id)
            except (ConnectionError, OSError, RuntimeError):
                _log.warning("drain signalling for %s failed; hard "
                             "stop", service_id[:8], exc_info=True)
            else:
                deadline = _time.monotonic() + drain_timeout
                while _time.monotonic() < deadline:
                    svc = self.meta.get_service(service_id)
                    if svc is None or svc["status"] not in _ACTIVE:
                        drained = True
                        break
                    _time.sleep(0.05)
        # Idempotent finish: destroys the container handle and releases
        # the chip group whether the worker exited cleanly or not.
        self._stop_service(service_id)
        return {"service_id": service_id, "drained": drained}

    def stop_inference_services(self, inference_job_id: str) -> None:
        for w in self.meta.get_inference_job_workers(inference_job_id):
            self._stop_service(w["service_id"])

    def stop_own_services(self) -> None:
        """Stop every still-active service THIS node launched (shutdown
        hygiene: a node leaving a shared meta store must not leak rows
        that read as live remote workers forever). NULL-node rows from
        pre-upgrade databases are stopped only by the adopting
        (primary) node."""
        for svc in self.meta.get_services():
            if svc["status"] in _ACTIVE and \
                    self._ownership(svc) == "local":
                self._stop_service(svc["id"])

    # --- Supervision (SURVEY.md §5: failure detection / recovery) ---

    def supervise(self) -> List[str]:
        """One sweep: mark dead services ERRORED, restart train workers
        and inference replicas.

        Train recovery: trial rows are idempotent (a crashed trial
        stays ERRORED; the advisor re-proposes), so recovery is a fresh
        worker on the same chip range. Inference recovery: a dead
        replica's trial bin loses serving capacity (and, when it was
        the bin's last replica, its ensemble vote), so a fresh replica
        is attached for the same bin while the job is still live — the
        Predictor's registry scan folds it into the next shard plan.
        Returns the ids of restarted services.
        """
        restarted: List[str] = []
        # Dead replicas whose earlier respawn failed for capacity are
        # already ERRORED — invisible to the RUNNING scan below, so
        # only this queue can ever retry them. Swapped out here,
        # retried AFTER the scan: chips the scan releases this very
        # sweep can then satisfy the retry. A retry that fails for
        # capacity again re-queues itself.
        pending, self._pending_respawns = self._pending_respawns, []
        try:
            # Node-scoped: this node's container manager can only
            # judge what IT launched. Foreign rows are swept by lease
            # expiry instead; NULL-node rows (pre-upgrade databases)
            # are adopted as local.
            for svc in self.meta.get_services(
                    status=ServiceStatus.RUNNING):
                own = self._ownership(svc)
                if own == "unowned-skip":
                    continue
                if own == "foreign":
                    if not self._lease_fresh(svc):
                        self.meta.update_service(
                            svc["id"], status=ServiceStatus.ERRORED)
                        _log.warning("lease expired on %s from node "
                                     "%s; marked errored",
                                     svc["id"][:8], svc["node_id"])
                    continue
                if self.container.service_alive(svc["container_id"]
                                                or svc["id"]):
                    continue
                self.meta.update_service(svc["id"],
                                         status=ServiceStatus.ERRORED)
                self._release_chips_of(svc)
                new_svc = None
                if svc["service_type"] == ServiceType.TRAIN:
                    new_svc = self._respawn_train_worker(svc)
                elif svc["service_type"] == ServiceType.INFERENCE:
                    try:
                        new_svc = self._respawn_inference_worker(svc)
                    except Exception:
                        # A failed launch (container error, transient
                        # meta/bus trouble) must not orphan the
                        # replica: queue it — the ERRORED row can
                        # never re-enter this scan.
                        _log.exception(
                            "respawn of dead inference worker %s "
                            "failed; queued for retry", svc["id"][:8])
                        self._pending_respawns.append(svc)
                self._note_restart(svc, new_svc, restarted)
            while pending:
                self._note_restart(
                    pending[0],
                    self._respawn_inference_worker(pending[0],
                                                   reap=False),
                    restarted)
                # Popped only AFTER the attempt resolved (a no-capacity
                # None already re-queued it on the fresh list).
                pending.pop(0)
        finally:
            # An exception mid-sweep must not orphan un-retried
            # replicas: their rows are ERRORED, invisible to every
            # future RUNNING scan, so this queue is their only way
            # back into a bin.
            self._pending_respawns.extend(pending)
        scrapes = None
        if self.slo_engine is not None or self.autoscaler is not None:
            # Both metric consumers judge the SAME predictor endpoints
            # this pass: one sweep-scoped cache means each /stats +
            # /metrics is fetched (and an unreachable host's timeout
            # paid) once, not once per consumer.
            from .scrape import ScrapeCache

            scrapes = ScrapeCache()
        if self.slo_engine is not None:
            # The SLO judgment layer rides the supervise cadence,
            # BEFORE the autoscaler so a same-sweep firing alert is
            # visible as scale-up pressure (docs/observability.md).
            # Isolated like the autoscaler: an evaluation failure must
            # not break dead-service recovery.
            try:
                self.slo_engine.sweep(scrapes=scrapes)
            except Exception:
                _log.exception("slo sweep failed")
        if self.autoscaler is not None:
            # The serving control loop rides the supervise cadence
            # (docs/autoscaling.md). Isolated: a scrape/actuation
            # failure must not break dead-service recovery.
            try:
                self.autoscaler.sweep(scrapes=scrapes)
            except Exception:
                _log.exception("autoscale sweep failed")
        if self._node_faults is not None:
            # Chaos plane: node.kill (op matches this node's id). Fires
            # at sweep END so the killed services stay dead until the
            # NEXT sweep detects and respawns them — tests get an
            # observable degraded window where only spread-placed
            # sibling replicas keep a bin's vote alive.
            act = self._node_faults(op=self.node_id)
            if act is not None and act[0] == "kill":
                self._kill_node_services()
        return restarted

    def _kill_node_services(self) -> None:
        """Whole-node death (chaos ``node.kill``): hard-kill every
        RUNNING service this node owns. Deliberately NO meta updates
        and NO chip release — a dying node can't tidy its own rows;
        the next sweep's normal dead-service path (alive probe ->
        ERRORED -> respawn) is what recovery exercises."""
        victims = [svc for svc in self.meta.get_services()
                   if svc["status"] == ServiceStatus.RUNNING
                   and self._ownership(svc) == "local"]
        _log.warning("node.kill fired on node %s: hard-killing %d "
                     "running services", self.node_id, len(victims))
        for svc in victims:
            try:
                self.container.kill_service(svc["container_id"]
                                            or svc["id"])
            except Exception:
                _log.exception("node.kill: hard kill of %s failed",
                               svc["id"][:8])

    def _note_restart(self, svc: Dict[str, Any],
                      new_svc: Optional[Dict[str, Any]],
                      restarted: List[str]) -> None:
        if new_svc is None:
            return
        restarted.append(new_svc["id"])
        _log.warning("restarted dead %s worker %s as %s",
                     svc["service_type"], svc["id"][:8],
                     new_svc["id"][:8])
        if _metrics.metrics_enabled():
            # rta: disable=RTA301 service_type is the bounded ServiceType vocabulary; supervise counters are deliberately immortal
            _metrics.registry().counter(
                "rafiki_tpu_node_restarts_total",
                "Dead services respawned by the supervise "
                "sweep, by service type").inc(
                    service_type=svc["service_type"])

    def _respawn_train_worker(self, svc: Dict[str, Any],
                              ) -> Optional[Dict[str, Any]]:
        rows = self.meta._select(
            "SELECT * FROM train_job_workers WHERE service_id = ?",
            (svc["id"],))
        if not rows:
            return None
        sub_id = rows[0]["sub_train_job_id"]
        # shared_ok mirrors admission: a worker that was admitted
        # time-sliced (full slice) could otherwise never restart —
        # exclusive allocation on the still-full slice returns None
        # and the job would keep an advisor but zero workers.
        return self.add_train_worker(
            sub_id, chips_per_trial=len(svc.get("chips") or [1]),
            shared_ok=self._sharing_ok())

    def _respawn_inference_worker(self, svc: Dict[str, Any],
                                  reap: bool = True,
                                  ) -> Optional[Dict[str, Any]]:
        """Fresh replica for a dead inference worker's trial bin.

        Only while the job itself is still live: a worker dying because
        its job was stopped must not resurrect serving capacity the
        operator just tore down. ``add_inference_worker`` already
        admits with ``shared_ok`` (same liveness fallback as the train
        respawn path); a None return for CAPACITY (this node's chips
        exhausted, even time-sliced) queues the replica on
        ``_pending_respawns`` — the bin stays degraded (the Predictor
        keeps serving partial-bin ensembles) and every later sweep
        retries until chips free or the job stops. ``reap=False`` on
        those retries: the stale registration was reaped at death."""
        rows = self.meta._select(
            "SELECT * FROM inference_job_workers WHERE service_id = ?",
            (svc["id"],))
        if not rows:
            return None
        job_id = rows[0]["inference_job_id"]
        trial_id = rows[0]["trial_id"]
        if reap:
            # A hard-killed worker never ran its unregister path: reap
            # its stale bus registration so the Predictor's registry
            # scan stops planning shards onto a ghost replica (the
            # respawned worker registers under its own fresh id).
            self._reap_worker_registration(job_id, svc["id"])
        job = self.meta.get_inference_job(job_id)
        if job is None or job["status"] not in (
                InferenceJobStatus.STARTED, InferenceJobStatus.RUNNING):
            return None
        n_chips = len(svc.get("chips") or [1])
        # Probe capacity BEFORE add_inference_worker: that path names
        # its allocation after a freshly-created service row, so a
        # no-capacity attempt leaves an orphan STOPPED row — tolerable
        # once, but this queue retries every sweep and would otherwise
        # grow the services table without bound on a saturated node.
        probe = f"respawn-probe:{svc['id']}"
        group = self.allocator.allocate(n_chips, name=probe,
                                        shared_ok=self._sharing_ok())
        if group is None:
            self._pending_respawns.append(svc)
            return None
        self.allocator.release(probe)
        new_svc = self.add_inference_worker(job_id, trial_id,
                                            chips_per_worker=n_chips)
        if new_svc is None:
            # Lost the probe-to-admit race; the next sweep retries.
            self._pending_respawns.append(svc)
        return new_svc

    def serving_bus(self):
        """The bus this node's serving plane rides: thread mode reuses
        the container's shared bus; subprocess / docker modes connect
        (once, lazily) by URI. Shared by registration reaping and the
        admin promotion path's wait-for-registration probe."""
        bus = getattr(getattr(self.container, "ctx", None),
                      "bus", None)
        if bus is not None:
            return bus
        from ..bus import connect

        if self._reap_bus is None:
            self._reap_bus = connect(self.bus_uri)
        return self._reap_bus

    def _reap_worker_registration(self, job_id: str,
                                  service_id: str) -> None:
        """Best-effort delete of a dead worker's bus registration.

        A broker outage here is benign — a restarted broker forgot the
        registration anyway."""
        try:
            from ..cache import Cache

            Cache(self.serving_bus()).unregister_worker(job_id,
                                                        service_id)
        except (ConnectionError, OSError, RuntimeError):
            _log.warning("could not reap bus registration of dead "
                         "worker %s", service_id[:8], exc_info=True)

    # --- Utilization (BASELINE north star: ≥90% chip utilization) ---

    def chip_utilization(self) -> float:
        return self.allocator.utilization()
