"""Cluster node registry: node identity + chip inventory over the bus.

The paper's Admin orchestrates workers across machines; this registry is
the piece that makes the node set *visible* to the serving plane. Each
node's :class:`~rafiki_tpu.admin.services_manager.ServicesManager`
announces one record on the serving bus under ``n:{node_id}`` — host,
pid, chip inventory, the node's broker URI, and a heartbeat stamp — and
every consumer (``GET /nodes``, the relay topology, failure-domain
spread placement) reads the same records back. The announce rides the
platform's EXISTING heartbeat cadence (``ServicesManager.heartbeat``),
so the registry adds zero threads.

Attached by the platform ONLY when ``RAFIKI_TPU_CLUSTER_FABRIC`` is on
(NodeConfig.cluster_fabric): off = ``services.node_registry`` stays
None — no ``rafiki_tpu_node_*`` series, no extra bus traffic,
byte-identical single-node behavior (docs/cluster.md).

Liveness here is registry-local and intentionally simpler than the
meta-store lease machinery: a record is *live* while its heartbeat is
younger than ``lease_s`` (the same NODE_LEASE window). A node that died
ungracefully stops influencing relay wiring and spread votes one lease
window later — exactly the staleness bound the supervise sweep already
accepts for foreign service rows.
"""

from __future__ import annotations

import logging
import os
import socket
import time
from typing import Any, Callable, Dict, List

from ..observe import metrics as _metrics

_log = logging.getLogger(__name__)

#: kv prefix for node records on the serving bus (vocabulary sibling of
#: the worker registration ``w:{job}:{service}`` keys).
NODE_KEY_PREFIX = "n:"


def node_key(node_id: str) -> str:
    return f"{NODE_KEY_PREFIX}{node_id}"


class NodeRegistry:
    """One node's view of the cluster membership (docs/cluster.md).

    ``bus_factory`` is a zero-arg callable returning the serving bus
    (``ServicesManager.serving_bus``) — lazy on purpose: construction
    must not open a connection the node may never need if the broker is
    still coming up.
    """

    def __init__(self, bus_factory: Callable[[], Any], node_id: str,
                 n_chips: int = 0, bus_uri: str = "",
                 lease_s: float = 120.0):
        self._bus_factory = bus_factory
        self.node_id = node_id
        self.n_chips = int(n_chips or 0)
        # This node's broker URI, published so peers can wire
        # BusServer.add_peer from the registry instead of static config.
        self.bus_uri = bus_uri
        self.lease_s = float(lease_s)
        # Gauge exists only while a registry does (fabric on) — the
        # cluster_fabric=off side of the bench A/B asserts ZERO
        # rafiki_tpu_node_* series.
        self._peers_gauge = None
        if _metrics.metrics_enabled():
            self._peers_gauge = _metrics.registry().gauge(
                "rafiki_tpu_node_peers",
                "Nodes with a fresh heartbeat in the cluster node "
                "registry, as seen by this node")

    # --- Write side (rides ServicesManager.heartbeat) -----------------

    def announce(self) -> None:
        """Write/refresh this node's record. Called from the heartbeat
        path, so failures must not raise into the beat loop — the
        caller already isolates us, but a broker outage is expected
        during rolling restarts and only merits a warning."""
        rec = {"node": self.node_id, "host": socket.gethostname(),
               "pid": os.getpid(), "chips": self.n_chips,
               "bus": self.bus_uri, "hb": time.time()}
        self._bus_factory().set(node_key(self.node_id), rec)
        if self._peers_gauge is not None:
            self._peers_gauge.set(float(len(self.live_nodes())))

    def withdraw(self) -> None:
        """Delete this node's record (shutdown hygiene: a leaving node
        must not count as a spread-placement target for a full lease
        window)."""
        try:
            self._bus_factory().delete(node_key(self.node_id))
        except (ConnectionError, OSError, RuntimeError):
            pass  # broker gone = record gone with it

    # --- Read side ----------------------------------------------------

    def nodes(self) -> Dict[str, Dict[str, Any]]:
        """Every registered node's record, annotated with heartbeat age
        and the registry-local liveness verdict."""
        bus = self._bus_factory()
        now = time.time()
        out: Dict[str, Dict[str, Any]] = {}
        for key in bus.keys(prefix=NODE_KEY_PREFIX):
            rec = bus.get(key)
            if not isinstance(rec, dict):
                continue
            nid = str(rec.get("node") or key[len(NODE_KEY_PREFIX):])
            try:
                age = max(0.0, now - float(rec.get("hb") or 0.0))
            except (TypeError, ValueError):
                age = float("inf")
            out[nid] = {
                "host": rec.get("host"), "pid": rec.get("pid"),
                "chips": rec.get("chips"), "bus": rec.get("bus"),
                "heartbeat_age_s": round(min(age, 1e9), 1),
                "live": age <= self.lease_s,
            }
        return out

    def live_nodes(self) -> List[str]:
        return sorted(n for n, r in self.nodes().items() if r["live"])

    def relay_peers(self) -> Dict[str, str]:
        """``node_id -> broker URI`` for every OTHER live node — the
        wiring input for ``BusServer.add_peer`` (relay topology)."""
        return {n: str(r["bus"]) for n, r in self.nodes().items()
                if r["live"] and r.get("bus") and n != self.node_id}

    def spread_ok(self, replicas_by_node: Dict[str, int]) -> bool:
        """Failure-domain spread vote for ONE bin's scale-up.

        ``replicas_by_node`` counts the bin's active replicas per node
        (meta rows carry node_id). Place locally iff this node holds a
        MINIMUM count among live nodes AND is the first such node in
        sorted order — the deterministic tie-break means exactly one
        node acts per pressure round, so N nodes under the same signal
        lay replicas down round-robin across failure domains instead of
        N-fold over-provisioning one node. A registry that cannot see
        this node (broker outage, pre-announce races) votes True:
        spread is an optimization, never a liveness gate.
        """
        live = self.live_nodes()
        if not live or self.node_id not in live:
            return True
        counts = {n: int(replicas_by_node.get(n, 0)) for n in live}
        lo = min(counts.values())
        if counts[self.node_id] > lo:
            return False
        leaders = sorted(n for n, c in counts.items() if c == lo)
        return leaders[0] == self.node_id

    # --- Surfaces -----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The ``GET /nodes`` body."""
        return {"enabled": True, "node_id": self.node_id,
                "lease_s": self.lease_s, "nodes": self.nodes()}

    def health(self) -> Dict[str, Any]:
        """The compact fold for ``GET /status`` (r20 health surface)."""
        nodes = self.nodes()
        return {"fabric": True, "nodes_registered": len(nodes),
                "nodes_live": sum(1 for r in nodes.values()
                                  if r["live"])}

    def close(self) -> None:
        """Withdraw + drop the registry's series (platform shutdown)."""
        self.withdraw()
        if self._peers_gauge is not None:
            self._peers_gauge.remove()
            self._peers_gauge = None
