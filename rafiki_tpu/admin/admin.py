"""Admin orchestration: users, models, train jobs, inference jobs.

Parity: SURVEY.md §2 "Admin" + §3.1/§3.2 call stacks (upstream
``rafiki/admin/admin.py``). The REST frontend (``rafiki_tpu.admin.app``)
is a thin shell over this class; everything here is also directly usable
in-process (the resident-runner deployment and the test seam).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

from ..constants import (BudgetOption, InferenceJobStatus, ModelAccessRight,
                         TrainJobStatus, TrialStatus, UserType)
from ..model.knobs import knob_config_to_json
from ..store import MetaStore, ParamStore
from ..utils import auth
from ..utils.model_loader import load_model_class
from .services_manager import ServicesManager, normalize_budget

_log = logging.getLogger(__name__)


class Admin:
    def __init__(self, meta: MetaStore, params: ParamStore,
                 services: ServicesManager, jwt_secret: str = "rafiki-tpu",
                 superadmin_email: str = "superadmin@rafiki",
                 superadmin_password: str = "rafiki",
                 datasets_dir: str = ""):
        self.meta = meta
        self.params = params
        self.services = services
        self.jwt_secret = jwt_secret
        # Uploaded datasets land here (REST/browser upload path); empty
        # disables uploads — jobs can always reference datasets by
        # filesystem path directly.
        self.datasets_dir = datasets_dir
        if self.meta.get_user_by_email(superadmin_email) is None:
            self.meta.create_user(
                superadmin_email, auth.hash_password(superadmin_password),
                UserType.SUPERADMIN)
        # Serializes promote_trial: its validate -> launch -> wait ->
        # swap sequence spans a registration wait, and two concurrent
        # promotes of the same trial would BOTH pass the already-served
        # check and both burn a chip allocation. Promotion is a rare
        # control-plane act; one node-wide lock is the simple fix.
        import threading

        self._promote_lock = threading.Lock()

    # --- Auth / users ---

    def authenticate(self, email: str, password: str) -> Dict[str, Any]:
        user = self.meta.get_user_by_email(email)
        if user is None or not auth.verify_password(password,
                                                   user["password_hash"]):
            raise PermissionError("invalid email or password")
        if user["banned_at"] is not None:
            raise PermissionError("user is banned")
        token = auth.encode_token(
            {"user_id": user["id"], "user_type": user["user_type"]},
            self.jwt_secret)
        return {"user_id": user["id"], "user_type": user["user_type"],
                "token": token}

    def authorize(self, token: str) -> Dict[str, Any]:
        """Decode a bearer token AND re-check the user row: a ban must
        revoke existing sessions immediately, not at token expiry."""
        try:
            claims = auth.decode_token(token, self.jwt_secret)
        except ValueError as e:
            raise PermissionError(f"invalid token: {e}")
        user = self.meta.get_user(claims.get("user_id", ""))
        if user is None or user["banned_at"] is not None:
            raise PermissionError("user is banned or deleted")
        return claims

    def create_user(self, email: str, password: str,
                    user_type: str) -> Dict[str, Any]:
        user = self.meta.create_user(email, auth.hash_password(password),
                                     user_type)
        return {"id": user["id"], "email": email, "user_type": user_type}

    # --- Access control ---

    @staticmethod
    def check_access(claims: Optional[Dict[str, Any]],
                     owner_user_id: str) -> None:
        """Resource-level authorization: the owner, or a platform admin.

        ``claims=None`` means an in-process trusted caller (resident
        runner / tests); the REST layer always passes the token claims.
        """
        if claims is None:
            return
        if claims.get("user_id") == owner_user_id:
            return
        if claims.get("user_type") in (UserType.SUPERADMIN, UserType.ADMIN):
            return
        err = PermissionError("not the owner of this resource")
        err.status = 403  # the REST layer maps this to Forbidden, not 401
        raise err

    def _owned_train_job(self, train_job_id: str,
                         claims: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        job = self.meta.get_train_job(train_job_id)
        if job is None:
            raise ValueError(f"unknown train job {train_job_id}")
        self.check_access(claims, job["user_id"])
        return job

    def _owned_inference_job(self, job_id: str,
                             claims: Optional[Dict[str, Any]],
                             ) -> Dict[str, Any]:
        job = self.meta.get_inference_job(job_id)
        if job is None:
            raise ValueError(f"unknown inference job {job_id}")
        self.check_access(claims, job["user_id"])
        return job

    # --- Models ---

    def create_model(self, user_id: str, name: str, task: str,
                     model_class: str, model_source: Optional[str] = None,
                     dependencies: Optional[Dict[str, str]] = None,
                     access_right: str = ModelAccessRight.PRIVATE,
                     ) -> Dict[str, Any]:
        # Resolve now: a model that doesn't import/declare knobs must be
        # rejected at upload, not at trial time.
        cls = load_model_class(model_class, model_source)
        knob_config = knob_config_to_json(cls.get_knob_config())
        row = self.meta.create_model(
            user_id, name, task, model_class, knob_config,
            model_source=model_source, dependencies=dependencies,
            access_right=access_right)
        return {"id": row["id"], "name": name, "task": task}

    def get_models(self, user_id: str,
                   task: Optional[str] = None) -> List[Dict[str, Any]]:
        return [_public_model(m) for m in self.meta.get_models(user_id, task)]

    # --- Datasets ---

    def create_dataset(self, user_id: str, name: str, task: str,
                       data: bytes, filename: str = "") -> Dict[str, Any]:
        """Store an uploaded dataset file (the browser/REST upload path)
        and return its row — ``path`` is what train-job forms submit as
        ``train/val_dataset_path``. Format validation stays with the
        model SDK loaders at train time (the dataset zip is
        task-specific); the upload only persists bytes."""
        import os
        import re

        if not self.datasets_dir:
            raise ValueError("this node has no datasets dir configured")
        if not data:
            raise ValueError("empty dataset upload")
        os.makedirs(self.datasets_dir, exist_ok=True)
        # The stored filename is server-generated; only the extension
        # survives from the client (sanitized), so an hostile filename
        # cannot traverse out of the datasets dir.
        ext = os.path.splitext(filename or "")[1]
        if not re.fullmatch(r"\.[A-Za-z0-9]{1,8}", ext or ""):
            ext = ".zip"
        safe = re.sub(r"[^A-Za-z0-9._-]", "_", name)[:48] or "dataset"
        import sqlite3
        import uuid

        # Bytes land on disk BEFORE the meta row commits: a failed write
        # (ENOSPC, permissions) must not leave a pathless row squatting
        # on the unique name with no delete API to recover it.
        path = os.path.join(self.datasets_dir,
                            f"{uuid.uuid4().hex[:12]}-{safe}{ext}")
        with open(path, "wb") as f:
            f.write(data)
        try:
            row = self.meta.create_dataset(user_id, name, task, path,
                                           len(data))
        except sqlite3.IntegrityError:
            os.unlink(path)
            # The dashboard defaults the name to the filename, so
            # re-uploads are routine — answer with a clear 400, not an
            # opaque constraint error.
            raise ValueError(
                f"you already have a dataset named {name!r}; pick "
                f"another name")
        return dict(row)

    def get_datasets(self, user_id: str,
                     task: Optional[str] = None) -> List[Dict[str, Any]]:
        return self.meta.get_datasets(user_id, task=task)

    # --- Services (dashboard log view) ---

    def _sees_all_services(self,
                           claims: Optional[Dict[str, Any]]) -> bool:
        return claims is None or claims.get("user_type") in (
            UserType.SUPERADMIN, UserType.ADMIN)

    def get_services(self, claims: Optional[Dict[str, Any]] = None,
                     ) -> List[Dict[str, Any]]:
        """Service rows, newest first (dashboard services table).
        Admins see the whole cluster; other users see only services
        working for THEIR jobs — another tenant's worker list (and the
        job structure it implies) is not theirs to read."""
        rows = self.meta.get_services()
        if not self._sees_all_services(claims):
            owned = self.meta.get_owned_service_ids(claims.get("user_id"))
            rows = [r for r in rows if r["id"] in owned]
        rows.sort(key=lambda r: r["created_at"], reverse=True)
        return [{k: r.get(k) for k in
                 ("id", "service_type", "status", "chips", "node_id",
                  "created_at", "stopped_at")} for r in rows]

    def get_service_logs(self, service_id: str, max_bytes: int = 65536,
                         claims: Optional[Dict[str, Any]] = None,
                         ) -> Dict[str, Any]:
        """Tail of one service's captured log (utils/service_logs).
        Same visibility rule as ``get_services``: logs carry trial
        knobs/scores/dataset paths, so only the owning user or an admin
        may read them."""
        from ..utils.service_logs import service_log_path, tail_log

        svc = self.meta.get_service(service_id)
        if svc is None:
            raise ValueError(f"unknown service {service_id}")
        if not self._sees_all_services(claims):
            owner = self.meta.get_service_owner(service_id)
            self.check_access(claims, owner or "")
        text = None
        if self.services.log_dir:
            text = tail_log(
                service_log_path(self.services.log_dir, service_id),
                max_bytes=max_bytes)
        return {"service_id": service_id, "status": svc["status"],
                "log": text,
                "captured": text is not None}

    # --- Train jobs (§3.1) ---

    def create_train_job(self, user_id: str, app: str, task: str,
                         model_ids: List[str], budget: Dict[str, Any],
                         train_dataset_path: str, val_dataset_path: str,
                         advisor_type: Optional[str] = None,
                         ) -> Dict[str, Any]:
        budget = normalize_budget(budget)
        budget.setdefault(BudgetOption.MODEL_TRIAL_COUNT, 5)
        if not model_ids:
            raise ValueError("model_ids must be non-empty")
        # Validate everything BEFORE inserting rows: a failed validation
        # must not leave an orphaned STARTED job burning the app-version.
        for model_id in model_ids:
            model = self.meta.get_model(model_id)
            if model is None:
                raise ValueError(f"unknown model {model_id}")
            if model["task"] != task:
                raise ValueError(
                    f"model {model['name']} is for task {model['task']}, "
                    f"not {task}")
        job = self.meta.create_train_job(
            user_id, app, task, budget, train_dataset_path,
            val_dataset_path, TrainJobStatus.STARTED)
        for model_id in model_ids:
            self.meta.create_sub_train_job(job["id"], model_id, "STARTED",
                                           advisor_type=advisor_type)
        self.services.create_train_services(job["id"])
        self.meta.update_train_job(job["id"], status=TrainJobStatus.RUNNING)
        return {"id": job["id"], "app": job["app"],
                "app_version": job["app_version"]}

    def get_train_job(self, train_job_id: str,
                      claims: Optional[Dict[str, Any]] = None,
                      ) -> Dict[str, Any]:
        job = self._owned_train_job(train_job_id, claims)
        self._refresh_train_job_status(job)
        job = self.meta.get_train_job(train_job_id)
        subs = []
        for sub in self.meta.get_sub_train_jobs(train_job_id):
            trials = self.meta.get_trials(sub["id"])
            subs.append({
                "id": sub["id"], "model_id": sub["model_id"],
                "n_trials": len(trials),
                "n_completed": sum(t["status"] == TrialStatus.COMPLETED
                                   for t in trials),
                "n_errored": sum(t["status"] == TrialStatus.ERRORED
                                 for t in trials),
            })
        return {"id": job["id"], "app": job["app"],
                "app_version": job["app_version"], "task": job["task"],
                "status": job["status"], "budget": job["budget"],
                "sub_train_jobs": subs}

    def _refresh_train_job_status(self, job: Dict[str, Any]) -> None:
        if job["status"] != TrainJobStatus.RUNNING:
            return
        if not self.services.train_services_active(job["id"]):
            # Budget exhausted and every worker wound down on its own:
            # tear the services down (releases their chip ranges).
            self.services.stop_train_services(job["id"])
            self.meta.update_train_job(job["id"],
                                       status=TrainJobStatus.STOPPED,
                                       stopped_at=time.time())

    def get_train_jobs(self, user_id: str) -> List[Dict[str, Any]]:
        return [{"id": j["id"], "app": j["app"],
                 "app_version": j["app_version"], "task": j["task"],
                 "status": j["status"]}
                for j in self.meta.get_train_jobs(user_id)]

    def stop_train_job(self, train_job_id: str,
                       claims: Optional[Dict[str, Any]] = None) -> None:
        self._owned_train_job(train_job_id, claims)
        self.services.stop_train_services(train_job_id)
        self.meta.update_train_job(train_job_id,
                                   status=TrainJobStatus.STOPPED,
                                   stopped_at=time.time())

    def get_best_trials(self, train_job_id: str, max_count: int = 2,
                        claims: Optional[Dict[str, Any]] = None,
                        ) -> List[Dict[str, Any]]:
        self._owned_train_job(train_job_id, claims)
        return [_public_trial(t) for t in
                self.meta.get_best_trials_of_train_job(train_job_id,
                                                       max_count)]

    def get_trials(self, train_job_id: str,
                   claims: Optional[Dict[str, Any]] = None,
                   ) -> List[Dict[str, Any]]:
        self._owned_train_job(train_job_id, claims)
        return [_public_trial(t) for t in
                self.meta.get_trials_of_train_job(train_job_id)]

    def get_trial_logs(self, trial_id: str,
                       claims: Optional[Dict[str, Any]] = None,
                       ) -> List[Dict[str, Any]]:
        trial = self.meta.get_trial(trial_id)
        if trial is None:
            raise ValueError(f"unknown trial {trial_id}")
        if claims is not None:
            sub = self.meta.get_sub_train_job(trial["sub_train_job_id"])
            self._owned_train_job(sub["train_job_id"], claims)
        return self.meta.get_trial_logs(trial_id)

    def wait_until_train_job_done(self, train_job_id: str,
                                  timeout: float = 3600.0,
                                  poll: float = 1.0) -> bool:
        """Block until every train worker stops; False on timeout."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not self.services.train_services_active(train_job_id):
                job = self.meta.get_train_job(train_job_id)
                self._refresh_train_job_status(job)
                return True
            time.sleep(poll)
        return False

    def attach_workers(self, train_job_id: str, chips_per_trial: int = 1,
                       ) -> List[Dict[str, Any]]:
        """Elastic scale-out (SURVEY.md §2.10 multi-host plan): attach
        one extra train worker per sub-job of a RUNNING job on THIS
        node's chips. Called on a secondary node sharing the meta store,
        params dir and bus (the ``join`` CLI); the new workers pull
        proposals from the job's existing bus-hosted advisor."""
        job = self.meta.get_train_job(train_job_id)
        if job is None:
            raise ValueError(f"unknown train job {train_job_id}")
        if job["status"] != TrainJobStatus.RUNNING:
            raise ValueError(f"train job {train_job_id} is not RUNNING")
        attached = []
        for sub in self.meta.get_sub_train_jobs(train_job_id):
            svc = self.services.add_train_worker(sub["id"], chips_per_trial)
            if svc is not None:
                attached.append(svc)
        return attached

    def attach_inference_workers(self, inference_job_id: str,
                                 chips_per_worker: int = 1,
                                 ) -> List[Dict[str, Any]]:
        """Elastic serving scale-out: attach one REPLICA worker per
        served trial bin of a RUNNING inference job on THIS node's
        chips (the ``join --inference-job`` path). The Predictor
        shards each super-batch across same-bin replicas
        (latency-weighted data parallelism), so QPS scales with
        unchanged ensemble semantics."""
        job = self.meta.get_inference_job(inference_job_id)
        if job is None:
            raise ValueError(f"unknown inference job {inference_job_id}")
        if job["status"] != InferenceJobStatus.RUNNING:
            raise ValueError(
                f"inference job {inference_job_id} is not RUNNING")
        from .services_manager import PREDICTOR_TRIAL

        bins = {w["trial_id"]
                for w in self.meta.get_inference_job_workers(
                    inference_job_id)
                if w["trial_id"] != PREDICTOR_TRIAL}
        attached = []
        for trial_id in sorted(bins):
            svc = self.services.add_inference_worker(
                inference_job_id, trial_id, chips_per_worker)
            if svc is not None:
                attached.append(svc)
        return attached

    # --- Inference jobs (§3.2) ---

    def create_inference_job(self, user_id: str, train_job_id: str,
                             max_models: int = 2,
                             chips_per_worker: int = 1,
                             claims: Optional[Dict[str, Any]] = None,
                             ) -> Dict[str, Any]:
        """``chips_per_worker > 1`` deploys each serving worker on a
        LARGER chip group — with a group spanning the node's slice,
        the whole best-N ensemble packs onto ONE worker (the compiled
        megabatch shape: stacked same-family bins serve as one vmapped
        dispatch over the full dp width; docs/serving.md)."""
        self._owned_train_job(train_job_id, claims)
        best = self.meta.get_best_trials_of_train_job(train_job_id,
                                                      max_models)
        if not best:
            raise ValueError(
                f"train job {train_job_id} has no completed trials")
        inf = self.meta.create_inference_job(user_id, train_job_id,
                                             InferenceJobStatus.STARTED)
        try:
            self.services.create_inference_services(
                inf["id"], [t["id"] for t in best],
                chips_per_worker=chips_per_worker)
        except Exception:
            self.meta.update_inference_job(inf["id"],
                                           status=InferenceJobStatus.ERRORED)
            raise
        self.meta.update_inference_job(inf["id"],
                                       status=InferenceJobStatus.RUNNING)
        return {"id": inf["id"], "train_job_id": train_job_id,
                "trial_ids": [t["id"] for t in best]}

    def get_inference_job(self, inference_job_id: str,
                          claims: Optional[Dict[str, Any]] = None,
                          ) -> Dict[str, Any]:
        return dict(self._owned_inference_job(inference_job_id, claims))

    def promote_trial(self, inference_job_id: str, trial_id: str,
                      replace_trial_id: Optional[str] = None,
                      register_timeout: float = 180.0,
                      claims: Optional[Dict[str, Any]] = None,
                      ) -> Dict[str, Any]:
        """Promote a trained trial into a RUNNING inference job's
        serving ensemble — the online half of train→serve, without a
        job restart.

        A worker for ``trial_id`` is launched and *waited for* (its bus
        registration is the moment the Predictor can plan shards onto
        it); only then are ``replace_trial_id``'s workers stopped (omit
        it for an additive promotion that grows the ensemble by one
        bin). Finally the predictor frontend's edge cache is
        invalidated — synchronously, BEFORE this call returns — so no
        request arriving after the promotion can be answered from a
        pre-promotion cache entry: the epoch bump also voids any
        still-in-flight pre-promotion scatter's insert. In-flight
        requests (including coalesced cache waiters) that scattered
        before the swap complete against the old ensemble, exactly like
        any request racing a deploy.

        Promotions are serialized node-wide (``_promote_lock``): the
        validate→launch→wait→swap sequence spans a registration wait,
        so a concurrent duplicate promote would otherwise pass the
        already-served check too and double-allocate.
        """
        with self._promote_lock:
            return self._promote_trial_locked(
                inference_job_id, trial_id, replace_trial_id,
                register_timeout, claims)

    def _promote_trial_locked(self, inference_job_id: str,
                              trial_id: str,
                              replace_trial_id: Optional[str],
                              register_timeout: float,
                              claims: Optional[Dict[str, Any]],
                              ) -> Dict[str, Any]:
        job = self._owned_inference_job(inference_job_id, claims)
        if job["status"] != InferenceJobStatus.RUNNING:
            raise ValueError(
                f"inference job {inference_job_id} is not RUNNING")
        trial = self.meta.get_trial(trial_id)
        if trial is None:
            raise ValueError(f"unknown trial {trial_id}")
        if trial["status"] != TrialStatus.COMPLETED or \
                not trial.get("params_id"):
            raise ValueError(
                f"trial {trial_id} is not COMPLETED with saved params")
        sub = self.meta.get_sub_train_job(trial["sub_train_job_id"])
        if sub is None or sub["train_job_id"] != job["train_job_id"]:
            raise ValueError(
                f"trial {trial_id} does not belong to train job "
                f"{job['train_job_id']}")
        rows = self.services.active_inference_workers(inference_job_id)
        served_bins = {w["trial_id"] for w in rows}
        if any(trial_id in str(b).split(",") for b in served_bins):
            raise ValueError(
                f"trial {trial_id} is already served by this job")
        old_rows: List[Dict[str, Any]] = []
        multi_rows: List[Dict[str, Any]] = []
        if replace_trial_id is not None:
            for w in rows:
                members = str(w["trial_id"]).split(",")
                if replace_trial_id not in members:
                    continue
                (multi_rows if len(members) > 1 else old_rows).append(w)
            if not old_rows and not multi_rows:
                raise ValueError(
                    f"trial {replace_trial_id} is not a served bin of "
                    f"this job")
            if multi_rows and old_rows:
                raise ValueError(
                    f"trial {replace_trial_id} is served both alone "
                    f"and inside a packed bin; promotion cannot "
                    f"target that mix")
        if multi_rows:
            # Surgical member replacement inside a packed bin — only
            # for workers that advertise ``stacked: true``: their
            # vmap-stacked weights swap ONE member's slices in place
            # (worker-side restack), the other members stay
            # device-resident, and no new worker launches. Per-member
            # runners cannot do this safely (the r12 refusal stands).
            # rta: disable=RTA105 deliberate (r12 rationale): holding _promote_lock across the restack wait is what serializes concurrent promotes of one trial; see promote_trial's docstring
            result = self._restack_packed_bins(
                job, trial_id, replace_trial_id, multi_rows,
                register_timeout)
            self._invalidate_predictor_cache(job)
            return result
        # Launch + wait-for-registration + teardown live in the
        # ServicesManager now (swap_inference_worker, the public
        # hot-swap seam): the new bin must be LIVE on the bus before
        # the old one stops, or the swap would drop the bin's vote —
        # and the incoming worker re-reads the serving env at load, so
        # e.g. int8 quant scales are recomputed for the promoted bin.
        # rta: disable=RTA105 deliberate (r12): holding _promote_lock across the registration wait IS the double-allocation fix; see promote_trial's docstring
        swap = self.services.swap_inference_worker(
            inference_job_id, trial_id,
            replace_service_ids=[w["service_id"] for w in old_rows],
            register_timeout=register_timeout)
        self._invalidate_predictor_cache(job)
        _log.info("promoted trial %s into inference job %s (replaced "
                  "%s; stopped %d worker(s))", trial_id,
                  inference_job_id, replace_trial_id,
                  len(swap["stopped_service_ids"]))
        return {"inference_job_id": inference_job_id,
                "promoted_trial_id": trial_id,
                "replaced_trial_id": replace_trial_id,
                "new_service_id": swap["new_service"]["id"],
                "stopped_service_ids": swap["stopped_service_ids"]}

    def _restack_packed_bins(self, job: Dict[str, Any],
                             trial_id: str, replace_trial_id: str,
                             multi_rows: List[Dict[str, Any]],
                             register_timeout: float,
                             ) -> Dict[str, Any]:
        """The stacked promote path: push a ``__restack__`` marker to
        every worker serving the packed bin, then WAIT for each
        worker's re-registration to show the new member (the worker
        re-registers only after the member's weights are swapped into
        the stacked device arrays — the moment the new bin serves).
        A worker whose restack fails (incongruent family, load error)
        keeps its old registration, so the poll times out and this
        raises — after converging the REST of the replicas back: any
        worker that already confirmed gets a reverse restack
        (new → old) so a multi-replica bin does not keep serving
        split-brain, and the predictor edge cache is invalidated
        best-effort (a still-queued marker on a backlogged worker may
        apply after this raises; the predictor's serving-vector
        self-check is the backstop for any answer cached across that
        late swap)."""
        import time as _time

        from ..cache import Cache as _BusCache

        inference_job_id = job["id"]
        cache = _BusCache(self.services.serving_bus())
        info = cache.running_worker_info(inference_job_id)
        not_stacked = [w["service_id"] for w in multi_rows
                       if not (info.get(w["service_id"]) or {})
                       .get("stacked")]
        if not_stacked:
            raise ValueError(
                f"bin {multi_rows[0]['trial_id']!r} packs several "
                f"trials and worker(s) "
                f"{[s[:8] for s in not_stacked]} serve it per-member; "
                f"promotion cannot surgically replace one member — "
                f"replace the whole bin (stacked workers restack in "
                f"place; see docs/serving.md)")
        for w in multi_rows:
            cache.send_restack(w["service_id"], replace_trial_id,
                               trial_id)
        deadline = _time.monotonic() + register_timeout
        pending = {w["service_id"] for w in multi_rows}
        confirmed: List[str] = []
        while pending:
            if _time.monotonic() >= deadline:
                self._rollback_restacks(cache, inference_job_id,
                                        confirmed, trial_id,
                                        replace_trial_id, job)
                raise RuntimeError(
                    f"worker(s) {[s[:8] for s in sorted(pending)]} did "
                    f"not confirm the restack within "
                    f"{register_timeout}s; confirmed replica(s) "
                    f"{[s[:8] for s in confirmed]} were rolled back "
                    f"(reverse restack) so the old member set keeps "
                    f"serving")
            info = cache.running_worker_info(inference_job_id)
            for sid in list(pending):
                members = str((info.get(sid) or {})
                              .get("trial_id", "")).split(",")
                if trial_id in members and \
                        replace_trial_id not in members:
                    pending.discard(sid)
                    confirmed.append(sid)
            if pending:
                # rta: disable=RTA102 deliberate (r12 rationale): the registration-confirm poll must complete under _promote_lock or a concurrent promote could double-target the bin mid-swap
                _time.sleep(0.1)
        _log.info("promoted trial %s into inference job %s by "
                  "restacking %d packed worker(s) (replaced %s in "
                  "place)", trial_id, inference_job_id,
                  len(multi_rows), replace_trial_id)
        return {"inference_job_id": inference_job_id,
                "promoted_trial_id": trial_id,
                "replaced_trial_id": replace_trial_id,
                "new_service_id": None,
                "restacked_service_ids": [w["service_id"]
                                          for w in multi_rows],
                "stopped_service_ids": []}

    def _rollback_restacks(self, cache, inference_job_id: str,
                           confirmed: List[str], trial_id: str,
                           replace_trial_id: str,
                           job: Dict[str, Any]) -> None:
        """Failure half of the surgical promote: reverse-restack every
        replica that already swapped (so the bin converges back to the
        OLD member set instead of serving split-brain) and invalidate
        the predictor edge cache — answers computed during the partial
        window must not outlive it. Both are best-effort: the promote
        is raising anyway, and the reverse marker rides the same
        queue-ordered mechanism as the forward one."""
        for sid in confirmed:
            try:
                cache.send_restack(sid, trial_id, replace_trial_id)
            except (ConnectionError, OSError, RuntimeError):
                _log.exception(
                    "reverse restack to %s failed; the replica keeps "
                    "the promoted member until the next promote",
                    sid[:8])
        if confirmed:
            try:
                self._invalidate_predictor_cache(job)
            except RuntimeError:
                _log.exception("edge-cache invalidation after a "
                               "partial restack failed")

    def _invalidate_predictor_cache(self, job: Dict[str, Any]) -> None:
        """Synchronous edge-cache invalidation on the job's predictor
        frontend — the promotion-correctness step. Failure raises: the
        ensemble already changed, and an unreachable frontend means
        cached pre-promotion answers could outlive the swap (the
        predictor's serving-vector cross-check would catch it on the
        next miss, but 'eventually' is not the promotion contract)."""
        import json as _json
        from urllib.request import Request, urlopen

        # Cluster fabric (docs/cluster.md): with several frontends the
        # job-row predictor_host names only the last-started one, so
        # the synchronous invalidate fans out to EVERY frontend in the
        # bus registry — each must acknowledge, or a peer could keep
        # serving (or re-exporting, via peer probes) pre-promotion
        # answers for its whole TTL. Single-node deploys have no
        # registry entries and keep the one-host path.
        hosts = []
        try:
            from ..cache import Cache as _BusCache

            hosts = sorted(_BusCache(self.services.serving_bus())
                           .frontends(job["id"]).values())
        except (ConnectionError, OSError, RuntimeError):
            _log.warning("frontend registry unreachable; falling back "
                         "to the job-row predictor host", exc_info=True)
        if not hosts:
            host = job.get("predictor_host")
            if not host:
                return  # no frontend deployed yet — nothing caches
            hosts = [host]
        for host in hosts:
            try:
                req = Request(f"http://{host}/cache/invalidate",
                              data=b"{}",
                              headers={"Content-Type":
                                       "application/json"},
                              method="POST")
                with urlopen(req, timeout=10) as resp:
                    _json.loads(resp.read())
            except OSError as e:
                raise RuntimeError(
                    f"promotion applied but the predictor at {host} "
                    f"did not acknowledge cache invalidation: {e}"
                ) from None

    def get_inference_job_stats(self, inference_job_id: str,
                                claims: Optional[Dict[str, Any]] = None,
                                ) -> Dict[str, Any]:
        """The job's predictor ``/stats`` snapshot, proxied server-side
        so the dashboard (same-origin against admin) can render queue
        depth / coalescing / per-stage latency without CORS and with
        the same ownership check every other job read gets."""
        import json as _json
        from urllib.request import urlopen

        job = self._owned_inference_job(inference_job_id, claims)
        host = job.get("predictor_host")
        if not host:
            raise ValueError(
                f"inference job {inference_job_id} has no predictor yet")
        try:
            with urlopen(f"http://{host}/stats", timeout=5) as resp:
                stats = _json.loads(resp.read())
        except OSError as e:
            raise ValueError(
                f"predictor at {host} unreachable: {e}") from None
        stats["inference_job_id"] = inference_job_id
        # Exemplars (when RAFIKI_TPU_METRICS_EXEMPLARS is on): the
        # frontend's /predict latency buckets each remember the last
        # traced observation, so the dashboard can link a p99 bucket
        # straight to its stitched GET /trace/<id> timeline. Resident-
        # runner visibility: the predictor shares this process's
        # registry; a subprocess frontend's exemplars ride its own
        # /metrics and this proxy simply reports none.
        from ..observe import metrics as obs_metrics

        hist = obs_metrics.registry().find(
            "rafiki_tpu_http_request_seconds")
        if hist is not None and stats.get("http_service"):
            stats["exemplars"] = hist.exemplars(
                service=stats["http_service"], route="/predict")
        return stats

    def profile_inference_job(self, inference_job_id: str,
                              duration_s: float = 5.0,
                              claims: Optional[Dict[str, Any]] = None,
                              ) -> Dict[str, Any]:
        """Trigger a bounded on-demand ``jax.profiler`` session on ONE
        live inference worker of the job (``POST
        /inference_jobs/<id>/profile``). The request travels as a
        queue-ordered ``__profile__`` control frame — exactly the
        drain/restack mechanism — so the worker starts the session
        between bursts and its serve loop stops it at the deadline:
        serving is never paused, the profile just observes the bursts
        that run inside its window. The artifact lands under the
        service log dir (``profiles/<job>/<ts>``, TensorBoard's
        profile plugin reads it); a worker whose profiler is busy (a
        trial trace in flight) skips the request, which the caller
        sees as an empty artifact dir."""
        import os as _os
        import uuid as _uuid

        from ..cache import Cache
        from ..observe.profiling import PROFILE_MAX_S

        job = self._owned_inference_job(inference_job_id, claims)
        if job["status"] != InferenceJobStatus.RUNNING:
            raise ValueError(
                f"inference job {inference_job_id} is not RUNNING")
        try:
            duration_s = float(duration_s)
        except (TypeError, ValueError):
            raise ValueError(f"duration_s {duration_s!r} is not a "
                             f"number") from None
        # Bounded by contract: the profiler holds device buffers and a
        # process-wide lock, so an abusive duration must clamp, not
        # honor.
        duration_s = min(max(0.5, duration_s), PROFILE_MAX_S)
        rows = self.services.active_inference_workers(inference_job_id)
        if not rows:
            raise ValueError(
                f"inference job {inference_job_id} has no active "
                f"workers to profile")
        target = rows[0]["service_id"]
        base = self.services.log_dir
        if not base:  # log capture disabled; still give the artifact
            import tempfile as _tempfile  # a well-known place to land

            base = _os.path.join(_tempfile.gettempdir(),
                                 "rafiki_tpu_profiles")
        out_dir = _os.path.join(
            base, "profiles", inference_job_id[:8],
            f"{int(time.time())}-{_uuid.uuid4().hex[:6]}")
        Cache(self.services.serving_bus()).send_profile(
            target, out_dir, duration_s)
        _log.info("profile session queued on worker %s of job %s "
                  "(%.1fs into %s)", target[:8], inference_job_id[:8],
                  duration_s, out_dir)
        return {"inference_job_id": inference_job_id,
                "service_id": target,
                "duration_s": duration_s,
                "profile_dir": out_dir}

    def get_trace(self, trace_id: str,
                  claims: Optional[Dict[str, Any]] = None,
                  ) -> Dict[str, Any]:
        """Stitch one trace's span events (collected from the service
        log dir's ``spans.jsonl``) into an ordered timeline — the
        answer to "why was this /predict slow" as one call."""
        # Spans carry timing + service/trial ids only; visible to any
        # authenticated user (the trace id itself is an unguessable
        # 128-bit capability handed to the caller that issued the
        # traced request).
        from ..observe import trace as trace_mod

        log_dir = self.services.log_dir
        if not log_dir:
            return {"trace_id": trace_id, "n_spans": 0, "spans": []}
        return trace_mod.collect_trace(log_dir, trace_id)

    def get_trial_phases(self) -> Dict[str, Any]:
        """Cumulative trial-lifecycle phase breakdown + residency-cache
        counters for the dashboard's trial view. Same visibility caveat
        as the /status MFU gauge: resident-runner mode puts the workers
        in THIS process so the registry has the series; subprocess
        workers publish the same families on their own /metrics, which
        this endpoint cannot see — ``resident`` says which case this is
        so the UI can label an all-zero table honestly."""
        from ..observe import metrics as obs_metrics
        from ..observe import phases as obs_phases

        totals = obs_phases.phase_totals()
        resident = any(v["count"] for v in totals.values())
        phases = {
            p: {"count": int(v["count"]),
                "total_s": round(v["sum"], 3),
                "mean_ms": round(v["sum"] / v["count"] * 1e3, 1)
                if v["count"] else 0.0}
            for p, v in totals.items()}
        caches = {c: obs_phases.cache_counts(c)
                  for c in ("dataset", "stage")}
        return {"enabled": obs_metrics.metrics_enabled(),
                "resident": resident, "phases": phases,
                "caches": caches}

    def get_autoscale(self) -> Dict[str, Any]:
        """The autoscaler's decision ring + per-bin targets (the
        ``GET /autoscale`` body; docs/autoscaling.md). Disabled nodes
        answer ``enabled: false`` — the dashboard renders the panel
        only when the loop is actually closed."""
        scaler = getattr(self.services, "autoscaler", None)
        if scaler is None:
            return {"enabled": False}
        return scaler.snapshot()

    def get_nodes(self) -> Dict[str, Any]:
        """The cluster node registry snapshot (the ``GET /nodes``
        body; docs/cluster.md). Single-node deployments answer
        ``enabled: false`` — the fabric is opt-in and the dashboard
        renders the cluster view only when a registry exists."""
        registry = getattr(self.services, "node_registry", None)
        if registry is None:
            return {"enabled": False}
        return registry.snapshot()

    def get_slo(self) -> Dict[str, Any]:
        """The SLO engine's objective/instance snapshot (the
        ``GET /slo`` body; docs/observability.md "SLOs & alerting").
        Disabled nodes answer ``enabled: false`` — the dashboard
        renders the panel only when the plane is armed."""
        engine = getattr(self.services, "slo_engine", None)
        if engine is None:
            return {"enabled": False}
        return engine.snapshot()

    def get_alerts(self) -> Dict[str, Any]:
        """The SLO engine's alert-transition ring (``GET /alerts``),
        newest first; ``enabled: false`` on unarmed nodes."""
        engine = getattr(self.services, "slo_engine", None)
        if engine is None:
            return {"enabled": False}
        return engine.alerts_snapshot()

    def get_capacity(self) -> Dict[str, Any]:
        """The capacity engine's snapshot (``GET /capacity``;
        docs/capacity.md): the node's recorded-workload inventory plus
        a canned-ramp policy-gate run of the policy this node would
        apply. Always enabled — the gate needs no live traffic, only
        the simulator."""
        from . import capacity as capacity_mod

        return capacity_mod.admin_snapshot(self.services)

    def get_inference_jobs(self, user_id: str) -> List[Dict[str, Any]]:
        return [dict(j) for j in self.meta.get_inference_jobs(user_id)]

    def get_status(self) -> Dict[str, Any]:
        """Node status for operators: chip allocation, live services,
        and — with several nodes sharing this meta store — a per-node
        cluster view (service counts + heartbeat age, so a stalled
        join node is visible before its lease expires)."""
        alloc = self.services.allocator
        running = self.meta.get_services(status="RUNNING")
        by_type: Dict[str, int] = {}
        nodes: Dict[str, Dict[str, Any]] = {}
        now = time.time()
        this_node = self.services.node_id
        for s in running:
            by_type[s["service_type"]] = by_type.get(s["service_type"],
                                                     0) + 1
            # NULL node_id rows (pre-upgrade databases) attribute to
            # whoever adopted them — the same ownership rule the
            # supervisor applies — so a one-node cluster never renders
            # a phantom "(unowned)" second node.
            own = self.services._ownership(s)
            nid = this_node if own == "local" else (
                s.get("node_id") or "(unowned)")
            node = nodes.setdefault(nid, {"services": 0,
                                          "heartbeat_age_s": None})
            node["services"] += 1
            hb = self.services.last_heartbeat(s)
            if hb:
                age = round(max(0.0, now - hb), 1)
                if node["heartbeat_age_s"] is None \
                        or age < node["heartbeat_age_s"]:
                    node["heartbeat_age_s"] = age
        nodes.setdefault(this_node, {"services": 0,
                                     "heartbeat_age_s": 0.0})
        # Per-trial chip utilization: the train loop publishes an MFU
        # gauge into the process registry (resident-runner mode puts
        # the workers in THIS process; subprocess workers expose the
        # same series on their own /metrics).
        from ..observe import metrics as obs_metrics

        mfu: Dict[str, float] = {}
        gauge = obs_metrics.registry().find("rafiki_tpu_train_mfu_ratio")
        if gauge is not None:
            for labels, value in gauge.samples():
                mfu[labels.get("trial", "(unlabeled)")] = round(value, 4)
        out = {
            "n_chips": alloc.n_chips,
            "free_chips": alloc.free_chips,
            "chip_allocation": round(alloc.utilization(), 4),
            "services_running": by_type,
            "node_id": this_node,
            "nodes": nodes,
            "mfu": mfu,
        }
        # Cluster fabric fold (docs/cluster.md): the meta-derived node
        # view above only sees nodes with RUNNING services; the
        # registry also counts idle-but-live peers, so operators see a
        # joined-but-empty node here before it serves anything.
        registry = getattr(self.services, "node_registry", None)
        if registry is not None:
            try:
                out["cluster"] = registry.health()
            except (ConnectionError, OSError, RuntimeError):
                out["cluster"] = {"fabric": True, "error": "registry "
                                  "unreachable"}
        return out

    # --- User administration (ADMIN-only; enforced by the REST layer) ---

    def get_users(self) -> List[Dict[str, Any]]:
        return [{"id": u["id"], "email": u["email"],
                 "user_type": u["user_type"],
                 "banned": u["banned_at"] is not None}
                for u in self.meta.get_users()]

    def ban_user(self, user_id: str,
                 claims: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        target = self.meta.get_user(user_id)
        if target is None:
            raise ValueError(f"unknown user {user_id}")
        # The root account must stay recoverable (there is no unban
        # route), and self-bans lock out the very session issuing them.
        if target["user_type"] == UserType.SUPERADMIN:
            raise PermissionError("the superadmin cannot be banned")
        if claims is not None and claims.get("user_id") == user_id:
            raise PermissionError("cannot ban yourself")
        self.meta.ban_user(user_id)
        return {"banned": user_id}

    def stop_inference_job(self, inference_job_id: str,
                           claims: Optional[Dict[str, Any]] = None) -> None:
        self._owned_inference_job(inference_job_id, claims)
        self.services.stop_inference_services(inference_job_id)
        self.meta.update_inference_job(inference_job_id,
                                       status=InferenceJobStatus.STOPPED,
                                       stopped_at=time.time())


def _public_model(m: Dict[str, Any]) -> Dict[str, Any]:
    return {"id": m["id"], "name": m["name"], "task": m["task"],
            "model_class": m["model_class"],
            "access_right": m["access_right"]}


def _public_trial(t: Dict[str, Any]) -> Dict[str, Any]:
    return {"id": t["id"], "no": t["no"], "score": t["score"],
            "knobs": t["knobs"], "status": t["status"],
            "params_id": t["params_id"]}
