"""Admin: the control plane.

Parity: SURVEY.md §2 "Admin" + "ServicesManager / GPU scheduler"
(upstream ``rafiki/admin/``). The REST frontend lives in
``rafiki_tpu.admin.app``; orchestration in ``Admin``; service sizing and
chip allocation in ``ServicesManager``.
"""

from .admin import Admin
from .services_manager import ServicesManager

__all__ = ["Admin", "ServicesManager"]
