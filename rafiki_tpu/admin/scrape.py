"""Shared predictor-endpoint scraping for the supervise-cadence
consumers (the autoscaler and the SLO engine).

Both control planes judge each RUNNING inference job from its
predictor's own ``/stats`` + ``/metrics`` over HTTP. With both armed
on one node they ride the SAME supervise pass, so fetching (and
parsing) each endpoint twice per sweep would double the work — and
double how long an unreachable frontend's timeout can stall the
supervise thread. ``ServicesManager.supervise`` hands one
:class:`ScrapeCache` to both sweeps; each endpoint is fetched at most
once per sweep, failures included (a dead host costs ONE timeout per
sweep, not one per consumer).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Tuple


def fetch_endpoint(host: str, path: str, timeout: float = 5.0) -> Any:
    """One predictor endpoint fetch: ``/metrics`` returns the raw
    exposition text, anything else parses as JSON. The ONE fetch
    implementation both control planes use (a fix applied here cannot
    silently miss one of them)."""
    from urllib.request import urlopen

    with urlopen(f"http://{host}{path}", timeout=timeout) as resp:
        body = resp.read()
    if path == "/metrics":
        return body.decode()
    return json.loads(body)


def worker_metrics_addrs(services, job_id: str) -> List[str]:
    """Advertised worker ``/metrics`` addresses for one inference job,
    flattened across nodes (see :func:`worker_scrape_targets`)."""
    by_node, _ = worker_scrape_targets(services, job_id)
    return sorted({a for addrs in by_node.values() for a in addrs})


def worker_scrape_targets(services, job_id: str
                          ) -> Tuple[Dict[str, List[str]], int]:
    """``(node -> advertised worker /metrics addrs, silent)`` for one
    inference job, from the bus worker registry (``metrics`` +
    ``node`` keys in each registration — worker/inference.py). The
    node grouping is the cluster aggregator's unit: the admin merges
    each node's worker registries so a whole-node scrape hole is
    attributable, not just "some worker missing".

    ``silent`` counts registered workers that advertise NO metrics
    endpoint. Resident-runner workers are silent BY DESIGN (their
    series live in the admin process's shared registry), so silent
    alone is not a failure — but under subprocess/docker runners it is
    exactly the population whose bin-scoped series the SLO plane
    cannot see, and the engine publishes it as a coverage ratio
    instead of silently reading "no data = healthy".

    Best-effort — a bus hiccup degrades to "no worker scrape this
    sweep", never into the supervise thread."""
    try:
        bus = services.serving_bus()
        prefix = f"w:{job_id}:"
        by_node: Dict[str, set] = {}
        silent = 0
        for k in bus.keys(prefix):
            info = bus.get(k) or {}
            addr = str(info.get("metrics") or "")
            if not addr:
                silent += 1
                continue
            node = str(info.get("node") or "")
            by_node.setdefault(node, set()).add(addr)
        return ({n: sorted(a) for n, a in sorted(by_node.items())},
                silent)
    except Exception:
        return ({}, 0)


def merge_worker_expositions(fetch, by_node: Dict[str, List[str]]
                             ) -> Tuple[str, int, int]:
    """Concatenate every advertised worker exposition across all
    nodes; returns ``(text, fetched, failed)``. The concatenation is
    safe because frontend- and worker-owned families never share a
    name+label set. A fetch failure skips that worker (a dead worker
    must not blind the whole job) but is COUNTED — the caller turns
    the tally into a coverage signal."""
    parts: List[str] = []
    fetched = failed = 0
    for addrs in by_node.values():
        for addr in addrs:
            try:
                parts.append(fetch(addr, "/metrics"))
                fetched += 1
            except (OSError, ValueError):
                failed += 1
    return ("\n".join(parts), fetched, failed)


class ScrapeCache:
    """Per-SWEEP memo over :func:`fetch_endpoint`. Exceptions are
    memoized too and re-raised to every consumer — each consumer keeps
    its own skip-this-job-this-sweep semantics, but the blocked socket
    wait is paid once. Built fresh each supervise pass (staleness
    within one sweep is the point: both consumers judge the same
    snapshot); single-threaded by construction — everything runs on
    the supervise thread."""

    def __init__(self, timeout: float = 5.0):
        self.timeout = timeout
        self._memo: Dict[Tuple[str, str], Tuple[bool, Any]] = {}

    def fetch(self, host: str, path: str) -> Any:
        key = (host, path)
        hit = self._memo.get(key)
        if hit is None:
            try:
                hit = (True, fetch_endpoint(host, path,
                                            timeout=self.timeout))
            except (OSError, ValueError) as e:
                hit = (False, e)
            self._memo[key] = hit
        ok, value = hit
        if not ok:
            raise value
        return value
