"""Shared predictor-endpoint scraping for the supervise-cadence
consumers (the autoscaler and the SLO engine).

Both control planes judge each RUNNING inference job from its
predictor's own ``/stats`` + ``/metrics`` over HTTP. With both armed
on one node they ride the SAME supervise pass, so fetching (and
parsing) each endpoint twice per sweep would double the work — and
double how long an unreachable frontend's timeout can stall the
supervise thread. ``ServicesManager.supervise`` hands one
:class:`ScrapeCache` to both sweeps; each endpoint is fetched at most
once per sweep, failures included (a dead host costs ONE timeout per
sweep, not one per consumer).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Tuple


def fetch_endpoint(host: str, path: str, timeout: float = 5.0) -> Any:
    """One predictor endpoint fetch: ``/metrics`` returns the raw
    exposition text, anything else parses as JSON. The ONE fetch
    implementation both control planes use (a fix applied here cannot
    silently miss one of them)."""
    from urllib.request import urlopen

    with urlopen(f"http://{host}{path}", timeout=timeout) as resp:
        body = resp.read()
    if path == "/metrics":
        return body.decode()
    return json.loads(body)


def worker_metrics_addrs(services, job_id: str) -> List[str]:
    """Advertised worker ``/metrics`` addresses for one inference job,
    from the bus worker registry's ``metrics`` key (set by subprocess/
    docker entrypoints after they bind a metrics server —
    container/services.py). Resident-runner workers advertise nothing:
    their series already live in the admin process's shared registry.
    Best-effort — a bus hiccup degrades to "no worker scrape this
    sweep", never into the supervise thread."""
    try:
        bus = services.serving_bus()
        prefix = f"w:{job_id}:"
        addrs = {str((bus.get(k) or {}).get("metrics") or "")
                 for k in bus.keys(prefix)}
        return sorted(a for a in addrs if a)
    except Exception:
        return []


class ScrapeCache:
    """Per-SWEEP memo over :func:`fetch_endpoint`. Exceptions are
    memoized too and re-raised to every consumer — each consumer keeps
    its own skip-this-job-this-sweep semantics, but the blocked socket
    wait is paid once. Built fresh each supervise pass (staleness
    within one sweep is the point: both consumers judge the same
    snapshot); single-threaded by construction — everything runs on
    the supervise thread."""

    def __init__(self, timeout: float = 5.0):
        self.timeout = timeout
        self._memo: Dict[Tuple[str, str], Tuple[bool, Any]] = {}

    def fetch(self, host: str, path: str) -> Any:
        key = (host, path)
        hit = self._memo.get(key)
        if hit is None:
            try:
                hit = (True, fetch_endpoint(host, path,
                                            timeout=self.timeout))
            except (OSError, ValueError) as e:
                hit = (False, e)
            self._memo[key] = hit
        ok, value = hit
        if not ok:
            raise value
        return value
