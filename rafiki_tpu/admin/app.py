"""Admin REST API: the upstream-shaped HTTP surface over ``Admin``.

Parity: SURVEY.md §2 "Admin" (upstream Flask ``app.py`` routes). Kept
route-compatible so reference quickstart scripts port 1:1:

- ``GET  /``                         web dashboard (SURVEY.md §2 "Web UI")
- ``POST /tokens``                   login → ``{user_id, user_type, token}``
- ``POST /users``                    (admin) create user
- ``POST /models``                   register model (source or class path)
- ``GET  /models``                   list visible models
- ``POST /train_jobs``               create train job
- ``GET  /train_jobs``               list own train jobs
- ``GET  /train_jobs/<id>``          job detail + per-model progress
- ``POST /train_jobs/<id>/stop``     stop workers
- ``GET  /train_jobs/<id>/trials``   ``?type=best&max_count=k`` or all
- ``GET  /trials/<id>/logs``         TrialLog rows
- ``POST /inference_jobs``           deploy best trials behind a predictor
- ``GET  /inference_jobs/<id>``      incl. ``predictor_host``
- ``GET  /inference_jobs/<id>/stats``  predictor serving stats (proxied
                                     server-side for the dashboard)
- ``POST /inference_jobs/<id>/stop``
- ``POST /inference_jobs/<id>/promote``  hot-swap a trained trial into
                                     the serving ensemble (``trial_id``,
                                     optional ``replace_trial_id``);
                                     invalidates the predictor edge
                                     cache before returning
- ``POST /inference_jobs/<id>/profile``  bounded on-demand
                                     ``jax.profiler`` session on a live
                                     worker (``duration_s``; serving
                                     never pauses — docs/observability)
- ``GET  /trace/<trace_id>``         stitched span timeline of one trace
- ``GET  /autoscale``                autoscaler decision ring + per-bin
                                     replica targets (``enabled: false``
                                     on nodes without the control loop;
                                     see docs/autoscaling.md)
- ``GET  /nodes``                    cluster node registry: per-node
                                     identity, chip inventory, broker
                                     URI, heartbeat age (``enabled:
                                     false`` without the cluster
                                     fabric; see docs/cluster.md)
- ``GET  /slo``                      SLO objectives with live burn
                                     rates / error budgets per instance
                                     (``enabled: false`` when no
                                     ``RAFIKI_TPU_SLO_RULES``; see
                                     docs/observability.md)
- ``GET  /alerts``                   burn-rate alert transition ring
                                     (newest first) + currently firing
                                     objectives
- ``GET  /capacity``                 recorded-workload inventory + a
                                     canned-ramp policy-gate simulation
                                     of this node's autoscale policy
                                     (docs/capacity.md)
- ``GET  /trial_phases``             trial-lifecycle phase breakdown +
                                     residency-cache counters (resident
                                     workers only; see docs/training.md)
- ``GET  /metrics``                  Prometheus exposition (auto-wired
                                     by ``JsonHttpServer``; no auth,
                                     like any scrape endpoint)
- ``POST /datasets``                 upload a dataset file (raw bytes body,
                                     ``?name=&task=&filename=``)
- ``GET  /datasets``                 list own uploaded datasets
- ``GET  /services``                 cluster service rows
- ``GET  /services/<id>/logs``       tail one service's captured log

Auth: ``Authorization: Bearer <jwt>`` on everything but ``POST /tokens``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..constants import UserType
from ..utils.service import HttpError, JsonHttpServer
from .admin import Admin

_WRITE_TYPES = {UserType.SUPERADMIN, UserType.ADMIN,
                UserType.MODEL_DEVELOPER, UserType.APP_DEVELOPER}


class AdminApp:
    def __init__(self, admin: Admin, host: str = "0.0.0.0", port: int = 0):
        self.admin = admin
        self._http = JsonHttpServer([
            # rta: disable=RTA702 the dashboard page is fetched by browsers, not by in-tree code
            ("GET", "/", self._dashboard),
            ("POST", "/tokens", self._login),
            ("POST", "/users", self._create_user),
            ("POST", "/models", self._create_model),
            ("GET", "/models", self._list_models),
            ("POST", "/train_jobs", self._create_train_job),
            ("GET", "/train_jobs", self._list_train_jobs),
            ("GET", "/train_jobs/<job_id>", self._get_train_job),
            ("POST", "/train_jobs/<job_id>/stop", self._stop_train_job),
            ("GET", "/train_jobs/<job_id>/trials", self._get_trials),
            ("GET", "/trials/<trial_id>/logs", self._get_trial_logs),
            ("POST", "/inference_jobs", self._create_inference_job),
            ("GET", "/inference_jobs", self._list_inference_jobs),
            ("GET", "/inference_jobs/<job_id>", self._get_inference_job),
            ("GET", "/inference_jobs/<job_id>/stats",
             self._inference_job_stats),
            ("POST", "/inference_jobs/<job_id>/stop",
             self._stop_inference_job),
            # rta: disable=RTA702 operator-only control surface (curl/runbooks); no SDK wrapper yet by design
            ("POST", "/inference_jobs/<job_id>/promote",
             self._promote_trial),
            # rta: disable=RTA702 operator-only profiling trigger (docs/profiling.md runbook), driven by curl
            ("POST", "/inference_jobs/<job_id>/profile",
             self._profile_inference_job),
            ("GET", "/trace/<trace_id>", self._get_trace),
            ("GET", "/users", self._list_users),
            ("POST", "/users/<user_id>/ban", self._ban_user),
            ("GET", "/status", self._status),
            # rta: disable=RTA702 operator surface for the cluster fabric (flag-gated); browsers/curl only
            ("GET", "/nodes", self._nodes),
            ("GET", "/trial_phases", self._trial_phases),
            ("GET", "/autoscale", self._autoscale),
            ("GET", "/slo", self._slo),
            ("GET", "/alerts", self._alerts),
            ("GET", "/capacity", self._capacity),
            ("POST", "/datasets", self._create_dataset),
            ("GET", "/datasets", self._list_datasets),
            ("GET", "/services", self._list_services),
            ("GET", "/services/<service_id>/logs", self._service_logs),
        ], host=host, port=port, name="admin")
        self.host = self._http.host
        self.port = self._http.port

    def start(self) -> "AdminApp":
        self._http.start()
        return self

    def stop(self) -> None:
        self._http.stop()

    # --- Auth helpers ---

    def _auth(self, ctx, *allowed: str) -> Dict[str, Any]:
        token = ctx.bearer_token
        if token is None:
            raise HttpError(401, "missing bearer token")
        claims = self.admin.authorize(token)
        if allowed and claims["user_type"] not in allowed:
            raise HttpError(403,
                            f"requires one of {sorted(allowed)}")
        return claims

    @staticmethod
    def _need(body: Optional[Dict[str, Any]], *keys: str) -> Dict[str, Any]:
        if body is None:
            raise HttpError(400, "missing JSON body")
        missing = [k for k in keys if k not in body]
        if missing:
            raise HttpError(400, f"missing fields: {missing}")
        return body

    # --- Routes ---

    def _dashboard(self, params, body, ctx):
        from ..utils.service import RawResponse
        from ..web import dashboard_html
        return 200, RawResponse("text/html; charset=utf-8",
                                dashboard_html())

    def _list_train_jobs(self, params, body, ctx):
        claims = self._auth(ctx)
        return 200, self.admin.get_train_jobs(claims["user_id"])

    def _login(self, params, body, ctx):
        body = self._need(body, "email", "password")
        return 200, self.admin.authenticate(body["email"], body["password"])

    def _create_user(self, params, body, ctx):
        self._auth(ctx, UserType.SUPERADMIN, UserType.ADMIN)
        body = self._need(body, "email", "password", "user_type")
        return 201, self.admin.create_user(body["email"], body["password"],
                                           body["user_type"])

    def _create_model(self, params, body, ctx):
        claims = self._auth(ctx, UserType.SUPERADMIN, UserType.ADMIN,
                            UserType.MODEL_DEVELOPER)
        body = self._need(body, "name", "task", "model_class")
        return 201, self.admin.create_model(
            claims["user_id"], body["name"], body["task"],
            body["model_class"], model_source=body.get("model_source"),
            dependencies=body.get("dependencies"),
            access_right=body.get("access_right", "PRIVATE"))

    def _list_models(self, params, body, ctx):
        claims = self._auth(ctx)
        return 200, self.admin.get_models(claims["user_id"],
                                          task=ctx.query_one("task"))

    def _create_train_job(self, params, body, ctx):
        claims = self._auth(ctx)
        body = self._need(body, "app", "task", "model_ids",
                          "train_dataset_path", "val_dataset_path")
        return 201, self.admin.create_train_job(
            claims["user_id"], body["app"], body["task"], body["model_ids"],
            body.get("budget", {}), body["train_dataset_path"],
            body["val_dataset_path"],
            advisor_type=body.get("advisor_type"))

    def _get_train_job(self, params, body, ctx):
        claims = self._auth(ctx)
        return 200, self.admin.get_train_job(params["job_id"], claims=claims)

    def _stop_train_job(self, params, body, ctx):
        claims = self._auth(ctx)
        self.admin.stop_train_job(params["job_id"], claims=claims)
        return 200, {"stopped": params["job_id"]}

    def _get_trials(self, params, body, ctx):
        claims = self._auth(ctx)
        if ctx.query_one("type") == "best":
            max_count = int(ctx.query_one("max_count", "2"))
            return 200, self.admin.get_best_trials(params["job_id"],
                                                   max_count, claims=claims)
        return 200, self.admin.get_trials(params["job_id"], claims=claims)

    def _get_trial_logs(self, params, body, ctx):
        claims = self._auth(ctx)
        return 200, self.admin.get_trial_logs(params["trial_id"],
                                              claims=claims)

    def _create_inference_job(self, params, body, ctx):
        claims = self._auth(ctx)
        body = self._need(body, "train_job_id")
        return 201, self.admin.create_inference_job(
            claims["user_id"], body["train_job_id"],
            max_models=int(body.get("max_models", 2)), claims=claims)

    def _get_inference_job(self, params, body, ctx):
        claims = self._auth(ctx)
        return 200, self.admin.get_inference_job(params["job_id"],
                                                 claims=claims)

    def _stop_inference_job(self, params, body, ctx):
        claims = self._auth(ctx)
        self.admin.stop_inference_job(params["job_id"], claims=claims)
        return 200, {"stopped": params["job_id"]}

    def _promote_trial(self, params, body, ctx):
        claims = self._auth(ctx)
        body = self._need(body, "trial_id")
        return 200, self.admin.promote_trial(
            params["job_id"], body["trial_id"],
            replace_trial_id=body.get("replace_trial_id"),
            claims=claims)

    def _list_inference_jobs(self, params, body, ctx):
        claims = self._auth(ctx)
        return 200, self.admin.get_inference_jobs(claims["user_id"])

    def _inference_job_stats(self, params, body, ctx):
        claims = self._auth(ctx)
        return 200, self.admin.get_inference_job_stats(params["job_id"],
                                                       claims=claims)

    def _profile_inference_job(self, params, body, ctx):
        claims = self._auth(ctx)
        duration = (body or {}).get("duration_s", 5.0)
        return 200, self.admin.profile_inference_job(
            params["job_id"], duration_s=duration, claims=claims)

    def _get_trace(self, params, body, ctx):
        self._auth(ctx)
        return 200, self.admin.get_trace(params["trace_id"])

    def _status(self, params, body, ctx):
        self._auth(ctx)
        return 200, self.admin.get_status()

    def _trial_phases(self, params, body, ctx):
        self._auth(ctx)
        return 200, self.admin.get_trial_phases()

    def _autoscale(self, params, body, ctx):
        self._auth(ctx)
        return 200, self.admin.get_autoscale()

    def _nodes(self, params, body, ctx):
        self._auth(ctx)
        return 200, self.admin.get_nodes()

    def _slo(self, params, body, ctx):
        self._auth(ctx)
        return 200, self.admin.get_slo()

    def _alerts(self, params, body, ctx):
        self._auth(ctx)
        return 200, self.admin.get_alerts()

    def _capacity(self, params, body, ctx):
        self._auth(ctx)
        return 200, self.admin.get_capacity()

    def _create_dataset(self, params, body, ctx):
        claims = self._auth(ctx, *_WRITE_TYPES)
        # The file travels as the raw request body (the browser posts
        # the File object directly; the client SDK streams the file) —
        # no multipart parser needed in a first-party server. Metadata
        # rides the query string.
        name = ctx.query_one("name")
        task = ctx.query_one("task")
        if not name or not task:
            raise HttpError(400, "need ?name= and ?task= query params")
        if ctx.raw_body is None:
            raise HttpError(
                400, "dataset bytes must be the request body with a "
                     "non-JSON Content-Type (application/octet-stream)")
        return 201, self.admin.create_dataset(
            claims["user_id"], name, task, ctx.raw_body,
            filename=ctx.query_one("filename", ""))

    def _list_datasets(self, params, body, ctx):
        claims = self._auth(ctx)
        return 200, self.admin.get_datasets(claims["user_id"],
                                            task=ctx.query_one("task"))

    def _list_services(self, params, body, ctx):
        claims = self._auth(ctx)
        return 200, self.admin.get_services(claims=claims)

    def _service_logs(self, params, body, ctx):
        claims = self._auth(ctx)
        max_bytes = int(ctx.query_one("max_bytes", "65536"))
        return 200, self.admin.get_service_logs(params["service_id"],
                                                max_bytes=max_bytes,
                                                claims=claims)

    def _list_users(self, params, body, ctx):
        self._auth(ctx, UserType.SUPERADMIN, UserType.ADMIN)
        return 200, self.admin.get_users()

    def _ban_user(self, params, body, ctx):
        claims = self._auth(ctx, UserType.SUPERADMIN, UserType.ADMIN)
        return 200, self.admin.ban_user(params["user_id"], claims=claims)
