"""Metrics-driven autoscaler: the serving control loop, closed.

The r7 metrics plane measures per-bin qps, p99, backpressure{reason},
queue depth and train MFU; until now nobody acted on any of it — the
paper's Admin/ServicesManager allocates accelerators once, at deploy
time (PAPER.md §1 "ServicesManager deploys worker services, allocates
GPUs"), and a traffic ramp after that is the operator's problem. This
module is the missing actuator: a deterministic control loop on the
supervise cadence that

1. **reads** load signals from each RUNNING inference job's predictor
   ``/metrics`` (request-rate deltas, admission-queue depth,
   backpressure counters, the ``/predict`` latency histogram — parsed
   with the same ``parse_exposition``/``bucket_percentile`` the bench
   uses, so the controller sees exactly what production scrapes) plus
   the in-process registry's ``rafiki_tpu_train_mfu_ratio`` gauges
   (the idle-training signal);
2. **decides** per-bin replica targets through :class:`AutoscalePolicy`
   — a pure decision table with a hysteresis band (no action between
   the low and high water marks, so an oscillating load inside the
   band never flaps), per-sweep step bounds, and asymmetric cooldowns
   (scale up in seconds, scale down only after a long quiet spell);
3. **actuates** through the seams earlier PRs already cut:
   ``ServicesManager.add_inference_worker`` (time-sliced chips via
   ``RAFIKI_TPU_MAX_CHIP_SHARE`` when the slice is full) to scale up,
   the new graceful ``ServicesManager.drain_inference_worker``
   (deregister from the bus, let in-flight shards finish, then stop —
   the Predictor's registry scan folds the replica out on its next
   plan) to scale down, and **idle-train preemption**: when a hot bin
   is starved for exclusive chips and a train sub-job's MFU has sat
   below the floor for N consecutive sweeps, one of its train workers
   is shrunk away to free chips — and re-grown once serving pressure
   subsides.

Every decision is an epoch-stamped, traced, metric-emitting action
(``rafiki_tpu_autoscale_actions_total{action,reason}``, per-bin
target/actual gauges, a bounded decisions ring behind the admin's
``GET /autoscale``), with a ``dry_run`` mode that records would-have
actions without actuating. Disabled (the default) means ONE attribute
check in ``ServicesManager.supervise`` and zero new metric series —
the r11 disabled-means-free discipline.

Preemption honesty note: the MFU gauges live in the process registry,
which sees resident-runner (thread) workers only; a sub-job with no
visible MFU series reads as idle (0.0). In subprocess/docker
deployments set ``RAFIKI_TPU_AUTOSCALE_MFU_FLOOR=0`` to disable
preemption rather than let invisible-but-busy training be shrunk.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..observe import metrics as _metrics
from ..observe import trace as _trace

_log = logging.getLogger(__name__)

#: Smoothing for the per-job qps EWMA (~the last handful of sweeps
#: dominate; one quiet sweep must not read as "the ramp ended").
_QPS_ALPHA = 0.4

#: Decisions kept for ``GET /autoscale`` (bounded: the ring is a
#: debugging/UI surface, not a log).
_RING_CAP = 256


@dataclass(frozen=True)
class PolicyKnobs:
    """The decision table's constants (NodeConfig ``autoscale_*``)."""

    max_replicas: int = 4          # per-bin ceiling
    step: int = 1                  # max replicas added per job per sweep
    up_cooldown_s: float = 10.0    # min gap between scale-ups
    down_cooldown_s: float = 60.0  # quiet time before a scale-down
    queue_high: float = 0.25       # queue_depth/queue_cap high water
    queue_low: float = 0.02        # low water (hysteresis band between)
    p99_high_ms: float = 0.0       # 0 = p99 not consulted
    mfu_floor: float = 0.05        # train sub-job idle threshold (0 = no
    #                                preemption)
    idle_sweeps: int = 3           # consecutive idle sweeps to preempt
    # Predictive scale-ahead (docs/capacity.md): 0 = reactive only.
    # With a horizon, a positive queue-fraction trend projected to
    # cross the high water mark within ``predict_horizon_s`` — or a
    # learned periodicity table expecting >= ``predict_ramp_ratio``x
    # the current qps within the horizon — scales up BEFORE the ramp.
    predict_horizon_s: float = 0.0
    predict_ramp_ratio: float = 1.5


@dataclass(frozen=True)
class BinSignals:
    """One serving bin's load, from the r17 attribution ledger
    (``rafiki_tpu_serving_bin_*``): smoothed queries/s scattered toward
    the bin and smoothed admission-wait seconds accrued per second by
    work bound for it."""

    qps: float = 0.0
    queue_rate: float = 0.0


@dataclass
class JobSignals:
    """One sweep's observed load for one inference job."""

    qps: float = 0.0               # smoothed requests/s
    queue_depth: float = 0.0       # admitted-unsent queries (gauge)
    queue_cap: float = 1.0         # the frontend's admission bound
    backpressure_delta: float = 0.0  # 429s since the previous sweep
    p99_ms: Optional[float] = None   # /predict p99 over this sweep
    # Per-bin load (None when the scraped frontend exposes no
    # attribution ledger — pre-r17 workers / attribution off — the
    # per-job fallback). Keyed by the ledger's truncated bin label.
    bins: Optional[Dict[str, BinSignals]] = None
    # Predictive inputs (None = predictive plane off or no basis):
    # queue_frac projected ``predict_horizon_s`` ahead along the trend
    # EWMA (set by AutoscalePolicy.note_trend), and the learned
    # periodicity table's expected qps at now+horizon (set by the
    # sweep from the loaded table; the replay simulator sets both the
    # same way — docs/capacity.md).
    queue_frac_pred: Optional[float] = None
    expected_qps: Optional[float] = None
    # A FIRING latency-SLO alert for this job (admin/slo_engine.py):
    # None = none firing; "" = job/tenant-scoped alert (any bin may
    # take the capacity); a bin label = the violating bin, which the
    # scale-up targets first. Prioritized over every queue signal —
    # "scale to the SLO, not the queue" (docs/autoscaling.md).
    slo_firing: Optional[str] = None

    @property
    def queue_frac(self) -> float:
        return self.queue_depth / max(self.queue_cap, 1.0)

    def bin_signal(self, bin_id: str) -> Optional[BinSignals]:
        """Ledger rows label bins by ``trial_id[:12]`` (bounded
        cardinality); replica counts key the full id — match here."""
        if not self.bins:
            return None
        return self.bins.get(str(bin_id)[:12])


@dataclass
class JobState:
    """Per-job controller memory across sweeps."""

    last_up_mono: float = float("-inf")
    last_down_mono: float = float("-inf")
    qps_ewma: Optional[float] = None
    # Previous scrape totals for delta signals.
    prev_requests: Optional[float] = None
    prev_backpressure: Optional[float] = None
    prev_buckets: Dict[float, int] = field(default_factory=dict)
    prev_mono: Optional[float] = None
    # Per-bin attribution totals + EWMAs (empty until a scrape exposes
    # the ledger families).
    prev_bin: Dict[str, Tuple[float, float]] = field(default_factory=dict)
    bin_qps_ewma: Dict[str, float] = field(default_factory=dict)
    bin_queue_ewma: Dict[str, float] = field(default_factory=dict)
    # Queue-fraction trend basis (predictive scale-ahead): previous
    # observation + slope EWMA, advanced by AutoscalePolicy.note_trend.
    trend_mono: Optional[float] = None
    trend_frac: float = 0.0
    queue_slope_ewma: Optional[float] = None
    # /stats memo: (serving service label, http service label,
    # queue cap, microbatch on?).
    labels: Optional[Tuple[str, str, float, bool]] = None


@dataclass(frozen=True)
class Decision:
    """One policy verdict for one bin (pre-actuation)."""

    action: str      # "scale_up" | "scale_down"
    bin: str
    reason: str      # "slo_firing" | "backpressure" | "queue_high" |
    #                  "p99_high" | "predicted" | "idle"


class AutoscalePolicy:
    """The pure decision table — unit-testable without a platform.

    Hysteresis: *overloaded* (any high-water signal) scales up,
    *idle* (every signal under its low water) scales down, anything
    between holds. Cooldowns: a scale-up is allowed ``up_cooldown_s``
    after the previous one; a scale-down needs ``down_cooldown_s`` of
    distance from the LAST ACTION in either direction — scaling up is
    cheap to undo, tearing a replica down right after adding it is the
    textbook flap. Step bounds: at most ``step`` replicas added per
    job per sweep (spread across the least-replicated bins first), at
    most ONE removed.
    """

    def __init__(self, knobs: PolicyKnobs):
        self.knobs = knobs

    def classify(self, sig: JobSignals) -> Tuple[str, str]:
        """``(regime, reason)``: regime is "up", "down" or "hold"."""
        k = self.knobs
        if sig.slo_firing is not None:
            # A firing latency SLO outranks every queue signal: the
            # queue can read idle while tail latency burns the error
            # budget (slow replicas drain a short queue slowly).
            return "up", "slo_firing"
        if sig.backpressure_delta > 0:
            return "up", "backpressure"
        if sig.queue_frac >= k.queue_high:
            return "up", "queue_high"
        if k.p99_high_ms > 0 and sig.p99_ms is not None \
                and sig.p99_ms >= k.p99_high_ms:
            return "up", "p99_high"
        if k.predict_horizon_s > 0:
            # Scale AHEAD of the ramp: the projected queue fraction
            # crosses the high water within the horizon (and the queue
            # already shows life — above the low water, so floor noise
            # cannot trigger a prediction), or the learned periodicity
            # table expects a >= ramp_ratio x step-up (vs the current
            # qps, floored at 1 qps so near-idle noise never reads as
            # an imminent ramp). Ranked below every OBSERVED pressure
            # signal — a prediction must not outrank a measurement.
            if sig.queue_frac_pred is not None \
                    and sig.queue_frac_pred >= k.queue_high \
                    and sig.queue_frac > k.queue_low:
                return "up", "predicted"
            if sig.expected_qps is not None and sig.expected_qps \
                    >= k.predict_ramp_ratio * max(sig.qps, 1.0):
                return "up", "predicted"
        p99_quiet = (k.p99_high_ms <= 0 or sig.p99_ms is None
                     or sig.p99_ms <= 0.5 * k.p99_high_ms)
        if sig.queue_frac <= k.queue_low and p99_quiet:
            return "down", "idle"
        return "hold", "band"

    def note_trend(self, sig: JobSignals, state: JobState,
                   now: float) -> None:
        """Fold this sweep's queue fraction into the per-job slope EWMA
        and project ``sig.queue_frac_pred`` at ``predict_horizon_s``
        (left None on a flat/negative trend, a first observation, or a
        disabled horizon). Shared verbatim by the live sweep and the
        replay simulator (observe/replay.py) — the regression gate only
        means something if both predict with the same arithmetic."""
        k = self.knobs
        if k.predict_horizon_s <= 0:
            return
        if state.trend_mono is not None and now > state.trend_mono:
            inst = (sig.queue_frac - state.trend_frac) \
                / (now - state.trend_mono)
            prev = state.queue_slope_ewma
            state.queue_slope_ewma = (
                inst if prev is None else
                _QPS_ALPHA * inst + (1.0 - _QPS_ALPHA) * prev)
            if state.queue_slope_ewma > 0:
                sig.queue_frac_pred = min(
                    1.0, sig.queue_frac
                    + state.queue_slope_ewma * k.predict_horizon_s)
        state.trend_mono = now
        state.trend_frac = sig.queue_frac

    def decide(self, sig: JobSignals, replicas: Dict[str, int],
               state: JobState, now: float) -> List[Decision]:
        """The per-sweep verdicts for one job. Pure in ``(signals,
        replica counts, state timestamps, now)``; the caller applies
        cooldown bookkeeping on actuation (dry-run must not consume a
        cooldown it never acted on)."""
        if not replicas:
            return []
        k = self.knobs
        regime, reason = self.classify(sig)
        out: List[Decision] = []

        def per_replica_load(b: str) -> Optional[float]:
            s = sig.bin_signal(b)
            if s is None:
                return None
            return s.qps / max(replicas[b], 1)

        if regime == "up":
            if now - state.last_up_mono < k.up_cooldown_s:
                return []
            if sig.bins:
                # Per-bin signals (r17 attribution ledger): the
                # HOTTEST bin per replica gets the capacity — a cold
                # bin that merely has fewer replicas no longer absorbs
                # a hot bin's scale-up. Unmeasured bins rank below any
                # measured one; replicas then bin id break ties.
                order = sorted(
                    replicas,
                    key=lambda b: (-(per_replica_load(b)
                                     if per_replica_load(b) is not None
                                     else -1.0), replicas[b], b))
            else:
                # Per-job fallback (old workers / attribution off):
                # fewest-replicas-first, bin id as the deterministic
                # tie break.
                order = sorted(replicas, key=lambda b: (replicas[b], b))
            if reason == "slo_firing" and sig.slo_firing:
                # A bin-scoped alert names its victim: the violating
                # bin takes the capacity first (stable sort keeps the
                # load/replica order among the rest).
                order.sort(key=lambda b: 0 if str(b)[:12]
                           == sig.slo_firing else 1)
            budget = k.step
            for b in order:
                if budget == 0:
                    break
                if replicas[b] >= k.max_replicas:
                    continue
                out.append(Decision("scale_up", b, reason))
                budget -= 1
        elif regime == "down":
            if now - max(state.last_up_mono,
                         state.last_down_mono) < k.down_cooldown_s:
                return []
            # Never below one replica (a bin's last replica is its
            # ensemble vote, not capacity).
            candidates = [b for b in replicas if replicas[b] > 1]
            if candidates:
                if sig.bins:
                    # COLDEST bin per replica drains first (most-
                    # replicated as the tie break). An UNMEASURED bin
                    # ranks coldest of all: no ledger rows means no
                    # observed traffic (a tiered best bin keeps every
                    # query from its siblings) — ranking it hottest
                    # would drain the one bin actually serving.
                    victim = min(candidates, key=lambda b: (
                        per_replica_load(b)
                        if per_replica_load(b) is not None
                        else -1.0, -replicas[b], b))
                else:
                    victim = sorted(candidates,
                                    key=lambda b: (-replicas[b], b))[0]
                out.append(Decision("scale_down", victim, reason))
        return out


class Autoscaler:
    """The controller: scrape → decide → actuate, one ``sweep()`` per
    supervise pass. Constructed only when ``RAFIKI_TPU_AUTOSCALE`` is
    on (LocalPlatform); ``ServicesManager.supervise`` holds a plain
    ``autoscaler`` attribute that is None otherwise."""

    def __init__(self, services, meta, knobs: Optional[PolicyKnobs] = None,
                 dry_run: bool = False,
                 periodicity: Optional[Dict[str, Any]] = None):
        self.services = services
        self.meta = meta
        self.policy = AutoscalePolicy(knobs or PolicyKnobs())
        self.dry_run = dry_run
        # Learned periodicity table (admin/capacity.py; None = no table
        # loaded). Consulted only when predict_horizon_s > 0.
        self.periodicity = periodicity
        self.epoch = 0
        self._jobs: Dict[str, JobState] = {}
        # sub_train_job_id -> consecutive sweeps its MFU sat below the
        # floor (missing gauge counts as 0.0 — see the module
        # docstring's honesty note).
        self._idle_train: Dict[str, int] = {}
        # Preemption debt: sub_id -> [n_chips, ...] of train workers we
        # shrank away, re-grown when pressure subsides.
        self._preempted: Dict[str, List[int]] = {}
        # Sweeps since any job last classified "up" — the regrow gate.
        self._quiet_sweeps = 0
        self._lock = threading.Lock()
        self._ring: "collections.deque" = collections.deque(
            maxlen=_RING_CAP)
        self._m_actions = self._m_target = self._m_actual = None
        self._m_reclaimed = None
        if _metrics.metrics_enabled():
            reg = _metrics.registry()
            self._m_actions = reg.counter(
                "rafiki_tpu_autoscale_actions_total",
                "Autoscaler decisions taken (or would-have, in dry "
                "run), by action and reason")
            self._m_target = reg.gauge(
                "rafiki_tpu_autoscale_target_replicas",
                "Replica target per serving bin (job= short job id, "
                "bin= short bin id)")
            self._m_actual = reg.gauge(
                "rafiki_tpu_autoscale_actual_replicas",
                "Live replicas per serving bin at the last sweep")
            self._m_reclaimed = reg.counter(
                "rafiki_tpu_autoscale_reclaimed_chips_total",
                "Chips reclaimed from idle train sub-jobs by "
                "preemption")

    @classmethod
    def from_env(cls, services, meta) -> "Autoscaler":
        """Build from the ``RAFIKI_TPU_AUTOSCALE_*`` env knobs
        ``NodeConfig.apply_env`` exported (the platform composition
        path; tests construct directly)."""
        import os

        from ..config import NodeConfig, _parse_bool

        def f(name, default):
            raw = os.environ.get(NodeConfig.env_name(name), "")
            try:
                return type(default)(raw) if raw else default
            except ValueError:
                return default

        knobs = PolicyKnobs(
            max_replicas=f("autoscale_max_replicas", 4),
            step=f("autoscale_step", 1),
            up_cooldown_s=f("autoscale_up_cooldown_s", 10.0),
            down_cooldown_s=f("autoscale_down_cooldown_s", 60.0),
            queue_high=f("autoscale_queue_high", 0.25),
            queue_low=f("autoscale_queue_low", 0.02),
            p99_high_ms=f("autoscale_p99_high_ms", 0.0),
            mfu_floor=f("autoscale_mfu_floor", 0.05),
            idle_sweeps=f("autoscale_idle_sweeps", 3),
            predict_horizon_s=f("autoscale_predict_horizon_s", 0.0),
            predict_ramp_ratio=f("autoscale_predict_ramp_ratio", 1.5),
        )
        dry = _parse_bool(os.environ.get(
            NodeConfig.env_name("autoscale_dry_run"), "0"))
        periodicity = None
        table_path = os.environ.get(
            NodeConfig.env_name("autoscale_periodicity"), "").strip()
        if table_path:
            from .capacity import load_periodicity

            try:
                periodicity = load_periodicity(table_path)
            except (OSError, ValueError):
                # NodeConfig.validate parsed this path at startup; a
                # table deleted since is a degraded signal, not a
                # reason to refuse the whole control loop.
                _log.warning("autoscale periodicity table %s "
                             "unreadable; periodicity predictions off",
                             table_path, exc_info=True)
        return cls(services, meta, knobs=knobs, dry_run=dry,
                   periodicity=periodicity)

    def close(self) -> None:
        """Drop every autoscale series (job/bin labels churn with
        deployments; a stopped autoscaler must not leak them into
        every future scrape)."""
        for m in (self._m_actions, self._m_target, self._m_actual,
                  self._m_reclaimed):
            if m is not None:
                m.remove()

    # --- The sweep -----------------------------------------------------

    def sweep(self, scrapes=None) -> List[Dict[str, Any]]:
        """One control pass; returns the decisions recorded (actuated
        or dry-run). Runs on the supervise thread — everything here is
        best-effort and must not raise into the sweep. ``scrapes`` is
        the sweep-shared :class:`~rafiki_tpu.admin.scrape.ScrapeCache`
        when the supervise pass runs several metric consumers (the SLO
        engine scraped the same endpoints moments ago); None fetches
        directly."""
        self.epoch += 1
        now = time.monotonic()
        acted: List[Dict[str, Any]] = []
        jobs = self.meta.get_inference_jobs(status="RUNNING")
        live_ids = {j["id"] for j in jobs}
        self._prune_departed(live_ids)
        self._track_idle_training()
        any_up = False
        slo = getattr(self.services, "slo_engine", None)
        for job in jobs:
            state = self._jobs.setdefault(job["id"], JobState())
            # scrapes forwarded only when present: _signals is a test
            # seam (monkeypatched fakes keep the legacy 3-arg shape).
            sig = (self._signals(job, state, now) if scrapes is None
                   else self._signals(job, state, now,
                                      scrapes=scrapes))
            if sig is None:
                continue
            if slo is not None:
                # The SLO engine swept just before us (same supervise
                # pass): a firing latency objective is scale-up
                # pressure for this job, ahead of the queue signals.
                sig.slo_firing = slo.slo_pressure(job["id"])
            # Predictive inputs (no-ops when predict_horizon_s == 0):
            # trend projection from controller state, expected qps from
            # the learned periodicity table at wall-clock phase.
            self.policy.note_trend(sig, state, now)
            if self.periodicity is not None and \
                    self.policy.knobs.predict_horizon_s > 0:
                from .capacity import expected_qps

                sig.expected_qps = expected_qps(
                    self.periodicity, time.time(),
                    self.policy.knobs.predict_horizon_s)
            replicas, by_bin = self._replica_counts(job["id"])
            if not replicas:
                continue
            self._publish_actual(job["id"], replicas)
            decisions = self.policy.decide(sig, replicas, state, now)
            regime, _ = self.policy.classify(sig)
            any_up = any_up or regime == "up"
            for d in decisions:
                acted.append(self._apply(job["id"], d, replicas,
                                         by_bin, sig, state, now))
        if any_up:
            self._quiet_sweeps = 0
        else:
            self._quiet_sweeps += 1
            regrown = self._maybe_regrow(now)
            if regrown is not None:
                acted.append(regrown)
        return acted

    def _prune_departed(self, live_ids) -> None:
        for job_id in [j for j in self._jobs if j not in live_ids]:
            del self._jobs[job_id]
            if self._m_target is not None:
                self._m_target.remove(job=job_id[:8])
                self._m_actual.remove(job=job_id[:8])

    # --- Signals -------------------------------------------------------

    def _scrape(self, host: str, path: str) -> Any:
        from .scrape import fetch_endpoint

        return fetch_endpoint(host, path)

    def _signals(self, job: Dict[str, Any], state: JobState,
                 now: float, scrapes=None) -> Optional[JobSignals]:
        """Scrape the job's predictor and fold the exposition into
        delta signals. None (skip this job this sweep) when the
        frontend is not reachable yet."""
        host = job.get("predictor_host")
        if not host:
            return None
        fetch = scrapes.fetch if scrapes is not None else self._scrape
        try:
            if state.labels is None:
                stats = fetch(host, "/stats")
                knobs = stats.get("knobs") or {}
                state.labels = (stats.get("service") or "",
                                stats.get("http_service") or "",
                                float(knobs.get("queue_cap")
                                      or stats.get("queue_cap") or 1.0),
                                bool(stats.get("microbatch", True)))
            text = fetch(host, "/metrics")
        except (OSError, ValueError):
            state.labels = None  # re-resolve after a frontend restart
            return None
        service, http_service, queue_cap, microbatch = state.labels
        if not microbatch:
            # A batcher-off frontend has no admission queue: depth is
            # always 0 and 429s only fire on the fairness cap, so the
            # policy would read permanent "idle" and drain manually
            # attached replicas under live traffic. No honest signal
            # basis — leave the job alone.
            return None
        metrics = _metrics.parse_exposition(text)

        def total(name, **match):
            return sum(v for labels, v in metrics.get(name, [])
                       if all(labels.get(k) == str(mv)
                              for k, mv in match.items()))

        requests = total("rafiki_tpu_serving_requests_total",
                         service=service)
        backpressure = total("rafiki_tpu_serving_rejected_total",
                             service=service)
        depth = total("rafiki_tpu_serving_queue_depth_queries",
                      service=service)
        buckets: Dict[float, int] = {}
        for labels, v in metrics.get(
                "rafiki_tpu_http_request_seconds_bucket", []):
            if labels.get("service") != http_service or \
                    labels.get("route") != "/predict":
                continue
            le = labels.get("le")
            bound = float("inf") if le == "+Inf" else float(le)
            buckets[bound] = buckets.get(bound, 0) + int(v)

        # Per-bin attribution ledger (present only when the scraped
        # frontend runs with RAFIKI_TPU_SERVING_ATTRIBUTION): fold the
        # per-bin query/queue-seconds totals into per-bin rate EWMAs.
        # Absent families leave `bins` None — the per-job fallback.
        bin_now: Dict[str, Tuple[float, float]] = {}
        for labels, v in metrics.get(
                "rafiki_tpu_serving_bin_queries_total", []):
            if labels.get("service") != service:
                continue
            b = labels.get("bin", "")
            q, w = bin_now.get(b, (0.0, 0.0))
            bin_now[b] = (q + v, w)
        for labels, v in metrics.get(
                "rafiki_tpu_serving_bin_queue_seconds_total", []):
            if labels.get("service") != service:
                continue
            b = labels.get("bin", "")
            q, w = bin_now.get(b, (0.0, 0.0))
            bin_now[b] = (q, w + v)

        sig = JobSignals(queue_depth=depth, queue_cap=queue_cap)
        dt = (now - state.prev_mono) if state.prev_mono is not None \
            else None
        if bin_now and dt and dt > 0:
            bins: Dict[str, BinSignals] = {}
            for b, (q, w) in bin_now.items():
                pq, pw = state.prev_bin.get(b, (None, None))
                if pq is None:
                    continue  # first sight of this bin: basis only
                inst_q = max(0.0, q - pq) / dt
                inst_w = max(0.0, w - pw) / dt
                prev = state.bin_qps_ewma.get(b)
                state.bin_qps_ewma[b] = (
                    inst_q if prev is None else
                    _QPS_ALPHA * inst_q + (1.0 - _QPS_ALPHA) * prev)
                prev = state.bin_queue_ewma.get(b)
                state.bin_queue_ewma[b] = (
                    inst_w if prev is None else
                    _QPS_ALPHA * inst_w + (1.0 - _QPS_ALPHA) * prev)
                bins[b] = BinSignals(
                    qps=state.bin_qps_ewma[b],
                    queue_rate=state.bin_queue_ewma[b])
            if bins:
                sig.bins = bins
        if bin_now:
            state.prev_bin = bin_now
            # Bins retired by promotion churn must not pin stale EWMAs.
            for stale in [b for b in state.bin_qps_ewma
                          if b not in bin_now]:
                state.bin_qps_ewma.pop(stale, None)
                state.bin_queue_ewma.pop(stale, None)
        if dt and dt > 0 and state.prev_requests is not None:
            inst = max(0.0, requests - state.prev_requests) / dt
            state.qps_ewma = (inst if state.qps_ewma is None else
                              _QPS_ALPHA * inst +
                              (1.0 - _QPS_ALPHA) * state.qps_ewma)
        sig.qps = state.qps_ewma or 0.0
        if state.prev_backpressure is not None:
            sig.backpressure_delta = max(
                0.0, backpressure - state.prev_backpressure)
        deltas = sorted((le, buckets.get(le, 0)
                         - state.prev_buckets.get(le, 0))
                        for le in buckets)
        if deltas and deltas[-1][1] > 0:
            p99 = _metrics.bucket_percentile(deltas, 0.99)
            sig.p99_ms = round(p99 * 1e3, 3) if p99 is not None else None
        first = state.prev_mono is None
        state.prev_requests = requests
        state.prev_backpressure = backpressure
        state.prev_buckets = buckets
        state.prev_mono = now
        # The first scrape has no delta basis: record it, act next
        # sweep (a controller must never act on totals it cannot
        # attribute to a time window).
        return None if first else sig

    def _replica_counts(self, job_id: str,
                        ) -> Tuple[Dict[str, int],
                                   Dict[str, List[Dict[str, Any]]]]:
        """Live replicas per trial bin + the mapping rows per bin
        (newest-first, for the drain pick)."""
        by_bin: Dict[str, List[Dict[str, Any]]] = {}
        for w in self.services.active_inference_workers(job_id):
            by_bin.setdefault(str(w["trial_id"]), []).append(w)
        for rows in by_bin.values():
            rows.sort(key=lambda w: self._created_at(w), reverse=True)
        return {b: len(rows) for b, rows in by_bin.items()}, by_bin

    def _created_at(self, w: Dict[str, Any]) -> float:
        svc = self.meta.get_service(w["service_id"])
        return float(svc.get("created_at") or 0.0) if svc else 0.0

    def _publish_actual(self, job_id: str,
                        replicas: Dict[str, int]) -> None:
        if self._m_actual is None:
            return
        for b, n in replicas.items():
            self._m_actual.set(n, job=job_id[:8], bin=b[:12])

    # --- Actuation -----------------------------------------------------

    def _apply(self, job_id: str, d: Decision,
               replicas: Dict[str, int],
               by_bin: Dict[str, List[Dict[str, Any]]],
               sig: JobSignals, state: JobState,
               now: float) -> Dict[str, Any]:
        t0 = time.monotonic()
        wall = time.time()
        target = replicas[d.bin] + (1 if d.action == "scale_up" else -1)
        entry: Dict[str, Any] = {
            "epoch": self.epoch, "t": round(wall, 3),
            "job": job_id[:8], "bin": d.bin[:12],
            "action": d.action, "reason": d.reason,
            "replicas": replicas[d.bin], "target": target,
            "dry_run": self.dry_run,
            "signals": {"qps": round(sig.qps, 2),
                        "queue_frac": round(sig.queue_frac, 4),
                        "backpressure_delta": sig.backpressure_delta,
                        "p99_ms": sig.p99_ms},
        }
        if sig.slo_firing is not None:
            entry["signals"]["slo_firing"] = sig.slo_firing
        if sig.queue_frac_pred is not None:
            entry["signals"]["queue_frac_pred"] = \
                round(sig.queue_frac_pred, 4)
        if sig.expected_qps is not None:
            entry["signals"]["expected_qps"] = round(sig.expected_qps, 2)
        if sig.bins:
            entry["signals"]["bins"] = {
                b: {"qps": round(s.qps, 2),
                    "queue_rate": round(s.queue_rate, 4)}
                for b, s in sorted(sig.bins.items())}
        ok = True
        if not self.dry_run:
            try:
                if d.action == "scale_up":
                    # The attempt consumes the cooldown no matter how
                    # it ends — blocked OR raising: a starved (or
                    # launch-failing) node must not burn a probe, a
                    # service row, and possibly a preempted train
                    # worker on every 0.5 s sweep. Set BEFORE the
                    # call so the except path cannot skip it.
                    state.last_up_mono = now
                    ok = self._scale_up(job_id, d.bin, by_bin, entry)
                else:
                    ok = self._scale_down(job_id, d.bin, by_bin, entry)
                    if ok:
                        state.last_down_mono = now
            except Exception as e:
                ok = False
                entry["error"] = f"{type(e).__name__}: {e}"
                _log.exception("autoscale %s of %s/%s failed",
                               d.action, job_id[:8], d.bin[:12])
        entry["applied"] = ok and not self.dry_run
        # The counter label vocabulary stays FIXED: a failure detail
        # belongs in the ring entry, never in a label (cardinality).
        blocked_reason = "error" if "error" in entry else "no_capacity"
        self._record(entry, d.action if ok else f"{d.action}_blocked",
                     d.reason if ok else blocked_reason, wall, t0)
        if self._m_target is not None and ok:
            self._m_target.set(target, job=job_id[:8], bin=d.bin[:12])
        return entry

    def _scale_up(self, job_id: str, bin_id: str,
                  by_bin: Dict[str, List[Dict[str, Any]]],
                  entry: Dict[str, Any]) -> bool:
        """Attach one replica for the bin. When no EXCLUSIVE chip
        placement exists and an idle train sub-job qualifies, preempt
        one of its workers first — a time-sliced replica on saturated
        silicon adds latency, not capacity, so reclaiming a chip from
        training that isn't using it beats co-owning one."""
        registry = getattr(self.services, "node_registry", None)
        if registry is not None:
            # Failure-domain spread (docs/cluster.md): with the cluster
            # fabric on, replicas of one bin land round-robin across
            # live nodes — a node death must never silence a bin's
            # ensemble vote. The registry's deterministic vote picks
            # exactly ONE placing node per pressure round; a deferring
            # node records why and lets the elected peer (seeing the
            # same shared meta rows + signals) act on ITS sweep.
            counts: Dict[str, int] = {}
            for w in by_bin.get(bin_id) or []:
                svc = self.meta.get_service(w["service_id"])
                nid = (svc or {}).get("node_id") or ""
                counts[nid] = counts.get(nid, 0) + 1
            if not registry.spread_ok(counts):
                entry["deferred_to_peer"] = True
                return False
        n_chips = self._bin_chips(by_bin.get(bin_id) or [])
        probe = f"autoscale-probe:{self.epoch}"
        group = self.services.allocator.allocate(n_chips, name=probe,
                                                 shared_ok=False)
        if group is not None:
            self.services.allocator.release(probe)
        else:
            reclaimed = self._preempt_idle_train(n_chips)
            if reclaimed:
                entry["preempted_chips"] = reclaimed
        svc = self.services.add_inference_worker(job_id, bin_id,
                                                 chips_per_worker=n_chips)
        if svc is None:
            return False
        entry["service_id"] = svc["id"][:8]
        return True

    def _scale_down(self, job_id: str, bin_id: str,
                    by_bin: Dict[str, List[Dict[str, Any]]],
                    entry: Dict[str, Any]) -> bool:
        rows = by_bin.get(bin_id) or []
        if len(rows) < 2:
            return False
        victim = rows[0]["service_id"]  # newest replica drains first
        # Short in-sweep wait: the common drain finishes within one
        # worker batch_timeout (~0.5 s); a worker wedged on a long
        # burst is hard-stopped at the deadline either way, and this
        # runs ON the supervise thread — a 15 s default here would
        # stall dead-service detection and every other decision.
        res = self.services.drain_inference_worker(victim,
                                                   drain_timeout=2.0)
        entry["service_id"] = victim[:8]
        entry["drained"] = bool(res.get("drained"))
        return True

    def _bin_chips(self, rows: List[Dict[str, Any]]) -> int:
        for w in rows:
            svc = self.meta.get_service(w["service_id"])
            if svc is not None and svc.get("chips"):
                return len(svc["chips"])
        return 1

    # --- Idle-train preemption ----------------------------------------

    def _track_idle_training(self) -> None:
        """Advance each RUNNING train sub-job's idle-sweep counter:
        below the MFU floor counts up, any sign of life resets. Runs
        every sweep (not only under pressure) so the idle verdict is
        already N sweeps deep when a starved bin needs chips."""
        floor = self.policy.knobs.mfu_floor
        if floor <= 0:
            self._idle_train.clear()
            return
        by_label = self._mfu_samples()
        live: set = set()
        for job in self.meta.get_train_jobs(status="RUNNING"):
            for sub in self.meta.get_sub_train_jobs(job["id"]):
                live.add(sub["id"])
                mfu = self._sub_job_mfu(sub["id"], by_label)
                if mfu < floor:
                    self._idle_train[sub["id"]] = \
                        self._idle_train.get(sub["id"], 0) + 1
                else:
                    self._idle_train.pop(sub["id"], None)
        for sub_id in [s for s in self._idle_train if s not in live]:
            del self._idle_train[sub_id]

    @staticmethod
    def _mfu_samples() -> Dict[str, float]:
        """MFU gauge value per ``trial`` label. The label is the
        TRUNCATED trial id (``trial_id[:12]`` — the TrialRunner's
        cardinality-bounded binding), so resolution to sub-jobs goes
        trial-row -> label prefix, never label -> meta lookup."""
        gauge = _metrics.registry().find("rafiki_tpu_train_mfu_ratio")
        if gauge is None:
            return {}
        return {labels.get("trial", ""): float(value)
                for labels, value in gauge.samples()}

    def _sub_job_mfu(self, sub_id: str,
                     by_label: Dict[str, float]) -> float:
        """max MFU over the sub-job's RUNNING trials' gauge samples
        (0.0 when none are visible — resident-runner visibility only,
        see the module docstring)."""
        if not by_label:
            return 0.0
        best = 0.0
        for trial in self.meta.get_trials(sub_id):
            if trial.get("status") != "RUNNING":
                continue
            v = by_label.get(str(trial["id"])[:12])
            if v is not None:
                best = max(best, v)
        return best

    def _idle_sub_jobs(self) -> List[str]:
        n = self.policy.knobs.idle_sweeps
        return sorted(s for s, c in self._idle_train.items() if c >= n)

    def _preempt_idle_train(self, want_chips: int) -> int:
        """Shrink idle train sub-jobs by one worker each until
        ``want_chips`` are freed (or candidates run out). A sub-job is
        never shrunk below ONE worker — the job must stay alive to be
        re-grown; trial rows are idempotent, so the stopped worker's
        in-flight trial is simply re-proposed later."""
        freed = 0
        for sub_id in self._idle_sub_jobs():
            if freed >= want_chips:
                break
            workers = [w for w in self.meta.get_train_job_workers(sub_id)
                       if self._active_train_worker(w)]
            if len(workers) < 2:
                continue
            victim = self.meta.get_service(workers[-1]["service_id"])
            n = len(victim.get("chips") or [1])
            self.services._stop_service(victim["id"])
            freed += n
            self._preempted.setdefault(sub_id, []).append(n)
            self._idle_train.pop(sub_id, None)
            if self._m_reclaimed is not None:
                self._m_reclaimed.inc(n)
            wall, t0 = time.time(), time.monotonic()
            self._record({"epoch": self.epoch, "t": round(wall, 3),
                          "job": sub_id[:8], "bin": "",
                          "action": "preempt_shrink",
                          "reason": "idle_train",
                          "chips": n, "dry_run": False,
                          "applied": True},
                         "preempt_shrink", "idle_train", wall, t0)
        return freed

    def _maybe_regrow(self, now: float) -> Optional[Dict[str, Any]]:
        """Give a preempted train sub-job its worker back once serving
        pressure has been absent for ``idle_sweeps`` sweeps — one
        worker per quiet sweep, so a regrow can never itself starve a
        ramp that returns mid-regrow."""
        if self._quiet_sweeps < self.policy.knobs.idle_sweeps \
                or not self._preempted:
            return None
        for sub_id in sorted(self._preempted):
            sub = self.meta.get_sub_train_job(sub_id)
            job = self.meta.get_train_job(sub["train_job_id"]) \
                if sub else None
            if job is None or job["status"] != "RUNNING":
                del self._preempted[sub_id]  # debt died with the job
                continue
            n = self._preempted[sub_id][-1]
            if self.dry_run:
                svc = None
            else:
                svc = self.services.add_train_worker(sub_id,
                                                     chips_per_trial=n)
            if svc is None and not self.dry_run:
                return None  # no chips yet; retry next quiet sweep
            self._preempted[sub_id].pop()
            if not self._preempted[sub_id]:
                del self._preempted[sub_id]
            wall, t0 = time.time(), time.monotonic()
            entry = {"epoch": self.epoch, "t": round(wall, 3),
                     "job": sub_id[:8], "bin": "",
                     "action": "regrow", "reason": "pressure_subsided",
                     "chips": n, "dry_run": self.dry_run,
                     "applied": not self.dry_run}
            self._record(entry, "regrow", "pressure_subsided", wall, t0)
            return entry
        return None

    def _active_train_worker(self, w: Dict[str, Any]) -> bool:
        svc = self.meta.get_service(w["service_id"])
        return svc is not None and svc["service_type"] == "TRAIN" and \
            svc["status"] in ("STARTED", "DEPLOYING", "RUNNING")

    # --- Recording -----------------------------------------------------

    def _record(self, entry: Dict[str, Any], action: str, reason: str,
                wall: float, t0: float) -> None:
        with self._lock:
            self._ring.append(entry)
        if self._m_actions is not None:
            # action/reason are a small fixed vocabulary; the whole
            # family is dropped by close()'s bare remove().
            self._m_actions.inc(action=action, reason=reason[:40])
        ctx = _trace.TraceContext(_trace.new_trace_id())
        _trace.record_event(f"autoscale.{action}", "autoscaler", [ctx],
                            wall, time.monotonic() - t0,
                            attrs={k: v for k, v in entry.items()
                                   if k in ("job", "bin", "reason",
                                            "target", "replicas",
                                            "chips", "dry_run")})
        entry["trace_id"] = ctx.trace_id

    def snapshot(self) -> Dict[str, Any]:
        """The ``GET /autoscale`` body."""
        with self._lock:
            decisions = list(self._ring)
        # dict()/list() copies are C-level (GIL-atomic): snapshot runs
        # on an HTTP handler thread while sweep() mutates on the
        # supervise thread, and a Python-level comprehension over the
        # live dicts could observe a resize mid-iteration.
        idle = dict(self._idle_train)
        preempted = {k: list(v)
                     for k, v in dict(self._preempted).items()}
        targets: Dict[str, Any] = {}
        for name, key in (("target", self._m_target),
                          ("actual", self._m_actual)):
            if key is None:
                continue
            for labels, v in key.samples():
                job = labels.get("job", "")
                targets.setdefault(job, {}).setdefault(
                    labels.get("bin", ""), {})[name] = int(v)
        return {
            "enabled": True,
            "dry_run": self.dry_run,
            "epoch": self.epoch,
            "knobs": dataclass_asdict(self.policy.knobs),
            "targets": targets,
            "idle_train_sweeps": idle,
            "preempted": preempted,
            "decisions": decisions[::-1],  # newest first for the UI
        }


def dataclass_asdict(obj) -> Dict[str, Any]:
    import dataclasses

    return dataclasses.asdict(obj)
