"""Typed node configuration: one validated object per serve process.

Parity: SURVEY.md §5 "Config / flag system" — the reference configures
every service through ``.env.sh`` exports and env vars injected by the
ServicesManager; the rebuild keeps that transport (env vars are how
container/subprocess children inherit settings) but fronts it with a
dataclass so a node constructs from ONE validated object instead of
scattered ``os.environ`` reads.

Precedence: explicit constructor/CLI overrides > ``RAFIKI_TPU_*`` env
vars > defaults. ``apply_env()`` writes the tunables back into
``os.environ`` so both in-process workers (threads reading env at
construction) and spawned service children see the same resolved values.
"""

from __future__ import annotations

import dataclasses
import os
import typing
from dataclasses import dataclass
from typing import Any, Dict, Mapping, Optional, Union

_PREFIX = "RAFIKI_TPU_"


def _parse_bool(raw: str) -> bool:
    return raw.strip().lower() not in ("0", "false", "no", "off", "")


def parse_tristate_bool(raw: str) -> Optional[bool]:
    """The ONE spelling of the tri-state env contract ("auto" -> None,
    falsy spellings -> False, else True) — NodeConfig coercion and
    direct env readers (InferenceWorker) must resolve identically."""
    if raw.strip().lower() == "auto":
        return None
    return _parse_bool(raw)


@dataclass(frozen=True)
class NodeConfig:
    """Everything a ``python -m rafiki_tpu serve`` node needs.

    Env var for field ``x``: ``RAFIKI_TPU_<X>`` (see ``_ENV_MAP`` for
    the exceptions that predate this layer).
    """

    # --- Node identity / state ---
    workdir: str = "./rafiki_workdir"
    port: int = 3000
    n_chips: Optional[int] = None          # None = all visible chips
    bus_uri: str = ""                      # "" = in-process bus
    supervise_interval: float = 10.0       # 0 disables the sweep
    log_level: str = "info"

    # --- Multi-host slice membership (jax.distributed) ---
    coordinator: str = ""                  # host:port; "" = single host
    num_processes: Optional[int] = None
    process_id: Optional[int] = None

    # --- Service tunables (inherited by workers) ---
    # One-burst-in-flight serving overlap. None = "auto": each
    # inference worker measures its device->host sync latency at
    # startup and pipelines only when there is latency worth hiding
    # (a tunneled chip's 100ms+ flush window) — on a directly attached
    # chip the handoff would COST a few percent for nothing to hide.
    serving_pipeline: Optional[bool] = None
    checkpoint_trials: bool = False        # mid-trial epoch snapshots
    trace_dir: str = ""                    # per-trial profiler traces
    probe_timeout: float = 60.0            # accelerator liveness probe

    # --- Serving frontend: continuous cross-request micro-batching ---
    # The predictor coalesces every /predict arriving within one fill
    # window into ONE scatter-gather super-batch (predictor/batcher.py).
    serving_microbatch: bool = True        # off = one scatter per request
    serving_fill_window: float = 0.005     # adaptive-window ceiling
    #                                        default (legacy fixed knob)
    serving_fill_window_min: float = 0.0   # adaptive floor; == max pins
    serving_fill_window_max: Optional[float] = None  # None = use
    #                                        serving_fill_window
    serving_max_batch: int = 1024          # queries per super-batch
    serving_max_inflight: int = 2          # scattered-ungathered batches
    serving_queue_cap: int = 4096          # admission bound (queries);
    #                                        beyond it: 429 + Retry-After
    # Data-parallel replica sharding: slice each trial bin's
    # super-batch across ALL live same-bin replicas (latency-weighted)
    # instead of sending it whole to one rotating pick.
    serving_shard_replicas: bool = True
    # Per-client fairness: cap one client key's share of the admission
    # queue. The key comes from the request header named by
    # serving_client_header ("" = fairness off).
    serving_client_header: str = ""
    serving_client_share: float = 0.25     # fraction of queue_cap

    # --- Predictor edge cache + tiered serving (docs/serving.md) ---
    # Content-addressed response cache at the predictor edge: repeat
    # queries are answered without touching the ensemble scatter.
    # Byte budget; 0 (the default) disables the cache entirely — the
    # serving hot path then pays one attribute check and registers NO
    # cache metric series.
    serving_cache_bytes: int = 0
    # Max age of a cached answer, seconds. Entries are additionally
    # invalidated wholesale whenever trial promotion changes any served
    # bin (the admin promotion path bumps the cache epoch), so TTL only
    # bounds staleness against out-of-band model changes.
    serving_cache_ttl_s: float = 60.0
    # Admission control: a key is cached only on its Nth miss (2 =
    # second-touch, the default), so one-off keys don't churn the LRU.
    # 1 admits on first touch.
    serving_cache_admit_after: int = 2
    # Confidence-tiered ensemble serving: scatter to the BEST bin (by
    # tracked eval score) first and escalate to the full ensemble vote
    # only for queries whose confidence (softmax margin) falls below
    # this threshold. 0 (the default) disables tiering — every query
    # fans out to the full ensemble, and no tier series is registered.
    serving_tier_threshold: float = 0.0

    # Packed batch-tensor wire format (docs/serving.md "Wire format"):
    # "on" (default) packs same-shape tensor super-batches into one
    # contiguous __ndbatch__ buffer per shard toward workers that
    # advertise it (negotiated — old workers keep per-query frames);
    # "compat" emits/advertises nothing packed but KEEPS the wire-bytes
    # / host-copies accounting (kill switch with observability, and the
    # bench A/B's measured legacy side); "off" = legacy frames and
    # ZERO wire metric series.
    serving_packed_wire: str = "on"
    # Serving quantization mode: "int8" quantizes each InferenceWorker's
    # model post-load (per-channel symmetric weight scales, dequant-free
    # int8 matmuls where the module supports it, f32 fallback per
    # layer); "" (default) serves the trained dtype. Promotion-spawned
    # workers recompute scales for their bin at load. Accuracy contract:
    # bench.py --config serving-concurrent --quant int8 gates on the
    # f32-vs-int8 accuracy delta.
    serving_quant: str = ""
    # Stacked-ensemble serving (docs/serving.md "Stacked ensembles"):
    # "on" (default) lets an InferenceWorker hosting a multi-member
    # same-family bin stack the member weights along a leading model
    # axis and serve every burst as ONE vmapped device dispatch
    # (shape-congruence probed at load; incongruent or sk-style
    # members fall back to per-member runners). "off" = per-member
    # serving and ZERO stacked metric series.
    serving_stacked: str = "on"

    # --- Generative serving (docs/serving.md "Generative serving") ---
    # Token-level continuous batching on LM-hosting inference workers:
    # paged KV cache, per-step admission, streamed token frames.
    # Default OFF — a generate-off node pays one attribute check per
    # worker loop pass and exposes ZERO rafiki_tpu_lm_* series.
    serving_generate: bool = False
    # Tokens per KV page (the allocation granule). Smaller pages waste
    # less on short tails but grow the per-sequence page table.
    generate_page_size: int = 16
    # Device page-pool size (pages; page 0 is reserved scratch). Total
    # KV bytes/layer/projection = pages * page_size * d_model * 2 (bf16).
    generate_pool_pages: int = 256
    # Decode-batch width: resident-sequence lanes per compiled decode
    # step. The continuous-batching dispatch win is ~1/width.
    generate_decode_batch: int = 8
    # Per-request cap on generated tokens (requests may ask for less).
    generate_max_new: int = 128

    # --- Metrics-driven autoscaler (docs/autoscaling.md) ---
    # Default OFF: supervise pays one attribute check, zero new metric
    # series, byte-identical sweep behavior. On, the admin-side control
    # loop scales inference replicas per bin from the predictors' own
    # /metrics (backpressure, queue depth, p99) and preempts idle
    # training for starved hot bins.
    autoscale: bool = False
    # Record would-have decisions (ring + counters) without actuating.
    autoscale_dry_run: bool = False
    # Per-bin replica ceiling and per-sweep scale-up step bound.
    autoscale_max_replicas: int = 4
    autoscale_step: int = 1
    # Asymmetric cooldowns: scale up within seconds of pressure, scale
    # down only after a long quiet spell (and never right after an up).
    autoscale_up_cooldown_s: float = 10.0
    autoscale_down_cooldown_s: float = 60.0
    # Hysteresis band over queue_depth/queue_cap: >= high scales up,
    # <= low (with zero backpressure) scales down, between holds.
    autoscale_queue_high: float = 0.25
    autoscale_queue_low: float = 0.02
    # Optional /predict p99 high-water, milliseconds (0 = p99 not
    # consulted by the policy; it is still recorded in decisions).
    autoscale_p99_high_ms: float = 0.0
    # Idle-train preemption: a sub-job whose MFU gauge sat below this
    # floor for autoscale_idle_sweeps consecutive sweeps may be shrunk
    # by one worker to feed a starved serving bin (re-grown when
    # pressure subsides). 0 disables preemption — set 0 in subprocess
    # deployments, where worker MFU is invisible to this registry.
    autoscale_mfu_floor: float = 0.05
    autoscale_idle_sweeps: int = 3
    # Predictive scale-ahead (docs/capacity.md): with a horizon > 0 the
    # autoscaler projects each job's queue occupancy forward along its
    # per-sweep trend (EWMA slope) and scales UP with reason
    # "predicted" when the projection crosses autoscale_queue_high
    # within the horizon — ahead of the ramp instead of behind it.
    # 0 (the default) disables the predictive path entirely.
    autoscale_predict_horizon_s: float = 0.0
    # Optional periodicity table (a JSON file learned from a recorded
    # workload trace by `python -m rafiki_tpu.capacity learn`): the
    # second predictive signal — a recurring ramp due within the
    # horizon whose expected qps exceeds the current bin's by
    # autoscale_predict_ramp_ratio pre-provisions the same way.
    # "" = trend signal only.
    autoscale_periodicity: str = ""
    autoscale_predict_ramp_ratio: float = 1.5

    # Time-sliced tenancy cap: max co-owners per chip when shared
    # placement is admitted (parallel/chips.py). Promoted from the
    # env-only expert baseline (r14): the autoscaler's scale-up leans
    # on time-sliced placement when the slice is full, which makes the
    # cap a per-deployment sizing decision, not an incident knob.
    max_chip_share: int = 4

    # InferenceWorker bus-registration lease cadence, seconds: the
    # registration is re-asserted at this period so a restarted broker
    # re-learns live workers (docs/robustness.md). Promoted from an
    # env-only expert knob (r12): per-deployment now that promotion /
    # cache invalidation correctness leans on registration freshness.
    worker_reregister: float = 5.0

    # How long a foreign node's RUNNING row stays credible without a
    # heartbeat, seconds (admin/services_manager.py). Promoted from an
    # env-only expert knob (r15): multi-node deployments size it from
    # their own heartbeat cadence + NFS/sqlite stall budget, which
    # makes it a per-deployment decision — and the old class-attribute
    # read froze the value at FIRST import, before apply_env could run.
    node_lease: float = 120.0

    # InferenceWorker serving-pipeline auto-probe threshold, seconds:
    # with serving_pipeline=auto the worker pipelines only when the
    # measured device->host sync latency exceeds this (tunneled chips
    # ~0.1-0.7s win; directly attached ~1ms lose). Promoted from an
    # env-only expert knob (r15): the tunneled-vs-direct mix is a
    # per-deployment fact, not an incident override.
    pipeline_sync_min: float = 0.02

    # --- Trial lifecycle / dataset residency (docs/training.md) ---
    # Host dataset cache: parsed datasets stay resident across trials,
    # keyed by (path, mtime, size), byte-budget LRU. 0 disables.
    dataset_cache_bytes: int = 1 << 30
    # Device staging cache: the replicated uint8 dataset arrays stay
    # resident on the mesh across trials (never donated). 0 disables.
    stage_cache_bytes: int = 2 << 30
    # Per-trial on-device staging threshold: datasets up to this many
    # bytes are staged whole on the mesh (one H2D, index-gathered
    # batches); larger ones fall back to per-chunk shipping.
    stage_bytes: int = 2 << 30
    # TrainWorkers compute the NEXT proposal on a background thread
    # while the current trial trains (advisor/prefetch.py). Opt-out.
    advisor_prefetch: bool = True
    # ParamStore write-behind: save() returns before the disk flush
    # (store/params.py). Off = synchronous saves again.
    params_write_behind: bool = True

    # --- Robustness (docs/robustness.md) ---
    # Fault-injection plan (rafiki_tpu/faults.py): ";"-separated
    # site.kind:params rules injected at the bus / http / worker seams.
    # "" = fault plane disabled (injection sites are strict no-ops).
    fault_plan: str = ""
    # PRNG seed for probabilistic (p=) fault rules: a seeded plan
    # replays the same per-rule decision sequence.
    fault_seed: int = 0
    # TCP bus client reconnection (bus/tcp.py): base backoff step for
    # the bounded exponential retry after a transport failure, and the
    # total retry budget. 0 budget = legacy behavior (one immediate
    # resend of an unsent frame, then fail). Only frame-UNSENT ops and
    # idempotent reads retry — a non-idempotent op whose frame was
    # fully sent is never blindly replayed across a broker restart.
    bus_retry_base_s: float = 0.05
    bus_retry_total_s: float = 15.0

    # --- Cluster serving fabric (docs/cluster.md) ---
    # Master gate for the multi-node serving plane: node registry rows
    # on the bus (admin/nodes.py), frontend peer-cache probes +
    # invalidation gossip (predictor/edge_cache.py), node-routed bus
    # relay and node-aware shard locality. Default OFF — zero new
    # metric series, zero extra threads, byte-identical single-node
    # behavior (one attribute/env check per seam).
    cluster_fabric: bool = False
    # Bound on ONE peer-cache probe, seconds: a frontend miss consults
    # at most one peer for at most this long before scattering to the
    # workers (the probe is strictly additive latency on a cold key, so
    # it must stay well under a scatter's own p50).
    cluster_probe_timeout_s: float = 0.25
    # Same-node replica preference in shard-plan weights: a replica
    # whose chips live on THIS node gets its inverse-latency weight
    # multiplied by this factor (EWMA latency still rules — a slow
    # local replica loses to a fast remote one once the measured gap
    # exceeds the boost). 1.0 = no locality preference.
    cluster_locality_boost: float = 1.0

    # --- Observability (docs/observability.md) ---
    metrics: bool = True                   # /metrics route + bus/http
    #                                        instrumentation wiring
    trace_sample: float = 1.0              # fresh-trace sample rate 0..1
    #                                        (incoming X-Trace-Id always
    #                                        honored)
    trace_max_mb: float = 64.0             # per-SEGMENT spans.jsonl size
    #                                        cap before a roll
    # Segmented span-store retention: how many rolled generations
    # (.1 .. .N, each sidecar-indexed for GET /trace/<id>) stay on
    # disk, and the total byte budget across them (oldest deleted
    # first; the newest rolled segment always survives).
    trace_retain_segments: int = 4
    trace_retain_mb: float = 256.0
    # Tail-based sampling, decided at trace COMPLETION on the minting
    # edge: error and slower-than-trace_tail_slow_ms traces are always
    # retained; fast/ok ones are kept at trace_tail_sample. 1.0 (the
    # default) disables tail sampling — every head-sampled trace is
    # written eagerly, the pre-r17 behavior. Head trace_sample
    # semantics are unchanged and apply first.
    trace_tail_sample: float = 1.0
    trace_tail_slow_ms: float = 250.0
    # OpenMetrics-style exemplars: histograms attach the last traced
    # observation's trace id per bucket to the exposition (and the
    # dashboard links p99 to its stitched timeline). Default off.
    metrics_exemplars: bool = False
    # Serving attribution ledger (docs/observability.md): per-bin and
    # per-tenant request/queue/device-time accounting at the serving
    # frontend and inference workers. Default OFF — disabled means one
    # None check per account site and ZERO rafiki_tpu_serving_bin_* /
    # serving_tenant_* series; the autoscaler consumes the per-bin
    # signals when a scraped frontend exposes them.
    serving_attribution: bool = False
    # --- SLO plane (docs/observability.md "SLOs & alerting") ---
    # Declarative objectives + multi-window burn-rate alerting over
    # the serving metrics (observe/slo.py): a path to a JSON/TOML
    # rules file (value ends .json/.toml) or the compact inline
    # grammar ("name:p99<50ms,window=300,...;..."). "" (the default)
    # disables the whole plane — supervise pays one attribute check
    # and a scrape shows ZERO rafiki_tpu_slo_* series.
    slo_rules: str = ""
    # Optional alert webhook: every alert transition is POSTed as one
    # JSON object (2 s timeout, best-effort) so an external pager can
    # attach. "" = off. Transitions always land in the bounded
    # <logs>/alerts.jsonl sink regardless.
    slo_webhook_url: str = ""
    # Size cap (MB) of the JSONL alert log before it rolls to one .1
    # generation.
    slo_alert_log_mb: float = 16.0

    # Workload recorder (docs/capacity.md): one JSONL arrival record
    # per /predict request at the predictor edge — what the capacity
    # engine replays. Default OFF — one bool check per request, zero
    # rafiki_tpu_workload_* series. The store rolls at
    # workload_max_mb per segment, keeping workload_retain_segments
    # rolled generations (the span store's discipline).
    workload_record: bool = False
    workload_max_mb: float = 64.0
    workload_retain_segments: int = 4

    # Metrics-only HTTP server for subprocess/docker worker runners
    # (they have no HTTP surface of their own). 0 = off; spawned
    # children inherit it via apply_env only when set.
    metrics_port: int = 0

    # Fields whose env names predate this layer (back-compat).
    _ENV_MAP = {
        "serving_pipeline": "RAFIKI_TPU_SERVING_PIPELINE",
        "checkpoint_trials": "RAFIKI_TPU_CKPT",
        "trace_dir": "RAFIKI_TPU_TRACE_DIR",
        "probe_timeout": "RAFIKI_TPU_PROBE_TIMEOUT",
    }
    _types_cache = None  # deliberately un-annotated: not fields
    _tristate_cache = None

    @classmethod
    def env_name(cls, field: str) -> str:
        return cls._ENV_MAP.get(field, _PREFIX + field.upper())

    @classmethod
    def from_env(cls, env: Optional[Mapping[str, str]] = None,
                 **overrides: Any) -> "NodeConfig":
        """Build from env vars; ``overrides`` (CLI args) win. An
        override of ``None`` means "not given" and is dropped."""
        env = os.environ if env is None else env
        values: Dict[str, Any] = {}
        for f in dataclasses.fields(cls):
            raw = env.get(cls.env_name(f.name))
            if raw is None:
                continue
            values[f.name] = cls._coerce(f.name, raw)
        values.update({k: v for k, v in overrides.items()
                       if v is not None})
        cfg = cls(**values)
        cfg.validate()
        return cfg

    @classmethod
    def _coerce(cls, name: str, raw: str) -> Any:
        target = cls._field_types().get(name, str)
        try:
            if target is bool:
                if name in cls._tristate_bools():
                    return parse_tristate_bool(raw)
                if raw.strip().lower() == "auto":
                    # Only tri-state (Optional[bool]) fields accept
                    # "auto"; on a plain bool it would silently become
                    # a falsy None (RAFIKI_TPU_CKPT=auto used to parse
                    # truthy) — reject loudly instead.
                    raise ValueError("'auto' is only valid for "
                                     "tri-state fields")
                return _parse_bool(raw)
            if target is int:
                return int(raw)
            if target is float:
                return float(raw)
        except ValueError as e:
            raise ValueError(
                f"{cls.env_name(name)}={raw!r}: {e}") from None
        return raw

    @classmethod
    def _field_types(cls) -> Dict[str, type]:
        """Resolved (Optional-unwrapped) scalar type per field. Fields
        whose hint is not a plain scalar / Optional[scalar] stay str —
        adding such a field must extend ``_coerce``, loudly, instead of
        being silently substring-matched to the wrong parser."""
        if cls._types_cache is None:
            resolved: Dict[str, type] = {}
            tristate = set()
            hints = typing.get_type_hints(cls)
            import types as _types

            # Optional[x] resolves to typing.Union; a PEP 604 `x | None`
            # resolves to types.UnionType — unwrap both.
            union_kinds = (Union, getattr(_types, "UnionType", Union))
            for f in dataclasses.fields(cls):
                hint = hints.get(f.name, str)
                if typing.get_origin(hint) in union_kinds:
                    args = [a for a in typing.get_args(hint)
                            if a is not type(None)]
                    hint = args[0] if len(args) == 1 else str
                    if hint is bool:
                        tristate.add(f.name)  # Optional[bool] = auto-able
                resolved[f.name] = hint if isinstance(hint, type) else str
            cls._types_cache = resolved
            cls._tristate_cache = tristate
        return cls._types_cache

    @classmethod
    def _tristate_bools(cls) -> set:
        cls._field_types()
        return cls._tristate_cache

    def validate(self) -> "NodeConfig":
        if not (0 <= self.port <= 65535):
            raise ValueError(f"port {self.port} out of range")
        if self.n_chips is not None and self.n_chips <= 0:
            raise ValueError("n_chips must be positive (or unset)")
        if self.supervise_interval < 0:
            raise ValueError("supervise_interval must be >= 0")
        if self.probe_timeout <= 0:
            raise ValueError("probe_timeout must be positive")
        if self.serving_fill_window < 0:
            raise ValueError("serving_fill_window must be >= 0")
        if self.serving_max_batch < 1 or self.serving_max_inflight < 1 \
                or self.serving_queue_cap < 1:
            raise ValueError("serving_max_batch, serving_max_inflight "
                             "and serving_queue_cap must be >= 1")
        fw_max = (self.serving_fill_window
                  if self.serving_fill_window_max is None
                  else self.serving_fill_window_max)
        if not (0 <= self.serving_fill_window_min <= fw_max):
            raise ValueError("need 0 <= serving_fill_window_min <= "
                             "serving_fill_window_max")
        if not (0.0 <= self.serving_client_share <= 1.0):
            raise ValueError("serving_client_share must be within "
                             "[0, 1]")
        if self.serving_cache_bytes < 0:
            raise ValueError("serving_cache_bytes must be >= 0 "
                             "(0 disables the edge cache)")
        if self.serving_cache_ttl_s <= 0:
            raise ValueError("serving_cache_ttl_s must be positive")
        if self.serving_cache_admit_after < 1:
            raise ValueError("serving_cache_admit_after must be >= 1 "
                             "(1 = admit on first touch)")
        if self.serving_tier_threshold < 0:
            raise ValueError("serving_tier_threshold must be >= 0 "
                             "(0 disables tiered serving)")
        # The accepted-spelling vocabularies live in observe.wire (the
        # env readers fail SAFE on anything outside them; config
        # rejects typos LOUDLY here — one list, two postures).
        from .observe.wire import (known_packed_wire_spelling,
                                   known_quant_spelling,
                                   known_stacked_spelling)

        if not known_packed_wire_spelling(self.serving_packed_wire):
            raise ValueError(
                f"serving_packed_wire {self.serving_packed_wire!r} is "
                f"not one of on/off/compat")
        if not known_quant_spelling(self.serving_quant):
            raise ValueError(
                f"serving_quant {self.serving_quant!r} is not one of "
                f"''/int8")
        if not known_stacked_spelling(self.serving_stacked):
            raise ValueError(
                f"serving_stacked {self.serving_stacked!r} is not one "
                f"of on/off")
        if self.generate_page_size < 1:
            raise ValueError("generate_page_size must be >= 1")
        if self.generate_pool_pages < 2:
            raise ValueError("generate_pool_pages must be >= 2 "
                             "(page 0 is reserved scratch)")
        if self.generate_decode_batch < 1:
            raise ValueError("generate_decode_batch must be >= 1")
        if self.generate_max_new < 1:
            raise ValueError("generate_max_new must be >= 1")
        if self.worker_reregister <= 0:
            raise ValueError("worker_reregister must be positive")
        if self.node_lease <= 0:
            raise ValueError("node_lease must be positive (it bounds "
                             "foreign-node liveness detection)")
        if self.pipeline_sync_min < 0:
            raise ValueError("pipeline_sync_min must be >= 0 (0 = "
                             "auto-pipeline whenever any sync latency "
                             "is measured)")
        if self.autoscale_max_replicas < 1 or self.autoscale_step < 1:
            raise ValueError("autoscale_max_replicas and autoscale_step "
                             "must be >= 1")
        if self.autoscale_up_cooldown_s < 0 \
                or self.autoscale_down_cooldown_s < 0:
            raise ValueError("autoscale cooldowns must be >= 0")
        if not (0.0 <= self.autoscale_queue_low
                <= self.autoscale_queue_high <= 1.0):
            raise ValueError("need 0 <= autoscale_queue_low <= "
                             "autoscale_queue_high <= 1")
        if self.autoscale_p99_high_ms < 0:
            raise ValueError("autoscale_p99_high_ms must be >= 0 "
                             "(0 = p99 not consulted)")
        if self.autoscale_mfu_floor < 0:
            raise ValueError("autoscale_mfu_floor must be >= 0 "
                             "(0 disables preemption)")
        if self.autoscale_idle_sweeps < 1:
            raise ValueError("autoscale_idle_sweeps must be >= 1")
        if self.autoscale_predict_horizon_s < 0:
            raise ValueError("autoscale_predict_horizon_s must be >= 0 "
                             "(0 disables predictive scale-ahead)")
        if self.autoscale_predict_ramp_ratio < 1.0:
            raise ValueError("autoscale_predict_ramp_ratio must be "
                             ">= 1 (a recurring ramp must mean MORE "
                             "load, not less)")
        if self.autoscale_periodicity.strip():
            # Parse now: a typo'd/missing table must fail the node's
            # construction, not silently predict nothing (the
            # fault-plan / slo-rules discipline).
            from .admin.capacity import load_periodicity

            load_periodicity(self.autoscale_periodicity)
        if self.max_chip_share < 1:
            raise ValueError("max_chip_share must be >= 1 (1 = no "
                             "time-sliced co-ownership)")
        if self.dataset_cache_bytes < 0 or self.stage_cache_bytes < 0:
            raise ValueError("dataset_cache_bytes and stage_cache_bytes "
                             "must be >= 0 (0 disables the cache)")
        if self.stage_bytes < 0:
            raise ValueError("stage_bytes must be >= 0 (0 forces "
                             "per-chunk staging)")
        if self.bus_retry_base_s <= 0:
            raise ValueError("bus_retry_base_s must be positive")
        if self.bus_retry_total_s < 0:
            raise ValueError("bus_retry_total_s must be >= 0 "
                             "(0 disables the retry budget)")
        if self.cluster_probe_timeout_s <= 0:
            raise ValueError("cluster_probe_timeout_s must be positive "
                             "(it bounds the single peer-cache probe)")
        if self.cluster_locality_boost < 1.0:
            raise ValueError("cluster_locality_boost must be >= 1 "
                             "(1.0 = no locality preference; below 1 "
                             "would PENALIZE same-node replicas)")
        if self.fault_plan.strip():
            # Parse now: a typo'd chaos plan must fail the node's
            # construction, not silently inject nothing.
            from .faults import FaultPlan

            FaultPlan.parse(self.fault_plan, seed=self.fault_seed)
        if not (0.0 <= self.trace_sample <= 1.0):
            raise ValueError("trace_sample must be within [0, 1]")
        if self.trace_max_mb <= 0:
            raise ValueError("trace_max_mb must be positive")
        if self.trace_retain_segments < 1:
            raise ValueError("trace_retain_segments must be >= 1 "
                             "(1 = the legacy single .1 generation)")
        if self.trace_retain_mb <= 0:
            raise ValueError("trace_retain_mb must be positive")
        if not (0.0 <= self.trace_tail_sample <= 1.0):
            raise ValueError("trace_tail_sample must be within [0, 1] "
                             "(1.0 disables tail sampling)")
        if self.trace_tail_slow_ms < 0:
            raise ValueError("trace_tail_slow_ms must be >= 0")
        if self.slo_rules.strip():
            # Parse now: a typo'd objective must fail the node's
            # construction, not silently judge nothing (the fault-plan
            # discipline). A file source must exist and parse here too.
            from .observe.slo import parse_rules

            parse_rules(self.slo_rules)
        if self.slo_webhook_url and not (
                self.slo_webhook_url.startswith("http://")
                or self.slo_webhook_url.startswith("https://")):
            raise ValueError(
                f"slo_webhook_url {self.slo_webhook_url!r} must be an "
                f"http(s) URL")
        if self.slo_alert_log_mb <= 0:
            raise ValueError("slo_alert_log_mb must be positive")
        if self.workload_max_mb <= 0:
            raise ValueError("workload_max_mb must be positive")
        if self.workload_retain_segments < 1:
            raise ValueError("workload_retain_segments must be >= 1")
        if not (0 <= self.metrics_port <= 65535):
            raise ValueError(f"metrics_port {self.metrics_port} out of "
                             f"range (0 = no standalone server)")
        if self.log_level.upper() not in (
                "DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL"):
            raise ValueError(f"unknown log_level {self.log_level!r}")
        multi = [self.coordinator != "", self.num_processes is not None,
                 self.process_id is not None]
        if any(multi) and not all(multi):
            raise ValueError("coordinator, num_processes and process_id "
                             "must be given together")
        if self.bus_uri and not (self.bus_uri.startswith("tcp://")
                                 or self.bus_uri.startswith("memory://")):
            raise ValueError(f"unsupported bus_uri {self.bus_uri!r}")
        return self

    def apply_env(self) -> None:
        """Export the service tunables so in-process workers and spawned
        children resolve the same values this node validated."""
        os.environ[self.env_name("serving_pipeline")] = \
            "auto" if self.serving_pipeline is None \
            else ("1" if self.serving_pipeline else "0")
        if self.checkpoint_trials:
            os.environ[self.env_name("checkpoint_trials")] = "1"
        else:
            os.environ.pop(self.env_name("checkpoint_trials"), None)
        if self.trace_dir:
            os.environ[self.env_name("trace_dir")] = self.trace_dir
        os.environ[self.env_name("probe_timeout")] = str(self.probe_timeout)
        # Micro-batcher knobs: the PredictorService reads these at
        # construction (it may be built in a spawned child or an
        # in-process thread — env is the one transport both inherit).
        os.environ[self.env_name("serving_microbatch")] = \
            "1" if self.serving_microbatch else "0"
        os.environ[self.env_name("serving_shard_replicas")] = \
            "1" if self.serving_shard_replicas else "0"
        for f in ("serving_fill_window", "serving_fill_window_min",
                  "serving_max_batch", "serving_max_inflight",
                  "serving_queue_cap", "serving_client_share",
                  "serving_cache_bytes", "serving_cache_ttl_s",
                  "serving_cache_admit_after"):
            os.environ[self.env_name(f)] = str(getattr(self, f))
        # Read at construction by Predictor / InferenceWorker directly
        # (not through the app-layer _env_knob helper), so RTA505
        # tracks these two by name.
        os.environ[self.env_name("serving_tier_threshold")] = \
            str(self.serving_tier_threshold)
        os.environ[self.env_name("worker_reregister")] = \
            str(self.worker_reregister)
        # Read at construction by ServicesManager (the lease window)
        # and InferenceWorker (the pipeline auto-probe threshold) — env
        # is the transport both in-process threads and spawned children
        # inherit, so RTA505 tracks these two by name.
        os.environ[self.env_name("node_lease")] = str(self.node_lease)
        os.environ[self.env_name("pipeline_sync_min")] = \
            str(self.pipeline_sync_min)
        # Generative serving: the InferenceWorker reads the gate and
        # the engine shape at construction (observe.lm resolves the
        # gate once at first use); the flag pops when off so "absent =
        # disabled" stays the contract for hand-launched children.
        if self.serving_generate:
            os.environ[self.env_name("serving_generate")] = "1"
        else:
            os.environ.pop(self.env_name("serving_generate"), None)
        # Spelled out one by one (not a loop) so RTA505 can track each
        # export by name, like the other construction-time knobs above.
        os.environ[self.env_name("generate_page_size")] = \
            str(self.generate_page_size)
        os.environ[self.env_name("generate_pool_pages")] = \
            str(self.generate_pool_pages)
        os.environ[self.env_name("generate_decode_batch")] = \
            str(self.generate_decode_batch)
        os.environ[self.env_name("generate_max_new")] = \
            str(self.generate_max_new)
        # Autoscaler: the platform constructs the controller from these
        # at startup (admin/autoscaler.py Autoscaler.from_env); the
        # enable flag is popped when off so "absent = disabled" stays
        # the contract for hand-launched children.
        if self.autoscale:
            os.environ[self.env_name("autoscale")] = "1"
        else:
            os.environ.pop(self.env_name("autoscale"), None)
        os.environ[self.env_name("autoscale_dry_run")] = \
            "1" if self.autoscale_dry_run else "0"
        for f in ("autoscale_max_replicas", "autoscale_step",
                  "autoscale_up_cooldown_s", "autoscale_down_cooldown_s",
                  "autoscale_queue_high", "autoscale_queue_low",
                  "autoscale_p99_high_ms", "autoscale_mfu_floor",
                  "autoscale_idle_sweeps",
                  "autoscale_predict_horizon_s",
                  "autoscale_predict_ramp_ratio"):
            os.environ[self.env_name(f)] = str(getattr(self, f))
        # Periodicity table path pops when empty so "absent = trend
        # signal only" stays the contract for hand-launched children.
        if self.autoscale_periodicity.strip():
            os.environ[self.env_name("autoscale_periodicity")] = \
                self.autoscale_periodicity
        else:
            os.environ.pop(self.env_name("autoscale_periodicity"), None)
        # Read per allocate() call by the chip allocator (a layer that
        # must work without a NodeConfig), so RTA505 tracks it by name.
        os.environ[self.env_name("max_chip_share")] = \
            str(self.max_chip_share)
        # Packed wire + quantization: Cache/Predictor/InferenceWorker
        # snapshot these at construction (observe.wire normalizes the
        # spellings); the quant knob pops when empty so a worker's
        # getenv default ("" = serve trained dtype) stays the contract.
        from .observe.wire import packed_wire_mode, stacked_mode

        os.environ[self.env_name("serving_packed_wire")] = \
            packed_wire_mode(self.serving_packed_wire)
        if self.serving_quant.strip():
            os.environ[self.env_name("serving_quant")] = \
                self.serving_quant
        else:
            os.environ.pop(self.env_name("serving_quant"), None)
        # Stacked serving: the InferenceWorker snapshots this at
        # construction (observe.wire normalizes the spellings).
        os.environ[self.env_name("serving_stacked")] = \
            "on" if stacked_mode(self.serving_stacked) else "off"
        # The adaptive ceiling defaults to the legacy fixed knob; only
        # an explicit override is exported (consumers fall back to
        # SERVING_FILL_WINDOW themselves).
        if self.serving_fill_window_max is not None:
            os.environ[self.env_name("serving_fill_window_max")] = \
                str(self.serving_fill_window_max)
        else:
            os.environ.pop(self.env_name("serving_fill_window_max"),
                           None)
        if self.serving_client_header:
            os.environ[self.env_name("serving_client_header")] = \
                self.serving_client_header
        else:
            os.environ.pop(self.env_name("serving_client_header"), None)
        # Trial-lifecycle knobs: the dataset/staging caches read their
        # budgets per call (model/dataset.py, model/jax_model.py); the
        # TrainWorker reads the prefetch toggle when its loop starts;
        # the ParamStore reads the write-behind toggle per save.
        os.environ[self.env_name("dataset_cache_bytes")] = \
            str(self.dataset_cache_bytes)
        os.environ[self.env_name("stage_cache_bytes")] = \
            str(self.stage_cache_bytes)
        os.environ[self.env_name("stage_bytes")] = str(self.stage_bytes)
        os.environ[self.env_name("advisor_prefetch")] = \
            "1" if self.advisor_prefetch else "0"
        os.environ[self.env_name("params_write_behind")] = \
            "1" if self.params_write_behind else "0"
        # Robustness: the fault plane and the tcp bus client read these
        # at construction; an empty plan is popped (absent = disabled),
        # matching the serving_client_header absent-means-off contract.
        if self.fault_plan.strip():
            os.environ[self.env_name("fault_plan")] = self.fault_plan
            os.environ[self.env_name("fault_seed")] = \
                str(self.fault_seed)
        else:
            os.environ.pop(self.env_name("fault_plan"), None)
            os.environ.pop(self.env_name("fault_seed"), None)
        os.environ[self.env_name("bus_retry_base_s")] = \
            str(self.bus_retry_base_s)
        os.environ[self.env_name("bus_retry_total_s")] = \
            str(self.bus_retry_total_s)
        # Cluster fabric: Predictor / PredictorService / ServicesManager
        # read the gate at construction; it pops when off so "absent =
        # disabled" stays the contract for hand-launched children (zero
        # node/relay/fabric series on an off node). The two tunables are
        # read at construction alongside it, so RTA505 tracks them by
        # name.
        if self.cluster_fabric:
            os.environ[self.env_name("cluster_fabric")] = "1"
        else:
            os.environ.pop(self.env_name("cluster_fabric"), None)
        os.environ[self.env_name("cluster_probe_timeout_s")] = \
            str(self.cluster_probe_timeout_s)
        os.environ[self.env_name("cluster_locality_boost")] = \
            str(self.cluster_locality_boost)
        # Observability: the /metrics route and bus/http instrumentation
        # check RAFIKI_TPU_METRICS at construction; the trace edges read
        # RAFIKI_TPU_TRACE_SAMPLE per request, the span sink its size
        # cap per flush.
        os.environ[self.env_name("metrics")] = \
            "1" if self.metrics else "0"
        os.environ[self.env_name("trace_sample")] = str(self.trace_sample)
        os.environ[self.env_name("trace_max_mb")] = str(self.trace_max_mb)
        # Span-store retention + tail sampling: the sink reads these
        # per roll / per mint, so late-spawned children and in-process
        # services resolve the same store shape. The tail knob pops at
        # 1.0 (absent = tail off) so the legacy eager-write contract
        # stays the default for hand-launched children.
        os.environ[self.env_name("trace_retain_segments")] = \
            str(self.trace_retain_segments)
        os.environ[self.env_name("trace_retain_mb")] = \
            str(self.trace_retain_mb)
        if self.trace_tail_sample < 1.0:
            os.environ[self.env_name("trace_tail_sample")] = \
                str(self.trace_tail_sample)
        else:
            os.environ.pop(self.env_name("trace_tail_sample"), None)
        os.environ[self.env_name("trace_tail_slow_ms")] = \
            str(self.trace_tail_slow_ms)
        # Exemplars + the attribution ledger resolve once at first use
        # (observe.metrics / observe.attribution); both pop when off so
        # "absent = disabled" stays the contract.
        if self.metrics_exemplars:
            os.environ[self.env_name("metrics_exemplars")] = "1"
        else:
            os.environ.pop(self.env_name("metrics_exemplars"), None)
        if self.serving_attribution:
            os.environ[self.env_name("serving_attribution")] = "1"
        else:
            os.environ.pop(self.env_name("serving_attribution"), None)
        # SLO plane: the platform constructs the engine from these at
        # startup (admin/slo_engine.py SloEngine.from_env); rules and
        # webhook pop when empty so "absent = disabled" stays the
        # contract for hand-launched children.
        if self.slo_rules.strip():
            os.environ[self.env_name("slo_rules")] = self.slo_rules
        else:
            os.environ.pop(self.env_name("slo_rules"), None)
        if self.slo_webhook_url:
            os.environ[self.env_name("slo_webhook_url")] = \
                self.slo_webhook_url
        else:
            os.environ.pop(self.env_name("slo_webhook_url"), None)
        os.environ[self.env_name("slo_alert_log_mb")] = \
            str(self.slo_alert_log_mb)
        # Workload recorder: the predictor edge resolves the gate once
        # at first use (observe.workload); pops when off so "absent =
        # disabled" stays the contract (the attribution pattern). The
        # store knobs are read per roll by the sink.
        if self.workload_record:
            os.environ[self.env_name("workload_record")] = "1"
        else:
            os.environ.pop(self.env_name("workload_record"), None)
        os.environ[self.env_name("workload_max_mb")] = \
            str(self.workload_max_mb)
        os.environ[self.env_name("workload_retain_segments")] = \
            str(self.workload_retain_segments)
        # 0 = "no standalone metrics server": exporting "0" would make
        # worker runners bind port 0 (a random free port) — pop instead,
        # mirroring serving_client_header's absent-means-off contract.
        if self.metrics_port:
            os.environ[self.env_name("metrics_port")] = \
                str(self.metrics_port)
        else:
            os.environ.pop(self.env_name("metrics_port"), None)
