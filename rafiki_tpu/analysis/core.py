"""Framework core: finding model, checker registry, waivers, baseline.

Design constraints, in order:

- **Stdlib only, zero imports from the rest of the package.** The suite
  must run where jax cannot (pre-commit hooks, the docs CI image) and
  must not execute the code it analyzes — everything is ``ast`` over
  source text. The one exception is the drift checker *loading*
  ``config.py`` by file path (exactly as ``scripts/check_knob_docs.py``
  always did) — that module is import-light by contract.
- **Stable finding identity.** Baselines must survive unrelated edits,
  so a finding's identity is ``CODE:path:anchor`` where ``anchor`` is a
  checker-chosen symbol (``MicroBatcher._dt_ewma@current_fill_window``,
  an env-var name, a metric name) — never a line number.
- **A waiver is a reviewed decision, not an escape hatch.** Inline
  waivers (``# rta: disable=RTA101 <reason>``) and baseline entries
  both REQUIRE a reason; a reasonless one is itself a finding (RTA001/
  RTA002) that cannot be waived.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import io
import json
import os
import re
import subprocess
import time
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: Meta-codes emitted by the framework itself (not waivable).
CODE_WAIVER_NO_REASON = "RTA001"
CODE_BASELINE_NO_REASON = "RTA002"
CODE_STALE_WAIVER = "RTA003"
_UNWAIVABLE = {CODE_WAIVER_NO_REASON, CODE_BASELINE_NO_REASON,
               CODE_STALE_WAIVER}

WAIVER_RE = re.compile(
    r"#\s*rta:\s*disable=([A-Z0-9x,]+)(?:\s+(\S.*))?\s*$")


@dataclasses.dataclass
class Finding:
    """One defect the suite reports.

    ``anchor`` is the stable symbol the baseline keys on; checkers MUST
    set one that survives line drift (class.attr, env name, ...).
    ``status`` is assigned by :func:`run_suite`: ``new`` (fails CI),
    ``waived`` (inline comment), or ``baselined`` (frozen pre-existing).
    """

    code: str
    path: str            # repo-relative, forward slashes
    line: int
    message: str
    hint: str = ""
    anchor: str = ""
    status: str = "new"
    reason: str = ""     # the waiver/baseline reason, when not new

    @property
    def ident(self) -> str:
        return f"{self.code}:{self.path}:{self.anchor or self.line}"

    def render(self) -> str:
        out = f"{self.path}:{self.line}: {self.code} {self.message}"
        if self.hint:
            out += f" [hint: {self.hint}]"
        return out

    def to_json(self) -> Dict[str, object]:
        return {"code": self.code, "path": self.path, "line": self.line,
                "message": self.message, "hint": self.hint,
                "id": self.ident, "status": self.status,
                "reason": self.reason}


class Module:
    """One parsed source file. ``tree`` is None on a syntax error (the
    error itself is reported by :func:`run_suite`, so a checker never
    needs to guard against it)."""

    def __init__(self, root: str, rel: str):
        self.rel = rel.replace(os.sep, "/")
        self.path = os.path.join(root, rel)
        with open(self.path, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.syntax_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.text, filename=self.rel)
        except SyntaxError as e:
            self.syntax_error = f"{e.msg} (line {e.lineno})"

    def waivers(self) -> Dict[int, Tuple[Set[str], str]]:
        """line -> (codes, reason). Only REAL comment tokens count:
        waiver-shaped text inside a string/docstring must neither
        suppress a finding nor mint a phantom RTA001. Cached on first
        use."""
        cached = getattr(self, "_waivers", None)
        if cached is None:
            cached = {}
            for line, comment in self._comments():
                m = WAIVER_RE.search(comment)
                if m:
                    codes = {c.strip() for c in m.group(1).split(",")
                             if c.strip()}
                    cached[line] = (codes, (m.group(2) or "").strip())
            self._waivers = cached
        return cached

    def _comments(self) -> List[Tuple[int, str]]:
        """(line, text) of every comment token. On a file the tokenizer
        rejects (already an RTA000 finding) fall back to raw lines so a
        waiver on a salvageable line still parses."""
        out: List[Tuple[int, str]] = []
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    out.append((tok.start[0], tok.string))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return [(i, ln) for i, ln in enumerate(self.lines, 1)]
        return out


class RepoContext:
    """Everything a checker may look at: the parsed package modules,
    non-Python repo files, and (in ``--changed`` mode) the changed set.
    """

    #: Directories scanned for Python modules, relative to root.
    PY_ROOTS = ("rafiki_tpu",)

    def __init__(self, root: str, changed: Optional[Set[str]] = None):
        self.root = os.path.abspath(root)
        self.changed = ({c.replace(os.sep, "/") for c in changed}
                        if changed is not None else None)
        self.modules: List[Module] = []
        for pyroot in self.PY_ROOTS:
            top = os.path.join(self.root, pyroot)
            if not os.path.isdir(top):
                continue
            for dirpath, dirnames, filenames in os.walk(top):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__")
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        rel = os.path.relpath(os.path.join(dirpath, fn),
                                              self.root)
                        self.modules.append(Module(self.root, rel))

    def target_modules(self) -> List[Module]:
        """Modules a per-file checker should flag: all of them, or the
        changed subset in ``--changed`` mode."""
        if self.changed is None:
            return self.modules
        return [m for m in self.modules if m.rel in self.changed]

    def program(self):
        """The whole-program model (``analysis.program.Program``) over
        ALL parsed modules, built lazily ONCE per run and shared by
        every checker — the single-parse/single-walk contract. Always
        repo-wide, even in ``--changed`` mode: interprocedural facts
        (a lock chain ending three modules away) are only sound with
        the full symbol table."""
        cached = getattr(self, "_program", None)
        if cached is None:
            from . import program as _program

            cached = _program.Program(self.modules)
            self._program = cached
        return cached

class Checker:
    """Base class; subclasses register via :func:`register`.

    ``scope`` is ``"file"`` (operates on ``ctx.target_modules()``; in
    ``--changed`` mode it simply sees fewer modules) or ``"repo"``
    (needs a global view — runs when any changed path matches
    ``triggers``, and always in full runs).
    """

    name = "base"
    codes: Tuple[str, ...] = ()
    scope = "file"
    #: fnmatch patterns (repo-relative) that make a repo-scope checker
    #: run in --changed mode.
    triggers: Tuple[str, ...] = ("rafiki_tpu/*", "rafiki_tpu/*/*",
                                 "rafiki_tpu/*/*/*")

    def run(self, ctx: RepoContext) -> List[Finding]:
        raise NotImplementedError

    def should_run(self, ctx: RepoContext) -> bool:
        if ctx.changed is None or self.scope == "file":
            return True
        return any(fnmatch.fnmatch(c, pat) for c in ctx.changed
                   for pat in self.triggers)


_CHECKERS: List[Checker] = []


def register(checker_cls):
    """Class decorator; instantiates and registers the checker."""
    _CHECKERS.append(checker_cls())
    return checker_cls


def all_checkers() -> List[Checker]:
    from . import checkers  # noqa: F401  (import registers them)

    return list(_CHECKERS)


# --- Baseline ---------------------------------------------------------

def baseline_path() -> str:
    """The committed baseline that freezes pre-existing findings."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: Optional[str]) -> Dict[str, str]:
    """id -> reason. Missing file = empty baseline (fresh tree)."""
    if not path or not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {e["id"]: e.get("reason", "")
            for e in data.get("findings", [])}


def save_baseline(path: str, findings: Iterable[Finding],
                  prior: Dict[str, str]) -> int:
    """``--update-baseline``: freeze the current new findings, keeping
    the reason of every entry that already had one. New entries get an
    UNREVIEWED placeholder that RTA002 keeps failing until a human
    writes the real reason — updating the baseline is never silently
    green."""
    entries = []
    seen = set()
    for f in sorted(findings, key=lambda f: f.ident):
        # Meta-findings (reasonless waiver/baseline entry) are never
        # consulted from the baseline at classification time, so
        # freezing them would only accrete dead line-anchored entries.
        if f.status == "waived" or f.ident in seen \
                or f.code in _UNWAIVABLE:
            continue
        seen.add(f.ident)
        reason = prior.get(f.ident, "")
        entries.append({
            "id": f.ident, "reason": reason or
            "UNREVIEWED: replace with why this finding is accepted",
            "where": f"{f.path}:{f.line}", "message": f.message})
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "findings": entries}, f, indent=1,
                  sort_keys=False)
        f.write("\n")
    return len(entries)


# --- Suite ------------------------------------------------------------

@dataclasses.dataclass
class Report:
    root: str
    findings: List[Finding]
    n_files: int
    checkers: List[str]
    stale_baseline: List[str]
    #: Every code a checker that RAN could have emitted — so
    #: counts_per_code carries explicit zeros (bench.py --config
    #: analysis records per-code counts; a zero for RTA104 is
    #: evidence the gate looked, absence would be ambiguous).
    covered_codes: List[str] = dataclasses.field(default_factory=list)
    #: Per-checker wall time (seconds) — the --diff mode's cost
    #: breakdown, so a checker that stops scaling is visible in CI
    #: output instead of as a slowly rotting gate latency.
    timings: Dict[str, float] = dataclasses.field(default_factory=dict)

    @property
    def new(self) -> List[Finding]:
        return [f for f in self.findings if f.status == "new"]

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {c: 0 for c in self.covered_codes}
        for f in self.findings:
            out[f.code] = out.get(f.code, 0) + 1
        return dict(sorted(out.items()))

    def to_json(self) -> Dict[str, object]:
        by_status: Dict[str, int] = {}
        for f in self.findings:
            by_status[f.status] = by_status.get(f.status, 0) + 1
        return {
            "root": self.root,
            "files": self.n_files,
            "checkers": self.checkers,
            "counts_per_code": self.counts(),
            "by_status": by_status,
            "new": len(self.new),
            "stale_baseline": self.stale_baseline,
            "timings_s": {k: round(v, 4)
                          for k, v in self.timings.items()},
            "findings": [f.to_json() for f in self.findings],
        }


def run_suite(root: str, changed: Optional[Set[str]] = None,
              baseline: Optional[Dict[str, str]] = None,
              only: Optional[Sequence[str]] = None) -> Report:
    """Run every registered checker and classify findings against the
    inline waivers and the baseline. ``only`` filters by checker name.
    """
    ctx = RepoContext(root, changed=changed)
    baseline = baseline or {}
    findings: List[Finding] = []

    # A file the suite cannot parse is a finding, not a crash.
    for mod in ctx.target_modules():
        if mod.syntax_error is not None:
            findings.append(Finding(
                code="RTA000", path=mod.rel, line=1,
                message=f"syntax error: {mod.syntax_error}",
                anchor="syntax"))

    ran = []
    covered: List[str] = []
    timings: Dict[str, float] = {}
    for checker in all_checkers():
        if only and checker.name not in only:
            continue
        if not checker.should_run(ctx):
            continue
        ran.append(checker.name)
        covered.extend(checker.codes)
        t0 = time.perf_counter()
        findings.extend(checker.run(ctx))
        timings[checker.name] = time.perf_counter() - t0

    # Reason-less waivers are findings in their own right, everywhere
    # (including modules no checker flagged).
    waiver_index: Dict[str, Dict[int, Tuple[Set[str], str]]] = {}
    for mod in ctx.modules:
        w = mod.waivers()
        if w:
            waiver_index[mod.rel] = w
        if ctx.changed is None or mod.rel in ctx.changed:
            for line, (codes, reason) in w.items():
                if not reason:
                    findings.append(Finding(
                        code=CODE_WAIVER_NO_REASON, path=mod.rel,
                        line=line,
                        message="waiver without a reason: "
                                "`# rta: disable=%s` must say why"
                                % ",".join(sorted(codes)),
                        anchor=f"waiver:{line}"))

    # Classify: inline waiver first (same line or the line above the
    # finding — the comment-above form keeps long lines readable),
    # baseline second. Waiver lines that actually suppressed a finding
    # are remembered: a reasoned waiver no finding matches anymore is
    # itself a finding (RTA003 below) — silently rotting disables are
    # how a real regression later slips in pre-waived.
    used_waivers: Set[Tuple[str, int]] = set()
    seen: Set[str] = set()
    deduped: List[Finding] = []
    for f in findings:
        if f.ident in seen:
            continue
        seen.add(f.ident)
        if f.code not in _UNWAIVABLE:
            waivers = waiver_index.get(f.path, {})
            for line in (f.line, f.line - 1):
                entry = waivers.get(line)
                if entry and _waiver_covers(entry[0], f.code) \
                        and entry[1]:
                    f.status, f.reason = "waived", entry[1]
                    used_waivers.add((f.path, line))
                    break
            if f.status == "new" and f.ident in baseline:
                reason = baseline[f.ident]
                if reason and not reason.startswith("UNREVIEWED"):
                    f.status, f.reason = "baselined", reason
                else:
                    deduped.append(Finding(
                        code=CODE_BASELINE_NO_REASON, path=f.path,
                        line=f.line,
                        message=f"baseline entry {f.ident} has no "
                                f"reviewed reason",
                        anchor=f"baseline:{f.ident}"))
                    f.status, f.reason = "baselined", reason
        deduped.append(f)

    # Stale-WAIVER detection (RTA003): a reasoned `# rta: disable=`
    # comment that suppressed nothing this run is dead — either the
    # guarded defect was fixed (delete the comment) or the code it
    # names is a typo (it never guarded anything). Only sound when the
    # full file view ran (``--changed`` skips unscanned modules whose
    # waivers would all read unused); under ``--checker`` scoping a
    # waiver counts only when a ran checker COVERS one of its codes.
    if changed is None:
        for mod in ctx.modules:
            for line, (codes, reason) in mod.waivers().items():
                if not reason or (mod.rel, line) in used_waivers:
                    continue  # reasonless = RTA001's finding already
                if only and not any(_code_covered(c, covered)
                                    for c in codes):
                    continue  # that checker didn't run this time
                deduped.append(Finding(
                    code=CODE_STALE_WAIVER, path=mod.rel, line=line,
                    message="stale waiver: `# rta: disable=%s` "
                            "suppresses nothing — the finding no "
                            "longer fires (or the code is unknown); "
                            "delete the comment"
                            % ",".join(sorted(codes)),
                    hint="a dead disable pre-waives the NEXT "
                         "regression on this line; remove it (or fix "
                         "the code list if it was a typo)",
                    anchor=f"stale-waiver:{line}"))

    # Stale detection is only sound on a FULL run: a scoped run
    # (--changed / --checker) never produces findings for unscanned
    # files or checkers, so their live baseline entries would all look
    # "fixed".
    if changed is None and not only:
        stale = sorted(set(baseline) - {f.ident for f in deduped})
    else:
        stale = []
    return Report(root=ctx.root, findings=deduped,
                  n_files=len(ctx.modules), checkers=ran,
                  stale_baseline=stale, covered_codes=covered,
                  timings=timings)


def _waiver_covers(codes: Set[str], code: str) -> bool:
    """``RTA101`` matches exactly; ``RTA1xx`` waives the whole class."""
    if code in codes:
        return True
    return any(c.endswith("xx") and code.startswith(c[:-2])
               for c in codes)


def _code_covered(code: str, covered: Sequence[str]) -> bool:
    """Whether a waiver's ``code`` (exact or ``RTAxx`` class form)
    belongs to a checker that RAN — the RTA003 scoping guard.
    Framework meta-codes count as always covered (run_suite itself
    emits them every run, and they are unwaivable — a waiver naming
    one is dead by construction)."""
    if code in covered or code in _UNWAIVABLE or code == "RTA000":
        return True
    if code.endswith("xx"):
        return any(c.startswith(code[:-2]) for c in covered)
    return False


# --- Git (--changed mode) --------------------------------------------

def changed_files(root: str, base: Optional[str] = None) -> Set[str]:
    """Repo-relative paths touched since the merge-base with main plus
    anything uncommitted/untracked — the fast pre-commit scope. An
    explicit ``base`` (``--diff <base>``) pins the comparison point
    instead of discovering it (CI diffing a PR against its merge
    target, or re-running against an arbitrary commit)."""

    def git(*args: str) -> List[str]:
        try:
            out = subprocess.run(
                ["git", "-C", root, *args], capture_output=True,
                text=True, timeout=30)
        except OSError:
            return []
        if out.returncode != 0:
            return []
        return [ln.strip() for ln in out.stdout.splitlines()
                if ln.strip()]

    if base is None:
        base = "HEAD"
        for ref in ("origin/main", "origin/master", "main", "master"):
            mb = git("merge-base", "HEAD", ref)
            if mb:
                base = mb[0]
                break
    changed: Set[str] = set()
    changed.update(git("diff", "--name-only", base))
    changed.update(git("diff", "--name-only"))           # worktree
    changed.update(git("diff", "--name-only", "--cached"))
    changed.update(git("ls-files", "--others", "--exclude-standard"))
    return {c.replace(os.sep, "/") for c in changed}


def repo_root() -> str:
    """The checkout this package sits in (three levels up)."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))
