"""CLI: ``python -m rafiki_tpu.analysis [--changed] [--json]
[--update-baseline]``.

Exit 0 = no NEW findings (everything is fixed, waived with a reason,
or frozen in the committed baseline); exit 1 otherwise. ``--changed``
scopes per-file checkers to files touched since the merge-base with
main (plus uncommitted work) for fast pre-commit runs; repo-scope
checkers still run when one of their trigger files changed.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import core


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m rafiki_tpu.analysis",
        description="Repo-native static analysis suite "
                    "(docs/analysis.md)")
    parser.add_argument("--root", default=None,
                        help="repo root (default: this checkout)")
    parser.add_argument("--changed", action="store_true",
                        help="only analyze files changed vs the "
                             "merge-base with main + uncommitted work")
    parser.add_argument("--diff", metavar="BASE", default=None,
                        help="incremental mode vs an explicit git "
                             "base (commit/ref): file-local checkers "
                             "see only changed files, whole-program "
                             "checkers still run on the full model "
                             "when triggered; prints per-checker "
                             "wall time")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the full machine-readable report")
    parser.add_argument("--update-baseline", action="store_true",
                        help="freeze current findings into the "
                             "baseline (keeps existing reasons; new "
                             "entries get an UNREVIEWED placeholder "
                             "that still fails)")
    parser.add_argument("--baseline", default=None,
                        help="baseline file (default: the committed "
                             "rafiki_tpu/analysis/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline (report everything)")
    parser.add_argument("--checker", action="append", default=None,
                        help="run only this checker (repeatable); "
                             "names: " + ", ".join(
                                 c.name for c in core.all_checkers()))
    parser.add_argument("--explain", metavar="CODE", default=None,
                        help="print the catalog entry + fix hint for "
                             "one RTA code and exit (self-serve on a "
                             "red gate)")
    args = parser.parse_args(argv)
    if args.explain is not None:
        from .catalog import CATALOG, explain

        code = args.explain.strip().upper()
        if code not in CATALOG:
            parser.error("unknown code %s (known: %s)"
                         % (code, ", ".join(sorted(CATALOG))))
        print(explain(code))
        return 0
    if args.checker:
        known = {c.name for c in core.all_checkers()}
        bad = sorted(set(args.checker) - known)
        if bad:
            # An unknown name would otherwise filter out EVERY checker
            # and exit 0 — a typo'd CI invocation must not go green.
            parser.error("unknown checker(s): %s (names: %s)"
                         % (", ".join(bad), ", ".join(sorted(known))))
    if args.update_baseline and (args.changed or args.checker
                                 or args.diff):
        # A scoped run never produces findings for unscanned files or
        # checkers, so rewriting the baseline from it would silently
        # drop every frozen entry outside the scope.
        parser.error("--update-baseline requires a full run "
                     "(drop --changed/--checker/--diff)")
    if args.changed and args.diff:
        parser.error("--changed and --diff are the same mode with "
                     "different bases; pick one")

    root = args.root or core.repo_root()
    bl_path = args.baseline or core.baseline_path()
    baseline = {} if args.no_baseline else core.load_baseline(bl_path)
    changed = None
    if args.diff is not None:
        changed = core.changed_files(root, base=args.diff)
    elif args.changed:
        changed = core.changed_files(root)

    report = core.run_suite(root, changed=changed, baseline=baseline,
                            only=args.checker)

    if args.update_baseline:
        n = core.save_baseline(bl_path, report.findings, baseline)
        print(f"baseline: wrote {n} entries to {bl_path}", file=sys.stderr)
        # Re-classify against what was just written so the printed
        # report (and exit code) reflect the new baseline — entries
        # with an UNREVIEWED placeholder still fail via RTA002.
        report = core.run_suite(root, changed=changed,
                                baseline=core.load_baseline(bl_path),
                                only=args.checker)

    if args.as_json:
        print(json.dumps(report.to_json(), indent=1))
    else:
        for f in sorted(report.new,
                        key=lambda f: (f.path, f.line, f.code)):
            print(f.render())
        n_waived = sum(1 for f in report.findings
                       if f.status == "waived")
        n_base = sum(1 for f in report.findings
                     if f.status == "baselined")
        if report.stale_baseline:
            print(f"note: {len(report.stale_baseline)} stale baseline "
                  f"entr{'y' if len(report.stale_baseline) == 1 else 'ies'} "
                  f"(fixed findings — run --update-baseline to prune):",
                  file=sys.stderr)
            for ident in report.stale_baseline:
                print(f"  {ident}", file=sys.stderr)
        verdict = ("ok" if not report.new else
                   f"{len(report.new)} new finding(s)")
        print(f"{verdict}: {report.n_files} files, "
              f"{len(report.findings)} findings "
              f"({n_base} baselined, {n_waived} waived) "
              f"[checkers: {', '.join(report.checkers)}]")
        if args.diff is not None:
            times = "  ".join(f"{k} {v:.2f}s" for k, v in
                              sorted(report.timings.items(),
                                     key=lambda kv: -kv[1]))
            print(f"timings: {times}", file=sys.stderr)
    return 1 if report.new else 0


if __name__ == "__main__":
    sys.exit(main())
