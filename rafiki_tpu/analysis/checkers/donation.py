"""RTA4xx — jax buffer donation vs escaped/cached values.

Historical bug this encodes: the r9 staged-arrays hazard. The device
staging cache keeps the replicated dataset arrays resident across
trials; if any compiled step ever listed them in ``donate_argnums``,
XLA would free the cached buffers out from under every later trial —
a use-after-free that only manifests as corrupted results or an
``is_deleted`` crash trials later. PR 4 shipped a never-donate guard
(only the train state is donated) plus a defensive re-stage check;
this checker makes the invariant mechanical.

Mechanics (module-scope, two-level dataflow — no execution):

- **Donating functions**: ``@jax.jit(donate_argnums=...)`` /
  ``@partial(jax.jit, donate_argnums=...)`` decorated defs and
  ``f2 = jax.jit(f, donate_argnums=...)`` bindings; plain-name
  aliases (``exe = train_chunk``) inherit the donation signature.
- **Forwarders**: a local function that passes its own parameter to a
  donating function at a donated position donates that parameter
  itself (the AOT ``dispatch`` wrapper pattern).
- **Cache-tainted values**: names assigned (possibly through tuple
  unpacking) from a call whose name mentions ``stage``/``cache``, or
  from a subscript/attribute of a ``*_CACHE`` global.
- **Taint through helper returns** (r13): a module function whose
  RETURN expression is cache-tainted taints its call sites by name —
  a neutral-named wrapper (``def fetch_resident(): return
  _STAGE_CACHE[k]``) poisons exactly like the direct read. Fixpoint
  over the module's defs, so helper-calls-helper chains resolve.

RTA401: a cache-tainted value is passed at a donated position.
RTA402: a name passed at a donated position is read again later in
the same scope with no rebind in between (use-after-donate); the
``state, m = step(state, ...)`` rebind idiom is recognized as safe.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ..core import Checker, Finding, RepoContext, register

_CACHE_CALL_RE = re.compile(r"stage|cache", re.IGNORECASE)
_CACHE_GLOBAL_RE = re.compile(r"_CACHE\b")


def _last_name(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _donate_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """``donate_argnums`` from a ``jax.jit``/``partial(jax.jit, ...)``
    call expression, or None when it doesn't donate."""
    is_jit = _last_name(call.func) == "jit"
    is_partial = _last_name(call.func) == "partial" and call.args and \
        _last_name(call.args[0]) == "jit"
    if not (is_jit or is_partial):
        return None
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            v = kw.value
            if isinstance(v, (ast.Tuple, ast.List)):
                out = tuple(el.value for el in v.elts
                            if isinstance(el, ast.Constant)
                            and isinstance(el.value, int))
                return out or None
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
    return None


class _Scope:
    """One function body (or the module body): tainted names, donating
    call sites, assignments — enough for the RTA401/402 judgments.
    ``tainted_fns`` are module helpers whose returns are cache-tainted
    (see ``_return_tainted_fns``) — calls to them taint like direct
    cache reads."""

    def __init__(self, node, name: str,
                 tainted_fns: frozenset = frozenset()):
        self.node = node
        self.name = name
        self.tainted_fns = tainted_fns
        self.tainted: Set[str] = set()
        # name -> lines where the name is (re)bound
        self.binds: Dict[str, List[int]] = {}
        # name -> lines where the name is read
        self.loads: Dict[str, List[int]] = {}
        self.calls: List[ast.Call] = []
        self.aliases: Dict[str, Set[str]] = {}  # name -> aliased names

    def body_stmts(self):
        return self.node.body

    def analyze(self) -> None:
        # walk, but do not descend into nested function bodies — they
        # are their own scopes (we still record the def line as a bind).
        stack = list(self.body_stmts())
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.binds.setdefault(node.name, []).append(node.lineno)
                continue
            if isinstance(node, ast.Lambda):
                continue
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    self._bind(tgt, node.value)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) and \
                    node.value is not None:
                self._bind(node.target, node.value)
            elif isinstance(node, ast.Call):
                self.calls.append(node)
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load):
                self.loads.setdefault(node.id, []).append(node.lineno)
            stack.extend(ast.iter_child_nodes(node))
        # Taint closure over plain aliases (a = b chains), 2 rounds.
        for _ in range(2):
            for name, srcs in self.aliases.items():
                if srcs & self.tainted:
                    self.tainted.add(name)

    def _bind(self, tgt: ast.AST, value: ast.AST) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            values = value.elts if isinstance(
                value, (ast.Tuple, ast.List)) and \
                len(value.elts) == len(tgt.elts) else \
                [value] * len(tgt.elts)
            for el, v in zip(tgt.elts, values):
                self._bind(el, v)
            return
        if not isinstance(tgt, ast.Name):
            return
        self.binds.setdefault(tgt.id, []).append(tgt.lineno)
        if _expr_tainted(value, self.tainted_fns):
            self.tainted.add(tgt.id)
        elif isinstance(value, ast.Name):
            self.aliases.setdefault(tgt.id, set()).add(value.id)


def _expr_tainted(value: ast.AST,
                  tainted_fns: frozenset = frozenset()) -> bool:
    """Does this RHS pull from a staging/residency cache — directly,
    or through a helper whose return is tainted (``tainted_fns``)?"""
    if isinstance(value, ast.Call):
        name = _last_name(value.func)
        if _CACHE_CALL_RE.search(name) or name in tainted_fns:
            return True
        # one level deep: _STAGE_CACHE.get(...)
        if isinstance(value.func, ast.Attribute):
            return _expr_tainted(value.func.value, tainted_fns)
        return False
    if isinstance(value, ast.Subscript) or isinstance(value,
                                                      ast.Attribute):
        return _expr_tainted(value.value, tainted_fns)
    if isinstance(value, ast.Name):
        return bool(_CACHE_GLOBAL_RE.search(value.id))
    return False


def _own_returns(fn) -> List[ast.AST]:
    """``return`` expressions of ``fn``'s OWN body (nested defs are
    their own scopes and must not leak their returns up)."""
    out: List[ast.AST] = []
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Return) and node.value is not None:
            out.append(node.value)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _return_tainted_fns(tree: ast.AST) -> frozenset:
    """Names of functions whose return value is cache-tainted — the
    r13 taint-through-helper-returns pass. Iterates to a TRUE fixpoint
    (a fixed round count would silently miss depth-3+ helper chains in
    adversarial definition order); each round can only grow the set,
    so it terminates within len(fns) rounds. Matching at call sites is
    by LAST name (methods included), same as the donating-function
    lookup."""
    fns = [n for n in ast.walk(tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    tainted: set = set()
    changed = True
    while changed:
        changed = False
        for fn in fns:
            if fn.name in tainted:
                continue
            scope = _Scope(fn, fn.name, tainted_fns=frozenset(tainted))
            scope.analyze()

            def ret_tainted(expr: ast.AST) -> bool:
                if isinstance(expr, ast.Tuple):
                    return any(ret_tainted(el) for el in expr.elts)
                if isinstance(expr, ast.Name):
                    return expr.id in scope.tainted or \
                        _expr_tainted(expr, frozenset(tainted))
                return _expr_tainted(expr, frozenset(tainted))

            if any(ret_tainted(r) for r in _own_returns(fn)):
                tainted.add(fn.name)
                changed = True
    return frozenset(tainted)


@register
class DonationChecker(Checker):
    name = "donation"
    codes = ("RTA401", "RTA402")

    def run(self, ctx: RepoContext) -> List[Finding]:
        findings: List[Finding] = []
        for mod in ctx.target_modules():
            if mod.tree is None or "donate" not in mod.text:
                continue
            findings.extend(self._check_module(mod.rel, mod.tree))
        return findings

    # --- per module ---

    def _check_module(self, rel: str, tree: ast.AST) -> List[Finding]:
        donating: Dict[str, Dict[int, str]] = {}  # fn -> {pos: param}

        # Pass A: decorated defs + jax.jit(f, donate_argnums=...) binds.
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        pos = _donate_positions(dec)
                        if pos:
                            donating[node.name] = self._params_at(
                                node, pos)
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                pos = _donate_positions(node.value)
                if pos and node.value.args:
                    inner = node.value.args[0]
                    if _last_name(node.value.func) == "partial":
                        inner = None  # partial(jax.jit, ...) is a decorator
                    if isinstance(inner, ast.Name):
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                donating[tgt.id] = {
                                    p: f"arg{p}" for p in pos}

        if not donating:
            return []

        # Pass B: plain-name aliases (exe = train_chunk) and forwarders
        # (dispatch passes its param at a donated position), 2 rounds.
        ret_tainted = _return_tainted_fns(tree)
        scopes = self._scopes(tree, ret_tainted)
        for _ in range(2):
            for scope in scopes:
                for stmt in ast.walk(scope.node):
                    if isinstance(stmt, ast.Assign) and \
                            isinstance(stmt.value, ast.Name) and \
                            stmt.value.id in donating:
                        for tgt in stmt.targets:
                            if isinstance(tgt, ast.Name) and \
                                    tgt.id not in donating:
                                donating[tgt.id] = donating[
                                    stmt.value.id]
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                fwd = self._forwarded_positions(node, donating)
                if fwd and node.name not in donating:
                    donating[node.name] = fwd

        # Pass C: judge call sites per scope.
        findings: List[Finding] = []
        for scope in scopes:
            scope.analyze()
            findings.extend(
                self._judge_scope(rel, scope, donating))
        return findings

    @staticmethod
    def _params_at(node, positions) -> Dict[int, str]:
        params = [a.arg for a in node.args.args]
        return {p: (params[p] if p < len(params) else f"arg{p}")
                for p in positions}

    def _forwarded_positions(self, node, donating) -> Dict[int, str]:
        """Positions of ``node``'s params that flow into a donated
        position of a known donating function."""
        params = [a.arg for a in node.args.args]
        out: Dict[int, str] = {}
        for call in [n for n in ast.walk(node)
                     if isinstance(n, ast.Call)]:
            sig = donating.get(_last_name(call.func))
            if not sig:
                continue
            for pos, _pname in sig.items():
                if pos < len(call.args) and \
                        isinstance(call.args[pos], ast.Name):
                    arg = call.args[pos].id
                    if arg in params:
                        out[params.index(arg)] = arg
        return out

    def _scopes(self, tree: ast.AST,
                tainted_fns: frozenset = frozenset()) -> List[_Scope]:
        scopes = [_Scope(tree, "<module>", tainted_fns)] \
            if hasattr(tree, "body") else []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(_Scope(node, node.name, tainted_fns))
        return scopes

    def _judge_scope(self, rel: str, scope: _Scope,
                     donating: Dict[str, Dict[int, str]]
                     ) -> List[Finding]:
        findings: List[Finding] = []
        for call in scope.calls:
            sig = donating.get(_last_name(call.func))
            if not sig:
                continue
            fname = _last_name(call.func)
            for pos, pname in sig.items():
                if pos >= len(call.args):
                    continue
                arg = call.args[pos]
                if _expr_tainted(arg, scope.tainted_fns) or (
                        isinstance(arg, ast.Name) and
                        arg.id in scope.tainted):
                    label = arg.id if isinstance(arg, ast.Name) \
                        else ast.unparse(arg) if hasattr(ast, "unparse") \
                        else "<expr>"
                    findings.append(Finding(
                        code="RTA401", path=rel, line=call.lineno,
                        message=f"{label!r} comes from a staging/"
                                f"residency cache but is passed at "
                                f"donated position {pos} ({pname}) of "
                                f"{fname}() — XLA will free the cached "
                                f"buffer under every later consumer",
                        hint="never donate cache-resident arrays; "
                             "donate only the per-call state "
                             "(train state / optimizer state)",
                        anchor=f"{scope.name}:{fname}:{pos}"))
                elif isinstance(arg, ast.Name):
                    f = self._use_after_donate(rel, scope, call, arg.id,
                                               fname, pos)
                    if f is not None:
                        findings.append(f)
        return findings

    def _use_after_donate(self, rel, scope: _Scope, call: ast.Call,
                          name: str, fname: str,
                          pos: int) -> Optional[Finding]:
        later_loads = [ln for ln in scope.loads.get(name, [])
                       if ln > call.lineno]
        if not later_loads:
            return None
        first_load = min(later_loads)
        rebinds = [ln for ln in scope.binds.get(name, [])
                   if call.lineno <= ln <= first_load]
        if rebinds:
            return None  # the state, _ = step(state, ...) idiom
        return Finding(
            code="RTA402", path=rel, line=first_load,
            message=f"{name!r} was donated to {fname}() on line "
                    f"{call.lineno} and is read again here — a donated "
                    f"buffer is deleted after the call",
            hint="rebind the result (x, ... = f(x, ...)) or pass a "
                 "copy at the donated position",
            anchor=f"{scope.name}:{fname}:{pos}:use-after")
