"""RTA1xx — guarded-state: infer each class's lock-guarded attribute
set and flag accesses that bypass it, blocking calls made while a lock
is held, and lock-order cycles.

Historical bugs this encodes (docs/analysis.md):

- the ParamStore write-behind row-before-file race (r6): ``_pending``
  had to be re-checked under ``_pending_lock`` atomically with the
  index insert — a hand-found cross-thread ordering bug of exactly the
  shape RTA101 mechanizes;
- the micro-batcher's stop()-vs-submit races (r6/r8): every admission
  field moved under ``_cond`` after review.

Inference (per class):

1. **Lock attributes**: ``self.X = threading.Lock()/RLock()/
   Condition()``. ``Event``/``Semaphore``/``queue.Queue`` etc. are
   *atomic* primitives — excluded from the guarded set (their methods
   synchronize internally).
2. **State attributes**: assigned outside ``__init__`` anywhere in the
   class, or mutated through a container method (``append``/``pop``/
   ``update``/...). Attributes bound once in ``__init__`` and only
   read afterwards (collaborators, config) are not state.
3. **Guarded set**: state attributes accessed at least once while a
   lock is held. The guard is the union of locks ever held at an
   access, so multi-lock classes (queue under ``_cond``, completions
   under ``_completions_cond``) resolve per attribute.
4. A **private method whose every intra-class call site holds lock L**
   is analyzed as if it held L (the ``_drain_into`` "caller holds
   _cond" pattern), to a fixpoint. Closures/nested defs run later and
   inherit nothing.

RTA101: guarded attribute accessed while holding none of its guards
(outside ``__init__``).
RTA102: blocking call (sleep, subprocess, socket, ``open``, thread
``join``, future ``result``, non-lock ``wait``, queue ``get``/``put``)
made while holding a lock.
RTA103: lock-order cycle across the class's intra-class call graph
(including a self-cycle on a non-reentrant ``Lock``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Checker, Finding, RepoContext, register

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
ATOMIC_FACTORIES = {"Event", "Semaphore", "BoundedSemaphore", "Barrier",
                    "local", "Queue", "SimpleQueue", "LifoQueue",
                    "PriorityQueue"}
MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
            "pop", "popleft", "popitem", "remove", "discard", "clear",
            "update", "setdefault", "add"}

#: Module roots whose calls block (network, processes, disk trees).
BLOCKING_MODULES = {"subprocess", "socket", "requests", "urllib"}


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _dotted(node: ast.AST) -> List[str]:
    """``a.b.c(...)`` -> ["a", "b", "c"]; best effort."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return list(reversed(parts))


class _Access:
    __slots__ = ("attr", "held", "method", "line", "is_write", "nested")

    def __init__(self, attr, held, method, line, is_write, nested):
        self.attr = attr
        self.held = held
        self.method = method
        self.line = line
        self.is_write = is_write
        self.nested = nested


class _MethodWalker(ast.NodeVisitor):
    """Walks one method body tracking the lexically-held lock set."""

    def __init__(self, cls: "_ClassInfo", method: str):
        self.cls = cls
        self.method = method
        self.held: Tuple[str, ...] = ()
        self.depth = 0  # nested function depth (closures run later)

    # --- lock context ---

    def visit_With(self, node: ast.With) -> None:
        entered = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr in self.cls.lock_attrs:
                entered.append(attr)
                self.cls.lock_entries.append(
                    (frozenset(self.held), attr, item.context_expr.lineno,
                     self.method, self.depth))
            else:
                self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        prior = self.held
        self.held = tuple(self.held) + tuple(entered)
        for stmt in node.body:
            self.visit(stmt)
        self.held = prior

    # --- scope boundaries ---

    def _enter_nested(self, node) -> None:
        prior, self.held = self.held, ()
        self.depth += 1
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.depth -= 1
        self.held = prior

    def visit_FunctionDef(self, node):
        self._enter_nested(node)

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    # --- accesses ---

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None:
            self.cls.accesses.append(_Access(
                attr, frozenset(self.held), self.method, node.lineno,
                isinstance(node.ctx, (ast.Store, ast.Del)),
                self.depth > 0))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        self.cls.calls.append(
            (node, frozenset(self.held), self.method, self.depth))
        self.generic_visit(node)


class _ClassInfo:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.name = node.name
        self.lock_attrs: Set[str] = set()
        self.lock_kind: Dict[str, str] = {}      # attr -> factory name
        self.atomic_attrs: Set[str] = set()
        self.thread_attrs: Set[str] = set()
        self.state_attrs: Set[str] = set()
        self.accesses: List[_Access] = []
        # (node, held, method, nested-depth)
        self.calls: List[Tuple[ast.Call, frozenset, str, int]] = []
        # (outer_held, lock, line, method, nested-depth)
        self.lock_entries: List[Tuple[frozenset, str, int, str, int]] = []

    # -- pass 1: classify attributes --

    def classify(self) -> None:
        for method in self._methods():
            in_init = method.name == "__init__"
            for sub in ast.walk(method):
                if isinstance(sub, (ast.Assign, ast.AnnAssign,
                                    ast.AugAssign)):
                    targets = (sub.targets
                               if isinstance(sub, ast.Assign)
                               else [sub.target])
                    for tgt in targets:
                        self._classify_target(tgt, sub, in_init)
                elif isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute):
                    owner = _self_attr(sub.func.value)
                    if owner is not None and sub.func.attr in MUTATORS:
                        self.state_attrs.add(owner)

    def _classify_target(self, tgt: ast.AST, stmt, in_init: bool) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._classify_target(el, stmt, in_init)
            return
        if isinstance(tgt, ast.Subscript):
            owner = _self_attr(tgt.value)
            if owner is not None:
                self.state_attrs.add(owner)
            return
        attr = _self_attr(tgt)
        if attr is None:
            return
        value = getattr(stmt, "value", None)
        factory = self._factory_of(value)
        if factory in LOCK_FACTORIES:
            self.lock_attrs.add(attr)
            self.lock_kind[attr] = factory
            return
        if factory in ATOMIC_FACTORIES:
            self.atomic_attrs.add(attr)
            return
        if factory == "Thread":
            self.thread_attrs.add(attr)
        if not in_init:
            self.state_attrs.add(attr)

    @staticmethod
    def _factory_of(value) -> Optional[str]:
        if isinstance(value, ast.Call):
            parts = _dotted(value.func)
            if parts:
                return parts[-1]
        return None

    def _methods(self):
        for item in self.node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield item

    # -- pass 2: walk --

    def walk(self) -> None:
        for method in self._methods():
            walker = _MethodWalker(self, method.name)
            for stmt in method.body:
                walker.visit(stmt)

    # -- held-by-callers fixpoint --

    def held_extra(self) -> Dict[str, frozenset]:
        """Locks a private method may assume held because every
        intra-class call site holds them."""
        sites: Dict[str, List[Tuple[frozenset, str, int]]] = {}
        for call, held, method, depth in self.calls:
            callee = _self_attr(call.func) \
                if isinstance(call.func, ast.Attribute) else None
            if callee and callee.startswith("_") and depth == 0:
                sites.setdefault(callee, []).append(
                    (held, method, depth))
        extra: Dict[str, frozenset] = {}
        for _ in range(3):  # call chains are shallow; 3 is plenty
            changed = False
            for callee, callsites in sites.items():
                effective = [held | extra.get(method, frozenset())
                             for held, method, _ in callsites]
                new = frozenset.intersection(*effective) if effective \
                    else frozenset()
                if new != extra.get(callee, frozenset()):
                    extra[callee] = new
                    changed = True
            if not changed:
                break
        return extra

    # -- acquired-locks fixpoint (for interprocedural ordering) --

    def acquired(self) -> Dict[str, Set[str]]:
        direct: Dict[str, Set[str]] = {}
        callees: Dict[str, Set[str]] = {}
        for held, lock, _line, method, depth in self.lock_entries:
            if depth == 0:
                direct.setdefault(method, set()).add(lock)
        for call, _held, method, depth in self.calls:
            callee = _self_attr(call.func) \
                if isinstance(call.func, ast.Attribute) else None
            if callee and depth == 0:
                callees.setdefault(method, set()).add(callee)
        acq = {m: set(locks) for m, locks in direct.items()}
        for _ in range(3):
            changed = False
            for method, cs in callees.items():
                cur = acq.setdefault(method, set())
                for c in cs:
                    extra = acq.get(c, set()) - cur
                    if extra:
                        cur.update(extra)
                        changed = True
            if not changed:
                break
        return acq


@register
class GuardedStateChecker(Checker):
    name = "guarded-state"
    codes = ("RTA101", "RTA102", "RTA103")

    def run(self, ctx: RepoContext) -> List[Finding]:
        findings: List[Finding] = []
        for mod in ctx.target_modules():
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    findings.extend(self._check_class(mod.rel, node))
        return findings

    def _check_class(self, rel: str, node: ast.ClassDef) -> List[Finding]:
        cls = _ClassInfo(node)
        cls.classify()
        if not cls.lock_attrs:
            return []
        cls.walk()
        extra = cls.held_extra()
        findings: List[Finding] = []
        findings.extend(self._unguarded(rel, cls, extra))
        findings.extend(self._blocking(rel, cls, extra))
        findings.extend(self._lock_order(rel, cls, extra))
        return findings

    # --- RTA101 ---

    def _unguarded(self, rel: str, cls: _ClassInfo,
                   extra: Dict[str, frozenset]) -> List[Finding]:
        def effective(acc: _Access) -> frozenset:
            if acc.nested:
                return acc.held  # closures run later, inherit nothing
            return acc.held | extra.get(acc.method, frozenset())

        candidates = (cls.state_attrs - cls.lock_attrs
                      - cls.atomic_attrs)
        guards: Dict[str, Set[str]] = {}
        for acc in cls.accesses:
            if acc.attr in candidates:
                guards.setdefault(acc.attr, set()).update(effective(acc))

        findings = []
        seen: Set[Tuple[str, str]] = set()
        for acc in cls.accesses:
            g = guards.get(acc.attr)
            if not g or acc.method == "__init__":
                continue
            if effective(acc) & g:
                continue
            key = (acc.attr, acc.method)
            if key in seen:
                continue
            seen.add(key)
            lock_list = "/".join(f"self.{x}" for x in sorted(g))
            findings.append(Finding(
                code="RTA101", path=rel, line=acc.line,
                message=f"{cls.name}.{acc.attr} is guarded by "
                        f"{lock_list} elsewhere but "
                        f"{'written' if acc.is_write else 'read'} in "
                        f"{acc.method}() without holding it",
                hint=f"wrap the access in `with {lock_list.split('/')[0]}:` "
                     f"or waive with the reason the race is benign",
                anchor=f"{cls.name}.{acc.attr}@{acc.method}"))
        return findings

    # --- RTA102 ---

    def _blocking(self, rel: str, cls: _ClassInfo,
                  extra: Dict[str, frozenset]) -> List[Finding]:
        findings = []
        seen: Set[str] = set()
        for call, held, method, depth in cls.calls:
            eff = held if depth > 0 else \
                held | extra.get(method, frozenset())
            if not eff:
                continue
            label = self._blocking_label(cls, call)
            if label is None:
                continue
            anchor = f"{cls.name}.{method}:{label}"
            if anchor in seen:
                continue
            seen.add(anchor)
            locks = "/".join(f"self.{x}" for x in sorted(eff))
            findings.append(Finding(
                code="RTA102", path=rel, line=call.lineno,
                message=f"{cls.name}.{method}() calls blocking "
                        f"{label} while holding {locks}",
                hint="move the blocking call outside the lock (snapshot "
                     "state under the lock, act on it after release)",
                anchor=anchor))
        return findings

    def _blocking_label(self, cls: _ClassInfo,
                        call: ast.Call) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Name):
            return "open()" if func.id == "open" else None
        if not isinstance(func, ast.Attribute):
            return None
        parts = _dotted(func)
        root, leaf = parts[0], parts[-1]
        if root in BLOCKING_MODULES:
            return ".".join(parts) + "()"
        if root == "time" and leaf == "sleep":
            return "time.sleep()"
        if root == "os" and leaf == "system":
            return "os.system()"
        if root == "shutil" and leaf in ("rmtree", "copytree"):
            return f"shutil.{leaf}()"
        if leaf == "sleep":
            return ".".join(parts) + "()"
        owner = _self_attr(func.value)
        if leaf == "wait":
            # Condition/Lock .wait releases the lock — the idiom, not a
            # bug. A wait on anything else (Event, future) blocks with
            # the lock held.
            if owner in cls.lock_attrs:
                return None
            return ".".join(parts) + "()"
        if leaf == "join" and owner is not None and \
                owner in cls.thread_attrs:
            return f"self.{owner}.join()"
        if leaf == "result":
            return ".".join(parts) + "()"
        if leaf in ("get", "put") and owner in cls.atomic_attrs:
            return f"self.{owner}.{leaf}()"
        return None

    # --- RTA103 ---

    def _lock_order(self, rel: str, cls: _ClassInfo,
                    extra: Dict[str, frozenset]) -> List[Finding]:
        acq = cls.acquired()
        edges: Dict[Tuple[str, str], Tuple[int, str]] = {}

        def add_edge(a: str, b: str, line: int, method: str) -> None:
            edges.setdefault((a, b), (line, method))

        for held, lock, line, method, depth in cls.lock_entries:
            eff = held if depth > 0 else \
                held | extra.get(method, frozenset())
            for outer in eff:
                add_edge(outer, lock, line, method)
        for call, held, method, depth in cls.calls:
            eff = held if depth > 0 else \
                held | extra.get(method, frozenset())
            if not eff:
                continue
            callee = _self_attr(call.func) \
                if isinstance(call.func, ast.Attribute) else None
            if callee:
                for inner in acq.get(callee, ()):  # locks the callee takes
                    for outer in eff:
                        add_edge(outer, inner, call.lineno, method)

        findings = []
        # Self-edge on a non-reentrant Lock is an immediate deadlock.
        for (a, b), (line, method) in sorted(edges.items()):
            if a == b and cls.lock_kind.get(a) == "Lock":
                findings.append(Finding(
                    code="RTA103", path=rel, line=line,
                    message=f"{cls.name}.{method}() re-acquires "
                            f"non-reentrant self.{a} while holding it "
                            f"(guaranteed deadlock)",
                    hint="use threading.RLock, or restructure so the "
                         "inner path is called lock-free",
                    anchor=f"{cls.name}:{a}->{a}"))
        # Two-lock cycles (A->B and B->A); deeper cycles reduce to one
        # of these in practice for intra-class locking.
        for (a, b), (line, method) in sorted(edges.items()):
            if a < b and (b, a) in edges:
                findings.append(Finding(
                    code="RTA103", path=rel, line=line,
                    message=f"{cls.name}: lock-order cycle self.{a} -> "
                            f"self.{b} (in {method}) vs self.{b} -> "
                            f"self.{a} (in {edges[(b, a)][1]})",
                    hint="pick one acquisition order and restructure "
                         "the other path to follow it",
                    anchor=f"{cls.name}:{a}<->{b}"))
        return findings
