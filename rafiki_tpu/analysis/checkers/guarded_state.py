"""RTA1xx — guarded-state: infer each class's lock-guarded attribute
set and flag accesses that bypass it, blocking calls made while a lock
is held, and lock-order cycles.

Historical bugs this encodes (docs/analysis.md):

- the ParamStore write-behind row-before-file race (r6): ``_pending``
  had to be re-checked under ``_pending_lock`` atomically with the
  index insert — a hand-found cross-thread ordering bug of exactly the
  shape RTA101 mechanizes;
- the micro-batcher's stop()-vs-submit races (r6/r8): every admission
  field moved under ``_cond`` after review.

Inference (per class):

1. **Lock attributes**: ``self.X = threading.Lock()/RLock()/
   Condition()``. ``Event``/``Semaphore``/``queue.Queue`` etc. are
   *atomic* primitives — excluded from the guarded set (their methods
   synchronize internally).
2. **State attributes**: assigned outside ``__init__`` anywhere in the
   class, or mutated through a container method (``append``/``pop``/
   ``update``/...). Attributes bound once in ``__init__`` and only
   read afterwards (collaborators, config) are not state.
3. **Guarded set**: state attributes accessed at least once while a
   lock is held. The guard is the union of locks ever held at an
   access, so multi-lock classes (queue under ``_cond``, completions
   under ``_completions_cond``) resolve per attribute.
4. A **private method whose every intra-class call site holds lock L**
   is analyzed as if it held L (the ``_drain_into`` "caller holds
   _cond" pattern), to a fixpoint. Closures/nested defs run later and
   inherit nothing.

The per-class walk itself lives in ``analysis.program`` (r15): one
classify+walk per class per run, shared with the interprocedural
RTA104-106 checker through ``ctx.program()``.

RTA101: guarded attribute accessed while holding none of its guards
(outside ``__init__``).
RTA102: blocking call (sleep, subprocess, socket, ``open``, thread
``join``, future ``result``, non-lock ``wait``, queue ``get``/``put``)
made while holding a lock — *directly in the method*; the call-chain
form is RTA105 (checkers/concurrency.py).
RTA103: lock-order cycle across the class's intra-class call graph
(including a self-cycle on a non-reentrant ``Lock``); the cross-class
form is RTA104.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from ..core import Checker, Finding, RepoContext, register
from ..program import (_Access, _blocking_label, _ClassInfo, _self_attr,
                       held_display)


@register
class GuardedStateChecker(Checker):
    name = "guarded-state"
    codes = ("RTA101", "RTA102", "RTA103")

    def run(self, ctx: RepoContext) -> List[Finding]:
        findings: List[Finding] = []
        program = ctx.program()
        for mod in ctx.target_modules():
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    cls = program.class_info(node)
                    if not cls.lock_attrs:
                        continue
                    findings.extend(self._check_class(mod.rel, cls))
            findings.extend(self._module_unguarded(mod.rel, program))
        return findings

    def _check_class(self, rel: str, cls: _ClassInfo) -> List[Finding]:
        extra = cls.held_extra()
        findings: List[Finding] = []
        findings.extend(self._unguarded(rel, cls, extra))
        findings.extend(self._blocking(rel, cls, extra))
        findings.extend(self._lock_order(rel, cls, extra))
        return findings

    # --- RTA101 ---

    def _unguarded(self, rel: str, cls: _ClassInfo,
                   extra: Dict[str, frozenset]) -> List[Finding]:
        def effective(acc: _Access) -> frozenset:
            if acc.nested:
                return acc.held  # closures run later, inherit nothing
            return acc.held | extra.get(acc.method, frozenset())

        candidates = (cls.state_attrs - cls.lock_attrs
                      - cls.atomic_attrs)
        guards: Dict[str, Set[str]] = {}
        for acc in cls.accesses:
            if acc.attr in candidates:
                guards.setdefault(acc.attr, set()).update(effective(acc))

        findings = []
        seen: Set[Tuple[str, str]] = set()
        for acc in cls.accesses:
            g = guards.get(acc.attr)
            if not g or acc.method == "__init__":
                continue
            if effective(acc) & g:
                continue
            key = (acc.attr, acc.method)
            if key in seen:
                continue
            seen.add(key)
            lock_list = "/".join(held_display(x) for x in sorted(g))
            findings.append(Finding(
                code="RTA101", path=rel, line=acc.line,
                message=f"{cls.name}.{acc.attr} is guarded by "
                        f"{lock_list} elsewhere but "
                        f"{'written' if acc.is_write else 'read'} in "
                        f"{acc.method}() without holding it",
                hint=f"wrap the access in `with {lock_list.split('/')[0]}:` "
                     f"or waive with the reason the race is benign",
                anchor=f"{cls.name}.{acc.attr}@{acc.method}"))
        return findings

    # --- RTA101, module-global form ---

    def _module_unguarded(self, rel: str, program) -> List[Finding]:
        """Free functions sharing module globals under module-global
        locks (the observe/* registry shape): a global guarded by
        ``with _lock:`` at some accesses but touched bare elsewhere is
        the same race RTA101 flags on classes. Guards are inferred the
        same way — the union of module locks ever held at an access —
        so consistently-bare globals (no lock discipline at all) never
        flag; the module equivalent of an unlocked class is out of
        scope by design."""
        ms = program.module_state(rel)
        if not ms.accesses:
            return []
        guards: Dict[str, Set[str]] = {}
        for name, held, _func, _line, _w in ms.accesses:
            guards.setdefault(name, set()).update(held)
        findings: List[Finding] = []
        seen: Set[Tuple[str, str]] = set()
        stem = rel.rsplit("/", 1)[-1][:-3]
        for name, held, func, line, is_write in ms.accesses:
            g = guards.get(name)
            if not g or held & g:
                continue
            key = (name, func)
            if key in seen:
                continue
            seen.add(key)
            lock_list = "/".join(sorted(g))
            findings.append(Finding(
                code="RTA101", path=rel, line=line,
                message=f"module global {name} is guarded by "
                        f"{lock_list} elsewhere but "
                        f"{'written' if is_write else 'read'} in "
                        f"{func}() without holding it",
                hint=f"wrap the access in `with "
                     f"{lock_list.split('/')[0].rsplit('.', 1)[-1]}:` "
                     f"or waive with the reason the race is benign",
                anchor=f"{stem}:{name}@{func}"))
        return findings

    # --- RTA102 ---

    def _blocking(self, rel: str, cls: _ClassInfo,
                  extra: Dict[str, frozenset]) -> List[Finding]:
        findings = []
        seen: Set[str] = set()
        for call, held, method, depth, _fns in cls.calls:
            eff = held if depth > 0 else \
                held | extra.get(method, frozenset())
            if not eff:
                continue
            label = _blocking_label(cls, call)
            if label is None:
                continue
            anchor = f"{cls.name}.{method}:{label}"
            if anchor in seen:
                continue
            seen.add(anchor)
            locks = "/".join(held_display(x) for x in sorted(eff))
            findings.append(Finding(
                code="RTA102", path=rel, line=call.lineno,
                message=f"{cls.name}.{method}() calls blocking "
                        f"{label} while holding {locks}",
                hint="move the blocking call outside the lock (snapshot "
                     "state under the lock, act on it after release)",
                anchor=anchor))
        return findings

    # --- RTA103 ---

    def _lock_order(self, rel: str, cls: _ClassInfo,
                    extra: Dict[str, frozenset]) -> List[Finding]:
        acq = cls.acquired()
        edges: Dict[Tuple[str, str], Tuple[int, str]] = {}

        def add_edge(a: str, b: str, line: int, method: str) -> None:
            edges.setdefault((a, b), (line, method))

        for held, lock, line, method, depth in cls.lock_entries:
            eff = held if depth > 0 else \
                held | extra.get(method, frozenset())
            for outer in eff:
                add_edge(outer, lock, line, method)
        for call, held, method, depth, _fns in cls.calls:
            eff = held if depth > 0 else \
                held | extra.get(method, frozenset())
            if not eff:
                continue
            callee = _self_attr(call.func)
            if callee:
                for inner in acq.get(callee, ()):  # locks the callee takes
                    for outer in eff:
                        add_edge(outer, inner, call.lineno, method)

        findings = []
        # Self-edge on a non-reentrant Lock is an immediate deadlock.
        for (a, b), (line, method) in sorted(edges.items()):
            if a == b and cls.lock_kind.get(a) == "Lock":
                findings.append(Finding(
                    code="RTA103", path=rel, line=line,
                    message=f"{cls.name}.{method}() re-acquires "
                            f"non-reentrant self.{a} while holding it "
                            f"(guaranteed deadlock)",
                    hint="use threading.RLock, or restructure so the "
                         "inner path is called lock-free",
                    anchor=f"{cls.name}:{a}->{a}"))
        # Two-lock cycles (A->B and B->A); deeper cycles reduce to one
        # of these in practice for intra-class locking.
        for (a, b), (line, method) in sorted(edges.items()):
            if a < b and (b, a) in edges:
                findings.append(Finding(
                    code="RTA103", path=rel, line=line,
                    message=f"{cls.name}: lock-order cycle self.{a} -> "
                            f"self.{b} (in {method}) vs self.{b} -> "
                            f"self.{a} (in {edges[(b, a)][1]})",
                    hint="pick one acquisition order and restructure "
                         "the other path to follow it",
                    anchor=f"{cls.name}:{a}<->{b}"))
        return findings
