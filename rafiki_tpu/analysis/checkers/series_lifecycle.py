"""RTA3xx — metric series lifecycle: dynamically-labeled series need a
matching ``.remove(...)`` in the same module.

Historical bug this encodes: the r7 review found every per-trial MFU /
step-time series and every per-instance serving/http series living
forever in the process registry — a long-lived resident runner that
deploys/stops predictors or cycles trials grew the registry (and every
``/metrics`` scrape payload) without bound. The fix added
``Counter/Gauge/Histogram.remove(**label_subset)`` and a ``.remove``
call on each owner's stop/close/trial-end path; this checker keeps
that contract mechanical.

Rule: a module that records metric samples with a **dynamic label** —
a keyword argument to ``.inc()``/``.dec()``/``.set()``/``.observe()``
whose value is not a literal, a ``**labels`` splat, or a
``label_context(label=<dynamic>)`` binding — must also contain a
``.remove(...)`` mentioning that label name (or a ``.remove(**...)``).
A dynamic label means unbounded series churn; the remove is the only
thing that lets them die.

Deliberately-immortal bounded-vocabulary labels (``phase=``, ``kind=``,
``event=`` drawn from fixed tuples) are the documented false-positive
class: waive them inline with the vocabulary as the reason.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..core import Checker, Finding, RepoContext, register

_SAMPLE_METHODS = {"inc", "dec", "set", "observe"}


def _is_metrics_module(text: str) -> bool:
    """Cheap scope filter: only modules that touch the metrics plane.

    Keeps ``.set(...)`` calls on unrelated objects in non-metrics
    modules out of scope entirely.
    """
    return ("rafiki_tpu_" in text and
            ("metrics" in text or "registry" in text)) or \
        "label_context" in text


@register
class SeriesLifecycleChecker(Checker):
    name = "series-lifecycle"
    codes = ("RTA301",)

    def run(self, ctx: RepoContext) -> List[Finding]:
        findings: List[Finding] = []
        for mod in ctx.target_modules():
            if mod.tree is None or not _is_metrics_module(mod.text):
                continue
            findings.extend(self._check_module(mod.rel, mod.tree))
        return findings

    def _check_module(self, rel: str, tree: ast.AST) -> List[Finding]:
        dynamic: Dict[str, Tuple[int, str]] = {}  # label -> (line, via)
        removed_labels = set()
        has_splat_remove = False

        calls = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) and \
                    func.attr == "remove":
                if not node.keywords and not node.args:
                    # A bare .remove() matches the EMPTY label subset —
                    # it deletes every series of that metric (the r17
                    # ledger's close-last-owner path), so it covers any
                    # dynamic label in the module. Positional-arg
                    # removes are NOT this: `os.remove(path)` and
                    # `list.remove(x)` in a metrics module must never
                    # silently disable the checker.
                    has_splat_remove = True
                for kw in node.keywords:
                    if kw.arg is None:
                        has_splat_remove = True
                    else:
                        removed_labels.add(kw.arg)
                continue
            label_kws = self._dynamic_label_kwargs(node)
            if label_kws:
                all_labels = {kw.arg for kw in node.keywords
                              if kw.arg is not None}
                calls.append((node, label_kws, all_labels))
        for node, label_kws, all_labels in calls:
            # ``remove(service=...)`` matches by label SUBSET, so it
            # kills every series of a sample that also carried a
            # stage=/reason= label — one removed co-label covers the
            # whole call.
            if all_labels & removed_labels:
                continue
            for label, via in label_kws:
                dynamic.setdefault(label, (node.lineno, via))

        findings = []
        for label, (line, via) in sorted(dynamic.items()):
            if label in removed_labels or has_splat_remove:
                continue
            shown = label if label != "**" else "**<labels>"
            findings.append(Finding(
                code="RTA301", path=rel, line=line,
                message=f"metric series get a dynamic "
                        f"{shown!r} label (via {via}) but this module "
                        f"never calls .remove({'' if label == '**' else label + '=...'}"
                        f"{'**...' if label == '**' else ''}) — series "
                        f"leak across instance/trial churn",
                hint="call <metric>.remove(%s=<value>) from the owner's "
                     "stop/close/trial-end path, or waive with the "
                     "bounded label vocabulary as the reason"
                     % (label if label != "**" else "label"),
                anchor=f"label:{label}"))
        return findings

    def _dynamic_label_kwargs(
            self, call: ast.Call) -> List[Tuple[str, str]]:
        """Dynamic labels this call binds: from a sample method
        (``.inc/.dec/.set/.observe``) or a ``label_context(...)``."""
        func = call.func
        via: Optional[str] = None
        if isinstance(func, ast.Attribute) and \
                func.attr in _SAMPLE_METHODS:
            via = f".{func.attr}()"
        elif (isinstance(func, ast.Attribute) and
              func.attr == "label_context") or \
                (isinstance(func, ast.Name) and
                 func.id == "label_context"):
            via = "label_context()"
        if via is None:
            return []
        out: List[Tuple[str, str]] = []
        for kw in call.keywords:
            if kw.arg is None:
                out.append(("**", via))
            elif not isinstance(kw.value, ast.Constant):
                out.append((kw.arg, via))
        return out
