"""RTA2xx — thread lifecycle: every ``threading.Thread`` must be
daemonized or joined on some stop/close/drain path; every executor
must be shut down.

Historical bug this encodes: the ``_PersistStage``/micro-batcher/
write-behind pattern (r6-r9) — each grew a background thread, and each
needed a review pass to guarantee the process can exit: a non-daemon,
never-joined thread wedges interpreter shutdown (the r6 batcher review
caught exactly this before it shipped).

Rules:

RTA201: a ``threading.Thread(...)`` that is neither constructed with
``daemon=True`` (or later ``X.daemon = True``) nor ``.join()``-ed —
joins are looked up where the thread lands:

- assigned to ``self.X``: a ``self.X.join(...)`` anywhere in the class,
  including the ``for t in (self.A, self.B): t.join()`` loop idiom;
- assigned to a local: a ``X.join()`` in the same function;
- bare/module-level: any ``.join`` in the same scope.

RTA202: a ``concurrent.futures`` executor bound to ``self.X`` with no
``self.X.shutdown(...)`` in the class and never used as a context
manager.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..core import Checker, Finding, RepoContext, register

_EXECUTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _callee_name(call: ast.Call) -> str:
    """Last segment of the callee (``threading.Thread`` -> ``Thread``)."""
    f = call.func
    return f.attr if isinstance(f, ast.Attribute) else \
        (f.id if isinstance(f, ast.Name) else "")


def _has_daemon_kwarg(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return True
    return False


def _joined_names(scope: ast.AST) -> Set[str]:
    """Names (locals and self-attrs, the latter as ``self.X``) that get
    a ``.join(...)`` call in ``scope``, including the loop-over-a-tuple
    idiom (``for t in (self.A, self.B): ... t.join()``)."""
    joined: Set[str] = set()
    loop_aliases: dict = {}
    for node in ast.walk(scope):
        if isinstance(node, ast.For) and \
                isinstance(node.target, ast.Name) and \
                isinstance(node.iter, (ast.Tuple, ast.List)):
            loop_aliases.setdefault(node.target.id, []).extend(
                node.iter.elts)
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join":
            obj = node.func.value
            attr = _self_attr(obj)
            if attr is not None:
                joined.add(f"self.{attr}")
            elif isinstance(obj, ast.Name):
                joined.add(obj.id)
                for el in loop_aliases.get(obj.id, []):
                    el_attr = _self_attr(el)
                    if el_attr is not None:
                        joined.add(f"self.{el_attr}")
                    elif isinstance(el, ast.Name):
                        joined.add(el.id)
    return joined


def _daemon_assigned(scope: ast.AST) -> Set[str]:
    """``X.daemon = True`` targets, as ``self.X`` or local names."""
    out: Set[str] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        tgt.attr == "daemon":
                    owner = tgt.value
                    attr = _self_attr(owner)
                    if attr is not None:
                        out.add(f"self.{attr}")
                    elif isinstance(owner, ast.Name):
                        out.add(owner.id)
    return out


@register
class ThreadLifecycleChecker(Checker):
    name = "thread-lifecycle"
    codes = ("RTA201", "RTA202")

    def run(self, ctx: RepoContext) -> List[Finding]:
        findings: List[Finding] = []
        for mod in ctx.target_modules():
            if mod.tree is None:
                continue
            findings.extend(self._check_module(mod.rel, mod.tree))
        return findings

    def _check_module(self, rel: str, tree: ast.AST) -> List[Finding]:
        findings: List[Finding] = []
        # Pre-compute class-level facts.
        for cls in [n for n in ast.walk(tree)
                    if isinstance(n, ast.ClassDef)]:
            joined = _joined_names(cls)
            daemons = _daemon_assigned(cls)
            shutdowns = {
                f"self.{_self_attr(n.func.value)}"
                for n in ast.walk(cls)
                if isinstance(n, ast.Call) and
                isinstance(n.func, ast.Attribute) and
                n.func.attr == "shutdown" and
                _self_attr(n.func.value) is not None}
            for meth in [n for n in cls.body
                         if isinstance(n, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))]:
                local_joined = _joined_names(meth)
                local_daemons = _daemon_assigned(meth)
                for stmt in ast.walk(meth):
                    if not isinstance(stmt, ast.Assign):
                        continue
                    call = stmt.value
                    if not isinstance(call, ast.Call):
                        continue
                    name = _callee_name(call)
                    target = self._single_target(stmt)
                    if name == "Thread":
                        if _has_daemon_kwarg(call):
                            continue
                        ok = (target is not None and
                              (target in joined or target in daemons or
                               target in local_joined or
                               target in local_daemons))
                        if not ok:
                            findings.append(self._thread_finding(
                                rel, cls.name, meth.name, call, target))
                    elif name in _EXECUTORS:
                        if target is None or not \
                                target.startswith("self."):
                            continue
                        if target not in shutdowns:
                            findings.append(Finding(
                                code="RTA202", path=rel,
                                line=call.lineno,
                                message=f"{cls.name}.{meth.name}() "
                                        f"creates {name} {target} but "
                                        f"the class never calls "
                                        f"{target}.shutdown()",
                                hint="add shutdown(wait=True) to the "
                                     "class's close/stop path",
                                anchor=f"{cls.name}.{target}:executor"))
        # Module-level / free-function threads.
        findings.extend(self._check_free_threads(rel, tree))
        return findings

    @staticmethod
    def _single_target(stmt: ast.Assign) -> Optional[str]:
        if len(stmt.targets) != 1:
            return None
        tgt = stmt.targets[0]
        attr = _self_attr(tgt)
        if attr is not None:
            return f"self.{attr}"
        if isinstance(tgt, ast.Name):
            return tgt.id
        return None

    def _thread_finding(self, rel, cls_name, meth_name, call,
                        target) -> Finding:
        where = f"{cls_name}.{meth_name}()" if cls_name else \
            (f"{meth_name}()" if meth_name else "module level")
        tgt = target or "<unnamed>"
        return Finding(
            code="RTA201", path=rel, line=call.lineno,
            message=f"{where} starts a Thread ({tgt}) that is neither "
                    f"daemon=True nor joined on any stop/close path",
            hint="pass daemon=True, or join it from stop()/close()/"
                 "drain() so process exit cannot wedge",
            anchor=f"{cls_name or meth_name or '<module>'}.{tgt}:thread")

    def _check_free_threads(self, rel: str,
                            tree: ast.AST) -> List[Finding]:
        findings: List[Finding] = []
        class_nodes = {id(n) for c in ast.walk(tree)
                       if isinstance(c, ast.ClassDef)
                       for n in ast.walk(c)}
        funcs = [n for n in ast.walk(tree)
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))
                 and id(n) not in class_nodes]
        func_inner = {id(n) for f in funcs for n in ast.walk(f)}
        for scope, scope_name in [(tree, "")] + \
                [(f, f.name) for f in funcs]:
            joined = _joined_names(scope)
            daemons = _daemon_assigned(scope)
            if scope is tree:
                # Whole-module walk minus class bodies (handled by
                # _check_module) and function interiors (their own
                # scope entries below): a Thread built under an if/
                # try/with block is still module-level.
                stmts = [n for n in ast.walk(tree)
                         if id(n) not in class_nodes
                         and id(n) not in func_inner]
            else:
                stmts = list(ast.walk(scope))
            for stmt in stmts:
                if isinstance(stmt, ast.Assign) and \
                        isinstance(stmt.value, ast.Call) and \
                        _callee_name(stmt.value) == "Thread" and \
                        id(stmt) not in class_nodes:
                    if _has_daemon_kwarg(stmt.value):
                        continue
                    target = self._single_target(stmt)
                    if target is not None and (target in joined or
                                               target in daemons):
                        continue
                    findings.append(self._thread_finding(
                        rel, "", scope_name, stmt.value, target))
        return findings
