"""Checker modules; importing this package registers them all."""

from . import (  # noqa: F401
    donation,
    drift,
    guarded_state,
    series_lifecycle,
    thread_lifecycle,
)
