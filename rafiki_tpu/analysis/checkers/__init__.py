"""Checker modules; importing this package registers them all."""

from . import (  # noqa: F401
    concurrency,
    donation,
    drift,
    flow,
    guarded_state,
    import_hygiene,
    series_lifecycle,
    thread_lifecycle,
)
