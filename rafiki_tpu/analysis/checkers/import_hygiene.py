"""RTA6xx — import hygiene: what happens when a module is merely
*imported*.

Historical context this encodes (docs/analysis.md): every subprocess
service runner (worker runners, the metrics-only server, docker
children) re-executes module import side effects in ITS process — a
thread started or a socket bound at import time runs once per child,
silently. And PR 2 established the lazy-import discipline for jax
(``observe/__init__`` loads the profiling symbols lazily precisely so
bus brokers never pay a jax import); nothing enforced it until now.

RTA601: a side effect at import time — statements that execute on a
bare ``import`` (module body through if/try/for/with blocks AND class
bodies; ``if __name__ == "__main__"`` and ``TYPE_CHECKING`` blocks are
exempt):

- a ``Thread(...)`` constructed (or started) at import;
- a socket/server bound (``socket.*``, ``.bind``/``.listen`` on a
  module-level socket, known server constructors);
- a process spawned (``subprocess.*``, ``os.system``);
- an environment variable read (``os.environ.get`` / ``os.getenv`` /
  ``environ[...]``) — the value is frozen at first-import order, which
  is exactly how the NODE_LEASE class-attribute read made apply_env
  ordering matter (fixed in r15 by moving it to construction time).

RTA602: an eager (module-level) ``jax``/``jaxlib``/``flax``/``optax``
import in any module the bus/broker processes load — computed as the
import-time reachability closure from ``rafiki_tpu/bus/*`` over the
program's module graph (package ``__init__`` chains included). A
broker that imports jax pays seconds of import and a device runtime it
must never touch.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..core import Checker, Finding, RepoContext, register
from ..program import _dotted, _toplevel_stmts

_SERVER_CTORS = {"HTTPServer", "ThreadingHTTPServer", "TCPServer",
                 "ThreadingTCPServer", "UDPServer", "JsonHttpServer",
                 "BusServer", "NativeBusServer"}
_JAX_ROOTS = {"jax", "jaxlib", "flax", "optax"}

#: Reachability roots: anything a broker/bus process imports first.
_BUS_ROOT_PREFIX = "rafiki_tpu/bus/"


def _import_time_calls(stmt: ast.AST):
    """Call and Subscript nodes inside ``stmt`` that EXECUTE at
    import time (subscripts carry the ``os.environ["X"]`` reads). The
    bodies of compound statements are yielded separately by
    ``_toplevel_stmts``, so here only the statement's own import-time
    expressions are walked: the whole of a simple statement, the
    test/iter/context of a compound one, and the decorators + default
    arguments of a def (both evaluate at import even though the body
    does not). Function/class/lambda subtrees are never descended
    into."""
    roots: List[ast.AST]
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        roots = list(stmt.decorator_list) + \
            [d for d in stmt.args.defaults if d is not None] + \
            [d for d in stmt.args.kw_defaults if d is not None]
    elif isinstance(stmt, ast.ClassDef):
        roots = list(stmt.decorator_list) + list(stmt.bases)
    elif isinstance(stmt, (ast.If, ast.While)):
        roots = [stmt.test]
    elif isinstance(stmt, ast.For):
        roots = [stmt.iter]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        roots = [item.context_expr for item in stmt.items]
    elif isinstance(stmt, ast.Try):
        roots = []
    else:
        roots = [stmt]
    stack = list(roots)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(node, (ast.Call, ast.Subscript)):
            yield node
        stack.extend(ast.iter_child_nodes(node))


@register
class ImportHygieneChecker(Checker):
    name = "import-hygiene"
    codes = ("RTA601", "RTA602")
    scope = "repo"

    def run(self, ctx: RepoContext) -> List[Finding]:
        findings: List[Finding] = []
        for mod in ctx.target_modules():
            if mod.tree is None:
                continue
            findings.extend(self._side_effects(mod.rel, mod.tree))
        findings.extend(self._eager_jax(ctx))
        return findings

    # --- RTA601 ---

    def _side_effects(self, rel: str, tree: ast.AST) -> List[Finding]:
        if rel.endswith("/__main__.py"):
            return []  # entrypoints run on purpose, not on import
        findings: List[Finding] = []
        seen: Set[str] = set()
        thread_names: Set[str] = set()

        def emit(kind: str, detail: str, line: int, what: str,
                 hint: str) -> None:
            anchor = f"import:{kind}:{detail}"
            if anchor in seen:
                return
            seen.add(anchor)
            findings.append(Finding(
                code="RTA601", path=rel, line=line,
                message=f"{what} at import time — every subprocess "
                        f"runner that imports this module re-executes "
                        f"it",
                hint=hint, anchor=anchor))

        for stmt, guarded in _toplevel_stmts(tree):
            if guarded:
                continue
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call):
                parts = _dotted(stmt.value.func)
                if parts and parts[-1] == "Thread":
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            thread_names.add(tgt.id)
            for node in _import_time_calls(stmt):
                if isinstance(node, ast.Subscript):
                    # os.environ["X"] reads (Load) — the subscript
                    # spelling of the same frozen-at-import hazard.
                    sparts = _dotted(node.value)
                    if sparts and sparts[-1] == "environ" and \
                            isinstance(node.ctx, ast.Load):
                        var = node.slice.value if (
                            isinstance(node.slice, ast.Constant) and
                            isinstance(node.slice.value, str)) else ""
                        emit("env", var or "environ[]", node.lineno,
                             f"environment variable "
                             f"{var or '<dynamic>'} is read",
                             "resolve env at construction/call time "
                             "so apply_env/spawn ordering cannot "
                             "freeze a stale value")
                    continue
                parts = _dotted(node.func)
                if not parts:
                    continue
                root, leaf = parts[0], parts[-1]
                dotted = ".".join(parts)
                if leaf == "Thread" or (leaf == "start"
                                        and root in thread_names):
                    emit("thread", dotted, node.lineno,
                         f"`{dotted}(...)` builds/starts a thread",
                         "create the thread inside a start()/serve() "
                         "call, not at module scope")
                elif (root == "socket" and
                      leaf in ("socket", "create_connection",
                               "create_server")) or \
                        leaf in ("bind", "listen") or \
                        leaf in _SERVER_CTORS:
                    emit("socket", dotted, node.lineno,
                         f"`{dotted}(...)` binds a socket/server",
                         "bind inside an explicit serve()/start() "
                         "entrypoint")
                elif root == "subprocess" or dotted == "os.system":
                    emit("process", dotted, node.lineno,
                         f"`{dotted}(...)` spawns a process",
                         "spawn from a function the caller invokes "
                         "deliberately")
                elif (var := self._env_read(node)) is not None:
                    emit("env", var or dotted, node.lineno,
                         f"environment variable "
                         f"{var or '<dynamic>'} is read",
                         "resolve env at construction/call time (a "
                         "NodeConfig field, or a read inside the "
                         "function that needs it) so apply_env/spawn "
                         "ordering cannot freeze a stale value")
        return findings

    @staticmethod
    def _env_read(node: ast.Call) -> Optional[str]:
        """'VAR' (or "" for a dynamic name) when this call reads the
        environment; None otherwise."""
        parts = _dotted(node.func)
        dotted = ".".join(parts)
        is_env = dotted in ("os.getenv", "getenv") or (
            len(parts) >= 2 and parts[-2] == "environ" and
            parts[-1] in ("get", "pop"))
        if not is_env:
            return None
        if node.args and isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            return node.args[0].value
        return ""

    # --- RTA602 ---

    def _eager_jax(self, ctx: RepoContext) -> List[Finding]:
        program = ctx.program()
        roots = [rel for rel in program.modules
                 if rel.startswith(_BUS_ROOT_PREFIX)]
        if not roots:
            return []
        reach = program.import_reach(roots)
        findings: List[Finding] = []
        for rel in sorted(reach):
            mi = program.modules[rel]
            for modname, line in mi.import_time:
                top = modname.split(".")[0]
                if top not in _JAX_ROOTS:
                    continue
                chain = self._chain(program, reach, rel)
                findings.append(Finding(
                    code="RTA602", path=rel, line=line,
                    message=f"eager `{modname}` import in a module the "
                            f"bus/broker processes load "
                            f"(import chain: {' -> '.join(chain)})",
                    hint="move the import inside the function that "
                         "needs it (the observe/__init__ lazy-symbol "
                         "pattern), or break the module edge from the "
                         "bus path",
                    anchor=f"eager-jax:{modname}"))
                break  # one finding per module is enough
        return findings

    @staticmethod
    def _chain(program, reach, rel: str) -> List[str]:
        chain = [rel]
        cur = rel
        for _ in range(12):
            via = reach.get(cur)
            if via is None or via[0] == cur:
                break
            cur = via[0]
            chain.append(cur)
        return list(reversed(chain))
