"""RTA5xx — drift: the contracts that rot silently when only humans
enforce them.

Folds in the two pre-existing tier-1 scripts (which remain as thin
shims over this module) and extends them:

RTA501: every metric registered anywhere follows
``rafiki_tpu_<subsystem>_<name>_<unit>`` (was
``scripts/check_metrics_names.py``; the r7 metrics plane shipped with
this gate because one typo'd name forks the namespace forever).
RTA502: every ``rafiki_tpu_*`` token a Grafana dashboard references is
a registered name — a renamed metric breaks the build instead of
silently blanking a panel (r8).
RTA503: every NodeConfig env knob appears in the ``docs/ops.md`` knob
table (was ``scripts/check_knob_docs.py``; the r9 audit found three
generations of knobs nobody had documented).
RTA504 (new): every ``RAFIKI_TPU_*`` string literal *read* anywhere in
the tree is a NodeConfig knob or a ServicesManager-injected identity
var (``constants.EnvVars``) — ad-hoc ``os.environ.get`` knobs are how
the r9 audit's three undocumented generations happened in the first
place.
RTA505 (new): every NodeConfig knob whose env var is read at worker
construction time is exported by ``apply_env()`` — otherwise spawned
children resolve different values than the node validated.
RTA506 (r19): every metric name the SLO plane READS — the consumed-
series vocabulary in ``observe/slo.py``/``admin/slo_engine.py`` and
every ``metric`` reference in a committed SLO rules file under
``docs/slo/`` — is a registered series name (same machinery as the
RTA502 Grafana check): a renamed source series must break the build,
not silently blank every objective that reads it.

The name vocabulary (subsystems, units) lives HERE: extending it is a
deliberate reviewed edit, exactly as it was in the scripts.
"""

from __future__ import annotations

import ast
import dataclasses
import importlib.util
import json
import os
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

from ..core import Checker, Finding, RepoContext, register

PREFIX = "rafiki_tpu_"

SUBSYSTEMS = {"bus", "serving", "http", "train", "trial", "trace",
              "node", "fault", "autoscale", "profile", "slo",
              "workload", "capacity", "lm", "relay"}

# _total marks counters (Prometheus convention); everything else is the
# physical unit of a gauge/histogram. "rate" is the SLO plane's burn
# rate (budget fractions per window-length — dimensionless but not a
# 0..1 ratio). "tokens" is the generative-serving unit (resident-KV
# gauge; token counters end _total like every counter).
# "peers" is the cluster registry's unit (live-peer-count gauge;
# relay/fabric traffic counters end _total like every counter).
UNITS = {"total", "seconds", "ratio", "bytes", "queries", "batches",
         "info", "replicas", "rate", "tokens", "peers"}

NAME_RE = re.compile(r"^rafiki_tpu_[a-z0-9]+(?:_[a-z0-9]+)+$")

#: Any rafiki_tpu_* token inside a dashboard JSON (panel exprs,
#: label_values templating queries, ...).
DASH_TOKEN_RE = re.compile(r"\brafiki_tpu_[a-z0-9_]+\b")

#: Exposition-level suffixes a histogram's series carry beyond its
#: registered name.
HIST_SUFFIXES = ("_bucket", "_sum", "_count")

ENV_PREFIX = "RAFIKI_TPU_"
#: A full env name: prefix fragments like "RAFIKI_TPU_SERVING_" (used
#: to CONSTRUCT names) are not reads of a specific knob.
ENV_NAME_RE = re.compile(r"^RAFIKI_TPU_[A-Z0-9_]*[A-Z0-9]$")

#: Modules the env-drift scan skips: the knob layer itself, the
#: injected-identity registry, and this suite.
ENV_SCAN_SKIP = ("rafiki_tpu/config.py", "rafiki_tpu/constants.py",
                 "rafiki_tpu/analysis/")


def _walk_py(root: str) -> List[Tuple[str, str]]:
    """(rel, text) for every .py under <root>/rafiki_tpu."""
    out = []
    pkg = os.path.join(root, "rafiki_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                with open(path, encoding="utf-8") as f:
                    out.append((rel, f.read()))
    return out


def _parsed_modules(root: str, modules=None
                    ) -> List[Tuple[str, str, Optional[ast.AST]]]:
    """(rel, text, tree-or-None). Inside the suite the ctx's
    already-parsed ``Module`` list is passed through so the repo is
    read+parsed exactly once per run; the standalone script shims walk
    and parse fresh."""
    if modules is not None:
        return [(m.rel, m.text, m.tree) for m in modules]
    out = []
    for rel, text in _walk_py(root):
        try:
            tree = ast.parse(text)
        except SyntaxError:
            tree = None  # run_suite reports RTA000 for the repo proper
        out.append((rel, text, tree))
    return out


# --- RTA501/RTA502: metric names + dashboard references ---------------

def check_metric_names(root: str, modules=None
                       ) -> Tuple[List[Finding], Set[str], int]:
    """All naming findings plus the registered-name set (for the
    dashboard cross-check) and the file count."""
    findings: List[Finding] = []
    registered: Set[str] = set()
    files = _parsed_modules(root, modules)
    for rel, text, tree in files:
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fname = node.func.attr if isinstance(node.func, ast.Attribute) \
                else (node.func.id if isinstance(node.func, ast.Name)
                      else "")
            if fname not in ("counter", "gauge", "histogram"):
                continue
            if not node.args or not isinstance(node.args[0], ast.Constant) \
                    or not isinstance(node.args[0].value, str):
                continue
            name = node.args[0].value
            if not name.startswith(PREFIX):
                continue
            registered.add(name)
            findings.extend(_judge_name(rel, node.lineno, fname, name))
    return findings, registered, len(files)


def _judge_name(rel: str, line: int, kind: str,
                name: str) -> List[Finding]:
    out = []

    def f(tag: str, message: str) -> Finding:
        return Finding(code="RTA501", path=rel, line=line,
                       message=message, anchor=f"{name}:{tag}",
                       hint="extend the vocabulary in rafiki_tpu/"
                            "analysis/checkers/drift.py if intentional")

    if not NAME_RE.match(name):
        out.append(f("shape", f"{name!r} is not "
                              f"rafiki_tpu_<subsystem>_<name>_<unit>"))
        return out
    tokens = name[len(PREFIX):].split("_")
    if tokens[0] not in SUBSYSTEMS:
        out.append(f("subsystem",
                     f"{name!r} subsystem {tokens[0]!r} not in "
                     f"{sorted(SUBSYSTEMS)}"))
    unit = tokens[-1]
    if unit not in UNITS:
        out.append(f("unit", f"{name!r} unit {unit!r} not in "
                            f"{sorted(UNITS)}"))
    if kind == "counter" and unit != "total":
        out.append(f("counter-total",
                     f"counter {name!r} must end in _total"))
    if kind != "counter" and unit == "total":
        out.append(f("total-not-counter",
                     f"{kind} {name!r} must not end in _total"))
    return out


def _strip_hist_suffix(name: str, registered: Set[str]) -> str:
    for suffix in HIST_SUFFIXES:
        if name.endswith(suffix) and name[:-len(suffix)] in registered:
            return name[:-len(suffix)]
    return name


def _scan_artifact_tokens(rel: str, text: str, registered: Set[str],
                          code: str, message_fmt: str,
                          ) -> List[Finding]:
    """Every ``rafiki_tpu_*`` token in one committed artifact (Grafana
    dashboard, SLO rules file) must be a registered series name after
    the histogram-suffix strip; ``message_fmt`` takes ``{name!r}``."""
    findings: List[Finding] = []
    for name in sorted(set(DASH_TOKEN_RE.findall(text))):
        if _strip_hist_suffix(name, registered) in registered:
            continue
        # Boundary-anchored like the extraction above — a plain
        # find() would land inside a longer token (e.g. the
        # `_total` form of the same name) on an earlier line.
        m = re.search(r"\b%s\b" % re.escape(name), text)
        line = text[:m.start()].count("\n") + 1
        findings.append(Finding(
            code=code, path=rel, line=line,
            message=message_fmt.format(name=name), anchor=name))
    return findings


def check_dashboards(root: str,
                     registered: Set[str]) -> Tuple[List[Finding], int]:
    """Every metric a dashboard references must be a registered name
    (after stripping the histogram exposition suffixes)."""
    findings: List[Finding] = []
    grafana = os.path.join(root, "docs", "grafana")
    n_dash = 0
    if not os.path.isdir(grafana):
        return findings, 0
    for fn in sorted(os.listdir(grafana)):
        if not fn.endswith(".json"):
            continue
        n_dash += 1
        rel = f"docs/grafana/{fn}"
        with open(os.path.join(grafana, fn), encoding="utf-8") as f:
            text = f.read()
        try:
            json.loads(text)
        except json.JSONDecodeError as e:
            findings.append(Finding(
                code="RTA502", path=rel, line=1,
                message=f"invalid JSON ({e})", anchor="json"))
            continue
        findings.extend(_scan_artifact_tokens(
            rel, text, registered, "RTA502",
            "references {name!r}, which no code path registers "
            "(renamed metric? update the dashboard)"))
    return findings, n_dash


# --- RTA506: SLO plane metric references ------------------------------

#: Modules whose rafiki_tpu_* string constants are READS of series the
#: SLO plane consumes (they also REGISTER their own rafiki_tpu_slo_*
#: gauges — registration is covered by the RTA501 scan, so those names
#: are in the registered set and pass trivially).
SLO_MODULES = ("rafiki_tpu/observe/slo.py",
               "rafiki_tpu/admin/slo_engine.py")

#: Committed SLO rules files live here (examples + deploy defaults).
SLO_RULES_DIR = os.path.join("docs", "slo")


def check_slo_refs(root: str, registered: Set[str], modules=None,
                   ) -> List[Finding]:
    """RTA506: SLO-consumed series names must be registered. Two
    sources: (1) full-shape metric-name string constants inside the
    SLO modules (the CONSUMED_SERIES vocabulary and any literal the
    engine matches on), (2) every ``rafiki_tpu_*`` token in a rules
    file under docs/slo/ (the ``metric`` override field included)."""
    findings: List[Finding] = []
    by_rel = {rel: (text, tree)
              for rel, text, tree in _parsed_modules(root, modules)}
    for rel in SLO_MODULES:
        if rel not in by_rel:
            continue
        text, tree = by_rel[rel]
        if tree is None:
            continue
        seen: Set[str] = set()
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            name = node.value
            if not NAME_RE.match(name):
                continue
            base = _strip_hist_suffix(name, registered)
            if base in registered or base in seen:
                continue
            seen.add(base)
            findings.append(Finding(
                code="RTA506", path=rel, line=node.lineno,
                message=f"SLO plane consumes {name!r}, which no code "
                        f"path registers (renamed source series? "
                        f"update the SLO vocabulary)",
                hint="fix the name in CONSUMED_SERIES / the engine, "
                     "or register the series it expects",
                anchor=name))
    rules_dir = os.path.join(root, SLO_RULES_DIR)
    if os.path.isdir(rules_dir):
        for fn in sorted(os.listdir(rules_dir)):
            if not (fn.endswith(".json") or fn.endswith(".toml")):
                continue
            rel = f"docs/slo/{fn}"
            with open(os.path.join(rules_dir, fn),
                      encoding="utf-8") as f:
                text = f.read()
            if fn.endswith(".json"):
                try:
                    json.loads(text)
                except json.JSONDecodeError as e:
                    findings.append(Finding(
                        code="RTA506", path=rel, line=1,
                        message=f"invalid JSON ({e})", anchor="json"))
                    continue
            findings.extend(_scan_artifact_tokens(
                rel, text, registered, "RTA506",
                "SLO rules reference {name!r}, which no code path "
                "registers (renamed metric? update the rules file)"))
    return findings


# --- RTA503: knob docs ------------------------------------------------

#: (path, mtime_ns) -> NodeConfig class. One run used to exec config.py
#: three times (knob docs, env drift, apply_env parity); the cache
#: makes it once — and keeps fixture trees correct via the path key.
_NODE_CONFIG_CACHE: Dict[Tuple[str, int], type] = {}


def load_node_config(root: str):
    """Load NodeConfig from THIS root by file path (never the installed
    package): the check must run without jax, and a tmp-tree run (the
    fixture tests) must see the tree's own config. Cached per
    (path, mtime)."""
    path = os.path.join(root, "rafiki_tpu", "config.py")
    key = (os.path.abspath(path), os.stat(path).st_mtime_ns)
    cached = _NODE_CONFIG_CACHE.get(key)
    if cached is not None:
        return cached
    spec = importlib.util.spec_from_file_location(
        "_rta_node_config", path)
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves field types through sys.modules[__module__];
    # an unregistered module would break the @dataclass decorator.
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
        _NODE_CONFIG_CACHE[key] = mod.NodeConfig
        return mod.NodeConfig
    finally:
        sys.modules.pop(spec.name, None)


def check_knob_docs(root: str) -> Tuple[List[Finding], int]:
    NodeConfig = load_node_config(root)
    doc_rel = "docs/ops.md"
    doc_path = os.path.join(root, doc_rel)
    fields = dataclasses.fields(NodeConfig)
    if not os.path.exists(doc_path):
        return [Finding(code="RTA503", path=doc_rel, line=1,
                        message="missing (the knob table lives here)",
                        anchor="missing")], len(fields)
    with open(doc_path, encoding="utf-8") as f:
        text = f.read()
    findings = []
    for f_ in fields:
        env = NodeConfig.env_name(f_.name)
        # Delimited-token match, not substring: RAFIKI_TPU_METRICS must
        # not count as documented just because RAFIKI_TPU_METRICS_PORT
        # appears somewhere.
        if not re.search(re.escape(env) + r"(?![A-Z0-9_])", text):
            findings.append(Finding(
                code="RTA503", path=doc_rel, line=1,
                message=f"NodeConfig.{f_.name} ({env}) is "
                        f"undocumented — add it to the knob table",
                anchor=env))
    return findings, len(fields)


# --- RTA504/RTA505: env literal drift + apply_env parity --------------

def _envvars_constants(root: str) -> Set[str]:
    """The ServicesManager-injected identity vars (constants.EnvVars):
    transport plumbing, not operator knobs."""
    path = os.path.join(root, "rafiki_tpu", "constants.py")
    out: Set[str] = set()
    if not os.path.exists(path):
        return out
    with open(path, encoding="utf-8") as f:
        try:
            tree = ast.parse(f.read())
        except SyntaxError:
            return out
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "EnvVars":
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and \
                        isinstance(stmt.value, ast.Constant) and \
                        isinstance(stmt.value.value, str):
                    out.add(stmt.value.value)
    return out


def _env_reads(tree: ast.AST) -> List[Tuple[str, int]]:
    """(env_name, line) for every read of a RAFIKI_TPU_* literal:
    ``*.get("X")``, ``*.getenv("X")``, ``*["X"]`` (Load), and the same
    through a module-level ``CONST = "X"`` indirection."""
    consts: Dict[str, str] = {}
    for node in getattr(tree, "body", []):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str) and \
                node.value.value.startswith(ENV_PREFIX):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    consts[tgt.id] = node.value.value

    def resolve(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                node.value.startswith(ENV_PREFIX):
            return node.value
        if isinstance(node, ast.Name):
            return consts.get(node.id)
        return None

    reads: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        name = None
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("get", "getenv", "pop") and node.args:
            name = resolve(node.args[0])
            # .pop with a default is cleanup, not a read the process
            # depends on — but a bare env.pop("X") still names a knob.
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "getenv" and node.args:
            name = resolve(node.args[0])
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load):
            name = resolve(node.slice)
        if name is not None and ENV_NAME_RE.match(name):
            reads.append((name, node.lineno))
    return reads


def check_env_drift(root: str, modules=None) -> List[Finding]:
    try:
        NodeConfig = load_node_config(root)
        knob_envs = {NodeConfig.env_name(f.name): f.name
                     for f in dataclasses.fields(NodeConfig)}
    except Exception:
        knob_envs = {}
    identity = _envvars_constants(root)
    findings: List[Finding] = []
    knob_reads: Set[str] = set()
    for rel, text, tree in _parsed_modules(root, modules):
        if any(rel.startswith(skip) or rel == skip
               for skip in ENV_SCAN_SKIP):
            continue
        if tree is None or ENV_PREFIX not in text:
            continue
        seen_here: Set[str] = set()
        for env, line in _env_reads(tree):
            if env in identity:
                continue
            if env in knob_envs:
                knob_reads.add(env)
                continue
            if env in seen_here:
                continue
            seen_here.add(env)
            findings.append(Finding(
                code="RTA504", path=rel, line=line,
                message=f"env literal {env!r} is read here but is not "
                        f"a NodeConfig knob — operators cannot discover "
                        f"or validate it",
                hint="promote it to a NodeConfig field (env parity + "
                     "apply_env export + docs/ops.md row), or waive "
                     "with why it is internal plumbing",
                anchor=env))

    # RTA505: knobs read by workers must be exported by apply_env.
    exported = _apply_env_exports(root)
    if exported is not None:
        for env in sorted(knob_reads):
            if env not in exported:
                findings.append(Finding(
                    code="RTA505", path="rafiki_tpu/config.py",
                    line=exported.get("__line__", 1),
                    message=f"NodeConfig.{knob_envs[env]} ({env}) is "
                            f"read at worker construction but "
                            f"apply_env() never exports it — spawned "
                            f"children may resolve different values "
                            f"than the node validated",
                    hint="export it in apply_env() like the other "
                         "service tunables",
                    anchor=f"apply_env:{env}"))
    return findings


def _apply_env_exports(root: str) -> Optional[Dict[str, int]]:
    """Env names apply_env() exports: ``self.env_name("field")`` calls
    and direct literals. Returns None when config.py is unparseable."""
    path = os.path.join(root, "rafiki_tpu", "config.py")
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        try:
            tree = ast.parse(f.read())
        except SyntaxError:
            return None
    try:
        NodeConfig = load_node_config(root)
    except Exception:
        return None
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and \
                node.name == "apply_env":
            out["__line__"] = node.lineno
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr == "env_name" and sub.args and \
                        isinstance(sub.args[0], ast.Constant):
                    try:
                        out[NodeConfig.env_name(sub.args[0].value)] = \
                            sub.lineno
                    except Exception:
                        pass
                elif isinstance(sub, ast.Constant) and \
                        isinstance(sub.value, str) and \
                        sub.value.startswith(ENV_PREFIX):
                    out[sub.value] = sub.lineno
    return out if out else None


# --- the registered checker ------------------------------------------

@register
class DriftChecker(Checker):
    name = "drift"
    codes = ("RTA501", "RTA502", "RTA503", "RTA504", "RTA505",
             "RTA506")
    scope = "repo"
    triggers = ("rafiki_tpu/*", "rafiki_tpu/*/*", "rafiki_tpu/*/*/*",
                "docs/grafana/*", "docs/slo/*", "docs/ops.md")

    def run(self, ctx: RepoContext) -> List[Finding]:
        findings, registered, _ = check_metric_names(
            ctx.root, modules=ctx.modules)
        dash, _ = check_dashboards(ctx.root, registered)
        findings.extend(dash)
        findings.extend(check_slo_refs(ctx.root, registered,
                                       modules=ctx.modules))
        try:
            knob_findings, _ = check_knob_docs(ctx.root)
            findings.extend(knob_findings)
        except Exception as e:  # config.py unloadable in this tree
            findings.append(Finding(
                code="RTA503", path="rafiki_tpu/config.py", line=1,
                message=f"could not load NodeConfig: {e}",
                anchor="load"))
        findings.extend(check_env_drift(ctx.root, modules=ctx.modules))
        return findings
