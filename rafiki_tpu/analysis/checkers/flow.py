"""RTA7xx — flow: conformance of the distributed seams.

The RTA1xx/5xx families check what one process does with its own state.
This family checks the seams BETWEEN processes, where nothing in the
type system or the test suite connects producer to consumer:

- **RTA701 — bus queue-flow drift.** The bus is stringly-typed: a
  worker pops ``f"q:{worker_id}"`` because the cache pushes the same
  spelling. The checker harvests the queue-name vocabulary at every
  push/pop site (string literals and f-string *prefixes*, resolved
  through the call graph so a helper forwarding a ``queue`` argument
  attributes the name to the real producer/consumer), groups names
  into families by their ``prefix:`` segment, and flags a family
  pushed with no popper (orphan producer) or popped with no pusher
  (dead consumer). Control-frame op tokens (the ``__restack__`` style
  dunder strings) are checked producer vs dispatcher the same way.
- **RTA702 — HTTP route drift.** Server-side registered method+path
  tuples (predictor/admin apps, the ``utils/service.py`` route table)
  vs every in-tree caller: the client SDK's ``_call``, autoscaler/SLO
  ``fetch`` scrapes, cluster peer probes (``urlopen``/``Request``),
  session-based uploads, and the dashboard's ``api(...)`` calls. A
  caller hitting an unregistered route flags; a served route with zero
  in-tree callers flags too (waivable for operator-only surfaces).
- **RTA703 — feature-flag off-path side effects.** For declared
  default-off flags (``FLAG_REGISTRY``; seeded with
  ``RAFIKI_TPU_CLUSTER_FABRIC``), any thread spawn, metric-series
  registration, bus subscription loop, or socket open reachable from
  import or construction *without* passing the flag gate flags. The
  gate vocabulary is the env-var name, its NodeConfig field, and
  attributes whose every truthy assignment is flag-gated (so
  ``if self._fabric:`` counts as a gate); functions whose every
  resolvable call site is gated (or whose class is only constructed
  under the gate) are *protected* and audited as on-path.

Resolution rules (documented blind spots in docs/analysis.md):

- f-string queue names resolve to their literal prefix up to the first
  placeholder; an empty prefix is dynamic and exempt.
- a ``Name`` queue argument resolves through local assignment, then
  through the call graph (bounded depth) when it is a parameter; a
  ``Call`` argument resolves when the callee's every return value
  resolves (the ``_req_queue(sub_id)`` helper shape).
- gate polarity is not tracked: ``if not flag: return`` gates the rest
  of the function (correct), but an inverted guard would too.
"""

from __future__ import annotations

import ast
import pathlib
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core import Checker, Finding, RepoContext, register
from ..program import _dotted, _self_attr

PUSH_OPS = frozenset({"push", "push_many", "relay_push",
                      "relay_push_many"})
POP_OPS = frozenset({"pop", "pop_all", "queue_len", "delete_queue"})
QUEUE_OPS = PUSH_OPS | POP_OPS
HTTP_VERBS = frozenset({"GET", "POST", "PUT", "DELETE", "PATCH"})
OP_TOKEN_RE = re.compile(r"^__\w+__$")
#: Modules implementing the bus itself — their push/pop are the
#: generic transport, not a named producer/consumer.
BUS_IMPL_PREFIX = "rafiki_tpu/bus/"
#: Bound on queue-name resolution through forwarding helpers.
MAX_FORWARD = 3

#: RTA703's declared default-off feature flags. Each entry names the
#: env gate, its NodeConfig field (both spellings are gate vocabulary),
#: the modules the flag wholly owns, and the metric-series prefixes
#: that must never register off-path. Extending this registry is the
#: documented procedure for every new default-off subsystem
#: (docs/analysis.md).
FLAG_REGISTRY: Tuple[Dict[str, object], ...] = (
    {
        "flag": "RAFIKI_TPU_CLUSTER_FABRIC",
        "field": "cluster_fabric",
        "owned_modules": ("rafiki_tpu/admin/nodes.py",),
        # Deliberately narrower than rafiki_tpu_node_*: the
        # supervisor's rafiki_tpu_node_restarts_total predates the
        # fabric and lives on the always-on path.
        "owned_series": ("rafiki_tpu_node_peers",
                         "rafiki_tpu_serving_fabric_"),
    },
)

SERIES_FACTORIES = frozenset({"counter", "gauge", "histogram"})


class _Ctx:
    """One function/method with everything needed to resolve calls and
    receiver types at its sites."""

    __slots__ = ("rel", "cls_key", "fname", "node", "atypes", "ltypes",
                 "key")

    def __init__(self, key, node, atypes, ltypes):
        self.key = key
        self.rel = key[0]
        self.cls_key = (key[0], key[1]) if key[1] else None
        self.fname = key[2]
        self.node = node
        self.atypes = atypes
        self.ltypes = ltypes


def _leaf(func) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _recv_name(expr) -> Optional[str]:
    attr = _self_attr(expr)
    if attr is not None:
        return attr
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _first_assign(fnode, name: str):
    for n in ast.walk(fnode):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.targets[0], ast.Name) \
                and n.targets[0].id == name:
            return n.value
    return None


def _fstr_prefix(node: ast.JoinedStr) -> str:
    """Literal prefix of an f-string up to the first placeholder."""
    prefix = ""
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            prefix += v.value
        else:
            break
    return prefix


def _family(name: str, is_prefix: bool) -> Optional[str]:
    """Queue-name family: through the first ``:`` inclusive, else the
    whole literal name. A *prefix* with no ``:`` yet is incomplete —
    dynamic, exempt."""
    i = name.find(":")
    if i >= 0:
        return name[:i + 1]
    return None if is_prefix else name


def _queue_arg(op: str, call: ast.Call):
    """The queue-name expression of a bus queue op, or None when the
    op embeds names in item tuples (push_many)."""
    for kw in call.keywords:
        if kw.arg == "queue":
            return kw.value
    if op in ("push_many", "relay_push_many"):
        return None
    idx = 1 if op == "relay_push" else 0
    if len(call.args) > idx:
        return call.args[idx]
    return None


def _segs(path: str) -> List[str]:
    """Normalized path segments: query stripped, ``<param>``/dynamic
    segments become the wildcard ``*``."""
    path = path.split("?", 1)[0]
    out = []
    for s in path.split("/"):
        if not s:
            continue
        if s.startswith("<") or "*" in s or "${" in s:
            out.append("*")
        else:
            out.append(s)
    return out


def _seg_match(a: Sequence[str], b: Sequence[str]) -> bool:
    return len(a) == len(b) and all(
        x == y or x == "*" or y == "*" for x, y in zip(a, b))


@register
class FlowChecker(Checker):
    name = "flow"
    codes = ("RTA701", "RTA702", "RTA703")
    scope = "repo"

    def run(self, ctx: RepoContext) -> List[Finding]:
        program = ctx.program()
        by_key: Dict[tuple, _Ctx] = {}
        contexts: List[_Ctx] = []
        for key, s in program.summaries().items():
            cls_key = (key[0], key[1]) if key[1] else None
            atypes = program.attr_types(cls_key) if cls_key else {}
            ltypes = program._local_types(key[0], cls_key, s.node,
                                          atypes)
            c = _Ctx(key, s.node, atypes, ltypes)
            by_key[key] = c
            contexts.append(c)
        # Cross-process call index: target -> [(caller key, Call)],
        # straight off the summaries' resolved call_nodes.
        call_index: Dict[tuple, List[Tuple[tuple, ast.Call]]] = {}
        for key, s in program.summaries().items():
            for tgt, call in s.call_nodes:
                if tgt is not None:
                    call_index.setdefault(tgt, []).append((key, call))

        findings: List[Finding] = []
        findings.extend(self._queue_flow(program, contexts, by_key,
                                         call_index))
        findings.extend(self._route_drift(ctx, program, contexts))
        findings.extend(self._flag_offpath(program, contexts, by_key,
                                           call_index))
        return findings

    # ------------------------------------------------------------------
    # RTA701 — bus queue-flow
    # ------------------------------------------------------------------

    def _bus_receiver(self, c: _Ctx, recv) -> bool:
        attr = _self_attr(recv)
        if attr is not None:
            fk = c.atypes.get(attr)
        elif isinstance(recv, ast.Name) and recv.id != "self":
            fk = c.ltypes.get(recv.id)
        else:
            fk = None
        return fk is not None and fk[0].startswith(BUS_IMPL_PREFIX)

    def _queue_families(self, program, c: _Ctx, expr, depth: int,
                        seen: set, by_key, call_index) -> Set[str]:
        if depth < 0 or expr is None:
            return set()
        if isinstance(expr, ast.Constant) and isinstance(expr.value,
                                                         str):
            f = _family(expr.value, False)
            return {f} if f else set()
        if isinstance(expr, ast.JoinedStr):
            p = _fstr_prefix(expr)
            f = _family(p, True) if p else None
            return {f} if f else set()
        if isinstance(expr, ast.Name):
            params = {a.arg for a in (c.node.args.args
                                      + c.node.args.kwonlyargs)}
            if expr.id in params:
                return self._param_families(program, c, expr.id, depth,
                                            seen, by_key, call_index)
            a = _first_assign(c.node, expr.id)
            if a is not None:
                return self._queue_families(program, c, a, depth - 1,
                                            seen, by_key, call_index)
            return set()
        if isinstance(expr, ast.Call):
            tgt, _label = program._resolve_call(c.rel, c.cls_key, expr,
                                                c.atypes, c.ltypes)
            tctx = by_key.get(tgt) if tgt is not None else None
            if tctx is None:
                return set()
            fams: Set[str] = set()
            for n in ast.walk(tctx.node):
                if isinstance(n, ast.Return) and n.value is not None:
                    fams |= self._queue_families(
                        program, tctx, n.value, depth - 1, seen,
                        by_key, call_index)
            return fams
        return set()

    def _param_families(self, program, c: _Ctx, pname: str, depth: int,
                        seen: set, by_key, call_index) -> Set[str]:
        """A queue name that is a *parameter* of the enclosing helper:
        resolve it at every resolvable call site, attributing the name
        to the real producer/consumer behind the forwarder."""
        mark = (c.key, pname)
        if mark in seen or depth <= 0:
            return set()
        seen.add(mark)
        names = [a.arg for a in c.node.args.args]
        offset = 1 if (c.cls_key is not None and names
                       and names[0] == "self") else 0
        fams: Set[str] = set()
        for caller_key, call in call_index.get(c.key, ()):
            cc = by_key.get(caller_key)
            if cc is None:
                continue
            aexpr = None
            if pname in names:
                pi = names.index(pname) - offset
                if 0 <= pi < len(call.args):
                    aexpr = call.args[pi]
            for kw in call.keywords:
                if kw.arg == pname:
                    aexpr = kw.value
            if aexpr is not None:
                fams |= self._queue_families(program, cc, aexpr,
                                             depth - 1, seen, by_key,
                                             call_index)
        return fams

    def _tuple_families(self, c: _Ctx) -> Set[str]:
        """push_many embeds ``(queue, value)`` tuples in its items
        argument, usually built earlier in the function — scan the
        enclosing function for 2-tuples with a resolvable first
        element."""
        fams: Set[str] = set()
        for n in ast.walk(c.node):
            if isinstance(n, ast.Tuple) and len(n.elts) == 2:
                e0 = n.elts[0]
                if isinstance(e0, ast.Constant) and isinstance(
                        e0.value, str):
                    f = _family(e0.value, False)
                elif isinstance(e0, ast.JoinedStr):
                    p = _fstr_prefix(e0)
                    f = _family(p, True) if p else None
                else:
                    f = None
                if f:
                    fams.add(f)
        return fams

    def _queue_flow(self, program, contexts, by_key,
                    call_index) -> List[Finding]:
        sites: Dict[str, Dict[str, List[Tuple[str, int]]]] = {}
        busy: Set[str] = set()
        push_calls: List[Tuple[_Ctx, ast.Call]] = []
        pop_calls: List[Tuple[_Ctx, ast.Call]] = []
        for c in contexts:
            if c.rel.startswith(BUS_IMPL_PREFIX):
                continue
            for node in ast.walk(c.node):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                op = node.func.attr
                if op not in QUEUE_OPS:
                    continue
                if not self._bus_receiver(c, node.func.value):
                    continue
                busy.add(c.rel)
                kind = "push" if op in PUSH_OPS else "pop"
                (push_calls if kind == "push"
                 else pop_calls).append((c, node))
                qexpr = _queue_arg(op, node)
                if qexpr is not None:
                    fams = self._queue_families(
                        program, c, qexpr, MAX_FORWARD, set(), by_key,
                        call_index)
                else:
                    fams = self._tuple_families(c)
                for fam in fams:
                    sites.setdefault(fam, {}).setdefault(
                        kind, []).append((c.rel, node.lineno))

        findings: List[Finding] = []
        for fam in sorted(sites):
            pushes = sites[fam].get("push", [])
            pops = sites[fam].get("pop", [])
            if pushes and not pops:
                rel, line = pushes[0]
                findings.append(Finding(
                    code="RTA701", path=rel, line=line,
                    message=f"queue family '{fam}' is pushed here but "
                            f"no in-tree consumer ever pops it "
                            f"(orphan producer)",
                    hint="point a consumer at this queue name, or fix "
                         "the producer-side spelling; f-string names "
                         "resolve by literal prefix",
                    anchor=f"queue:{fam}"))
            elif pops and not pushes:
                rel, line = pops[0]
                findings.append(Finding(
                    code="RTA701", path=rel, line=line,
                    message=f"queue family '{fam}' is popped here but "
                            f"no in-tree producer ever pushes it "
                            f"(dead consumer)",
                    hint="wire a producer, or delete the consumer "
                         "loop; f-string names resolve by literal "
                         "prefix",
                    anchor=f"queue:{fam}"))
        findings.extend(self._op_tokens(program, busy, push_calls,
                                        pop_calls, contexts))
        return findings

    def _op_tokens(self, program, busy: Set[str], push_calls,
                   pop_calls, contexts) -> List[Finding]:
        """Control-frame op tokens (``__drain__``-style dunder strings
        defined next to bus queue ops): every token needs both a
        producer (pushed inside a bus push op) and a dispatcher (a
        membership/equality test, subscript, or dict-pop on the
        token)."""
        token_defs: Dict[str, Tuple[str, str, int]] = {}
        for rel in sorted(busy):
            mi = program.modules.get(rel)
            if mi is None or mi.tree is None:
                continue
            for stmt in mi.tree.body:
                if isinstance(stmt, ast.Assign) \
                        and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name) \
                        and isinstance(stmt.value, ast.Constant) \
                        and isinstance(stmt.value.value, str) \
                        and OP_TOKEN_RE.match(stmt.value.value):
                    token_defs.setdefault(
                        stmt.value.value,
                        (rel, stmt.targets[0].id, stmt.lineno))
        if not token_defs:
            return []
        by_def = {(program.modules[rel].modname, name): value
                  for value, (rel, name, _l) in token_defs.items()}

        def names_for(rel: str) -> Dict[str, str]:
            out = {name: value
                   for value, (drel, name, _l) in token_defs.items()
                   if drel == rel}
            mi = program.modules.get(rel)
            if mi is not None:
                for local, (modname, symbol) in mi.imports.items():
                    if symbol is not None \
                            and (modname, symbol) in by_def:
                        out[local] = by_def[(modname, symbol)]
            return out

        def refs(expr, names: Dict[str, str]) -> Set[str]:
            out: Set[str] = set()
            if isinstance(expr, ast.Name) and expr.id in names:
                out.add(names[expr.id])
            elif isinstance(expr, ast.Constant) \
                    and expr.value in token_defs:
                out.add(expr.value)
            return out

        produced: Set[str] = set()
        for c, call in push_calls:
            names = names_for(c.rel)
            for arg in list(call.args) + [kw.value
                                          for kw in call.keywords]:
                for n in ast.walk(arg):
                    produced |= refs(n, names)
        dispatched: Set[str] = set()
        for c in contexts:
            if c.rel.startswith(BUS_IMPL_PREFIX):
                continue
            names = names_for(c.rel)
            if not names:
                continue
            for n in ast.walk(c.node):
                if isinstance(n, ast.Compare) and any(
                        isinstance(op, (ast.In, ast.NotIn, ast.Eq,
                                        ast.NotEq)) for op in n.ops):
                    for side in [n.left] + list(n.comparators):
                        dispatched |= refs(side, names)
                elif isinstance(n, ast.Subscript):
                    dispatched |= refs(n.slice, names)
                elif isinstance(n, ast.Call) \
                        and _leaf(n.func) in ("pop", "get") \
                        and n.args:
                    dispatched |= refs(n.args[0], names)

        findings: List[Finding] = []
        for value in sorted(token_defs):
            rel, name, line = token_defs[value]
            if value in produced and value not in dispatched:
                findings.append(Finding(
                    code="RTA701", path=rel, line=line,
                    message=f"control token {name} ({value}) is "
                            f"pushed onto the bus but no dispatcher "
                            f"ever checks for it",
                    hint="add the token to the consumer's dispatch "
                         "(membership test / dict pop), or delete "
                         "the producer",
                    anchor=f"op-token:{value}"))
            elif value in dispatched and value not in produced:
                findings.append(Finding(
                    code="RTA701", path=rel, line=line,
                    message=f"control token {name} ({value}) is "
                            f"dispatched on but never pushed by any "
                            f"in-tree producer",
                    hint="wire the producer, or delete the dead "
                         "dispatch arm",
                    anchor=f"op-token:{value}"))
        return findings

    # ------------------------------------------------------------------
    # RTA702 — HTTP route drift
    # ------------------------------------------------------------------

    def _path_str(self, c: _Ctx, expr, depth: int) -> Optional[str]:
        if depth < 0 or expr is None:
            return None
        if isinstance(expr, ast.Constant) and isinstance(expr.value,
                                                         str):
            return expr.value
        if isinstance(expr, ast.JoinedStr):
            return "".join(
                str(v.value) if isinstance(v, ast.Constant) else "*"
                for v in expr.values)
        if isinstance(expr, ast.Name):
            a = _first_assign(c.node, expr.id)
            return self._path_str(c, a, depth - 1)
        if isinstance(expr, ast.BinOp) and isinstance(expr.op,
                                                      ast.Add):
            return self._path_str(c, expr.left, depth - 1)
        if isinstance(expr, ast.IfExp):
            return self._path_str(c, expr.body, depth - 1)
        return None

    def _call_sites(self, c: _Ctx,
                    node: ast.Call) -> List[Tuple[str, str]]:
        func = node.func
        leaf = _leaf(func)
        out: List[Tuple[str, str]] = []
        if leaf == "_call" and len(node.args) >= 2 \
                and isinstance(node.args[0], ast.Constant) \
                and str(node.args[0].value).upper() in HTTP_VERBS:
            p = self._path_str(c, node.args[1], 2)
            if p and p.startswith("/"):
                out.append((str(node.args[0].value).upper(), p))
        elif leaf in ("urlopen", "Request"):
            url = node.args[0] if node.args else None
            s = self._path_str(c, url, 1)
            if s and (s.startswith("http://")
                      or s.startswith("https://")):
                rest = s.split("://", 1)[1]
                i = rest.find("/")
                if i >= 0:
                    method = "GET"
                    if leaf == "Request" and len(node.args) >= 2:
                        method = "POST"  # positional data payload
                    for kw in node.keywords:
                        if kw.arg == "method" and isinstance(
                                kw.value, ast.Constant):
                            method = str(kw.value.value).upper()
                        elif kw.arg == "data" and method == "GET":
                            method = "POST"
                    out.append((method, rest[i:]))
        elif leaf in ("fetch", "fetch_endpoint"):
            for a in node.args[:2]:
                if isinstance(a, ast.Constant) \
                        and isinstance(a.value, str) \
                        and a.value.startswith("/"):
                    out.append(("GET", a.value))
                    break
        elif leaf in ("get", "post", "put", "delete") \
                and isinstance(func, ast.Attribute):
            rname = _recv_name(func.value)
            if rname and "session" in rname and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.BinOp) \
                        and isinstance(a0.op, ast.Add):
                    p = self._path_str(c, a0.right, 1)
                    if p and p.startswith("/"):
                        out.append((leaf.upper(), p))
        return out

    def _html_calls(self, root: str) -> List[Tuple[str, str, str, int]]:
        """Dashboard ``api("VERB", "/path")`` calls (string and
        template-literal forms; ``${...}`` becomes a wildcard)."""
        out: List[Tuple[str, str, str, int]] = []
        web = pathlib.Path(root) / "rafiki_tpu" / "web"
        if not web.is_dir():
            return out
        pat = re.compile(
            r'api\(\s*"(GET|POST|PUT|DELETE|PATCH)"\s*,\s*'
            r'(?:"([^"]*)"|`([^`]*)`)')
        for path in sorted(web.glob("*.html")):
            try:
                text = path.read_text(encoding="utf-8",
                                      errors="replace")
            except OSError:
                continue
            rel = path.relative_to(root).as_posix()
            for m in pat.finditer(text):
                raw = m.group(2) if m.group(2) is not None \
                    else m.group(3)
                raw = re.sub(r"\$\{[^}]*\}", "*", raw)
                line = text.count("\n", 0, m.start()) + 1
                out.append((m.group(1), raw, rel, line))
        return out

    def _route_drift(self, ctx: RepoContext, program,
                     contexts) -> List[Finding]:
        served: List[Tuple[str, str, str, int]] = []
        for mi in program.modules.values():
            if mi.tree is None:
                continue
            for node in ast.walk(mi.tree):
                if isinstance(node, (ast.Tuple, ast.List)) \
                        and len(node.elts) == 3:
                    e0, e1 = node.elts[0], node.elts[1]
                    if isinstance(e0, ast.Constant) \
                            and isinstance(e0.value, str) \
                            and e0.value.upper() in HTTP_VERBS \
                            and isinstance(e1, ast.Constant) \
                            and isinstance(e1.value, str) \
                            and e1.value.startswith("/"):
                        served.append((e0.value.upper(), e1.value,
                                       mi.rel, node.lineno))
        callers: List[Tuple[str, str, str, int]] = []
        for c in contexts:
            for node in ast.walk(c.node):
                if isinstance(node, ast.Call):
                    for m, p in self._call_sites(c, node):
                        callers.append((m, p, c.rel, node.lineno))
        callers.extend(self._html_calls(ctx.root))

        served_norm = [(m, _segs(p), p, rel, line)
                       for m, p, rel, line in served]
        matched = [False] * len(served_norm)
        findings: List[Finding] = []
        seen: Set[Tuple[str, str]] = set()
        for m, p, rel, line in callers:
            segs = _segs(p)
            hit = False
            for i, (sm, ssegs, _sp, _srel, _sl) in enumerate(
                    served_norm):
                if sm == m and _seg_match(segs, ssegs):
                    matched[i] = True
                    hit = True
            if not hit:
                disp = "/" + "/".join(segs)
                if (m, disp) in seen:
                    continue
                seen.add((m, disp))
                findings.append(Finding(
                    code="RTA702", path=rel, line=line,
                    message=f"HTTP call {m} {disp} matches no served "
                            f"route",
                    hint="fix the path/method to a registered route, "
                         "or register the route server-side",
                    anchor=f"route-call:{m} {disp}"))
        for i, (sm, _ssegs, sp, srel, sline) in enumerate(served_norm):
            if matched[i]:
                continue
            findings.append(Finding(
                code="RTA702", path=srel, line=sline,
                message=f"served route {sm} {sp} has no in-tree "
                        f"caller",
                hint="wire a caller (client SDK / dashboard / "
                     "scraper), or waive as an operator-only surface",
                anchor=f"route:{sm} {sp}"))
        return findings

    # ------------------------------------------------------------------
    # RTA703 — feature-flag off-path side effects
    # ------------------------------------------------------------------

    def _node_effects(self, fnode) -> List[Tuple[str, str, ast.AST]]:
        out: List[Tuple[str, str, ast.AST]] = []
        for n in ast.walk(fnode):
            if isinstance(n, ast.Call):
                leaf = _leaf(n.func)
                if leaf == "Thread":
                    out.append(("thread", "Thread()", n))
                elif leaf in SERIES_FACTORIES \
                        and isinstance(n.func, ast.Attribute):
                    recv = n.func.value
                    rleaf = _leaf(recv.func) if isinstance(
                        recv, ast.Call) else None
                    if rleaf == "registry":
                        name = ""
                        if n.args and isinstance(n.args[0],
                                                 ast.Constant):
                            name = str(n.args[0].value)
                        out.append(("series", name, n))
                elif leaf in ("socket", "create_connection"):
                    parts = _dotted(n.func)
                    if parts and parts[0] == "socket":
                        out.append(("socket",
                                    ".".join(parts) + "()", n))
                elif leaf == "urlopen":
                    out.append(("socket", "urlopen()", n))
            elif isinstance(n, ast.While):
                for sub in ast.walk(n):
                    if isinstance(sub, ast.Call) \
                            and isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr in ("pop", "pop_all"):
                        rn = _recv_name(sub.func.value)
                        if rn and "bus" in rn:
                            out.append((
                                "bus-loop",
                                f"subscription loop "
                                f"({sub.func.attr}())", n))
                            break
        return out

    @staticmethod
    def _gated_nodes(fnode, test) -> Set[int]:
        """ids of AST nodes only reachable under a gate the vocabulary
        test accepts. ``if <gate>: return`` gates the statements after
        it (the early-return shape); polarity is not tracked."""
        gated: Set[int] = set()

        def mark(n):
            for sub in ast.walk(n):
                gated.add(id(sub))

        def walk(stmts, gate: bool):
            for i, st in enumerate(stmts):
                if gate:
                    mark(st)
                    continue
                if isinstance(st, ast.If):
                    t = test(st.test)
                    walk(st.body, t)
                    walk(st.orelse, False)
                    if t and st.body and all(
                            isinstance(x, (ast.Return, ast.Raise,
                                           ast.Break, ast.Continue))
                            for x in st.body):
                        walk(list(stmts[i + 1:]), True)
                elif isinstance(st, ast.While):
                    walk(st.body, test(st.test))
                    walk(st.orelse, False)
                elif isinstance(st, (ast.For, ast.AsyncFor)):
                    walk(st.body, False)
                    walk(st.orelse, False)
                elif isinstance(st, ast.Try):
                    walk(st.body, False)
                    for h in st.handlers:
                        walk(h.body, False)
                    walk(st.orelse, False)
                    walk(st.finalbody, False)
                elif isinstance(st, (ast.With, ast.AsyncWith)):
                    walk(st.body, False)
                elif isinstance(st, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    walk(st.body, False)

        walk(fnode.body, False)
        for n in ast.walk(fnode):
            if isinstance(n, ast.IfExp) and test(n.test):
                mark(n.body)
        return gated

    def _flag_offpath(self, program, contexts, by_key,
                      call_index) -> List[Finding]:
        findings: List[Finding] = []
        for spec in FLAG_REGISTRY:
            findings.extend(self._audit_flag(spec, program, contexts,
                                             by_key, call_index))
        return findings

    def _audit_flag(self, spec, program, contexts, by_key,
                    call_index) -> List[Finding]:
        flag = spec["flag"]
        field = spec["field"]
        owned = set(spec["owned_modules"])
        series_prefixes = tuple(spec["owned_series"])

        def base_vocab(expr) -> bool:
            for n in ast.walk(expr):
                if isinstance(n, ast.Constant) \
                        and n.value in (flag, field):
                    return True
                if isinstance(n, ast.Attribute) and n.attr == field:
                    return True
            return False

        # Pass A: per-function locals bound from base vocabulary
        # (``cluster_on = _parse_bool(env(...cluster_fabric...))``).
        base_locals: Dict[tuple, Set[str]] = {}
        for c in contexts:
            locs: Set[str] = set()
            for n in ast.walk(c.node):
                if isinstance(n, ast.Assign) \
                        and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name) \
                        and base_vocab(n.value):
                    locs.add(n.targets[0].id)
            if locs:
                base_locals[c.key] = locs

        def vocab_a(c: _Ctx):
            locs = base_locals.get(c.key, set())

            def test(expr) -> bool:
                if base_vocab(expr):
                    return True
                return any(isinstance(n, ast.Name) and n.id in locs
                           for n in ast.walk(expr))
            return test

        gated_cache_a: Dict[tuple, Set[int]] = {}

        def gated_a(c: _Ctx) -> Set[int]:
            g = gated_cache_a.get(c.key)
            if g is None:
                g = self._gated_nodes(c.node, vocab_a(c))
                gated_cache_a[c.key] = g
            return g

        # Gate attributes: every truthy assignment is flag-gated or
        # flag-derived, so testing the attribute IS testing the flag.
        attr_assigns: Dict[str, List[Tuple[bool, bool]]] = {}
        for c in contexts:
            test = vocab_a(c)
            g = gated_a(c)
            for n in ast.walk(c.node):
                attr = val = None
                if isinstance(n, ast.Assign) \
                        and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Attribute):
                    attr, val = n.targets[0].attr, n.value
                elif isinstance(n, ast.AnnAssign) \
                        and isinstance(n.target, ast.Attribute) \
                        and n.value is not None:
                    attr, val = n.target.attr, n.value
                if attr is None:
                    continue
                truthy = not (isinstance(val, ast.Constant)
                              and (val.value is None
                                   or val.value is False))
                ok = (id(n) in g) or test(val)
                attr_assigns.setdefault(attr, []).append((truthy, ok))
        gate_attrs = {a for a, lst in attr_assigns.items()
                      if any(t for t, _ in lst)
                      and all(ok for t, ok in lst if t)}

        def vocab_b(c: _Ctx):
            locs = set(base_locals.get(c.key, set()))

            def contains(expr) -> bool:
                for n in ast.walk(expr):
                    if isinstance(n, ast.Constant) \
                            and n.value in (flag, field):
                        return True
                    if isinstance(n, ast.Attribute) \
                            and (n.attr == field
                                 or n.attr in gate_attrs):
                        return True
                    if isinstance(n, ast.Name) and n.id in locs:
                        return True
                return False

            for n in ast.walk(c.node):
                if isinstance(n, ast.Assign) \
                        and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name) \
                        and contains(n.value):
                    locs.add(n.targets[0].id)
            return contains

        gated_cache: Dict[tuple, Set[int]] = {}

        def gated(key: tuple) -> Set[int]:
            g = gated_cache.get(key)
            if g is None:
                c = by_key[key]
                g = self._gated_nodes(c.node, vocab_b(c))
                gated_cache[key] = g
            return g

        # Constructor sites of every resolvable class (Name-call form).
        ctor_sites: Dict[tuple, List[Tuple[tuple, ast.Call]]] = {}
        for c in contexts:
            for n in ast.walk(c.node):
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Name):
                    ck = program.resolve_class(c.rel, n.func.id)
                    if ck is not None:
                        ctor_sites.setdefault(ck, []).append(
                            (c.key, n))

        # Protected fixpoint: methods of construction-gated classes,
        # plus functions whose every resolvable call site is gated or
        # made from protected code.
        protected: Set[tuple] = set()
        methods_of: Dict[tuple, List[tuple]] = {}
        for key in by_key:
            if key[1] is not None:
                methods_of.setdefault((key[0], key[1]),
                                      []).append(key)
        for _round in range(10):
            changed = False
            for ck, csites in ctor_sites.items():
                if all(id(n) in gated(k) or k in protected
                       for k, n in csites):
                    for mkey in methods_of.get(ck, ()):
                        if mkey not in protected:
                            protected.add(mkey)
                            changed = True
            for fkey, fsites in call_index.items():
                if fkey in protected or fkey not in by_key:
                    continue
                if fsites and all(id(n) in gated(k) or k in protected
                                  for k, n in fsites):
                    protected.add(fkey)
                    changed = True
            if not changed:
                break

        findings: List[Finding] = []

        def disp(key: tuple) -> str:
            return f"{key[1]}.{key[2]}" if key[1] else key[2]

        # V1: ungated import-time effects in an owned module.
        for rel in sorted(owned & set(program.modules)):
            mi = program.modules[rel]
            if mi.tree is None:
                continue
            for gate, kind, label, n in self._import_effects(
                    mi.tree, base_vocab):
                if not gate:
                    findings.append(Finding(
                        code="RTA703", path=rel, line=n.lineno,
                        message=f"{label} runs at import time of "
                                f"{rel}, which {flag} (default off) "
                                f"owns — the off path pays for it",
                        hint=f"move the effect behind the {flag} "
                             f"gate (lazy construction)",
                        anchor=f"{flag}:import-effect:{label}"))
        # V2: ungated construction of an owned-module class.
        for ck in sorted(ctor_sites, key=lambda k: (k[0], k[1])):
            if ck[0] not in owned:
                continue
            for key, n in ctor_sites[ck]:
                if id(n) in gated(key) or key in protected:
                    continue
                findings.append(Finding(
                    code="RTA703", path=key[0], line=n.lineno,
                    message=f"{ck[1]} (owned by default-off {flag}) "
                            f"is constructed in {disp(key)}() without "
                            f"passing the flag gate",
                    hint=f"guard the construction with the {flag} "
                         f"gate, or move it behind a protected "
                         f"(all-call-sites-gated) helper",
                    anchor=f"{flag}:unguarded-ctor:{ck[1]}"
                           f"@{disp(key)}"))
        # V3: ungated effect in an owned-module function that is not
        # protected by construction/call-site gating.
        for c in contexts:
            if c.rel not in owned or c.key in protected:
                continue
            g = gated(c.key)
            for kind, label, n in self._node_effects(c.node):
                if id(n) in g:
                    continue
                findings.append(Finding(
                    code="RTA703", path=c.rel, line=n.lineno,
                    message=f"{disp(c.key)}() in {flag}-owned "
                            f"{c.rel} reaches {kind} effect {label} "
                            f"without the flag gate (and the "
                            f"function is reachable off-path)",
                    hint="gate the effect, or gate every call site "
                         "so the function becomes protected",
                    anchor=f"{flag}:offpath:{disp(c.key)}:{label}"))
        # V4: owned-prefix metric series registered outside the owned
        # modules without a gate.
        for c in contexts:
            if c.rel in owned or c.key in protected:
                continue
            g = gated(c.key)
            for kind, label, n in self._node_effects(c.node):
                if kind != "series" or id(n) in g:
                    continue
                if label.startswith(series_prefixes):
                    findings.append(Finding(
                        code="RTA703", path=c.rel, line=n.lineno,
                        message=f"metric series {label} (a {flag} "
                                f"surface) is registered in "
                                f"{disp(c.key)}() without the flag "
                                f"gate — it would appear on scrapes "
                                f"with the flag off",
                        hint="register the series under the flag "
                             "gate (the disabled-means-free "
                             "discipline)",
                        anchor=f"{flag}:series:{label}"))
        return findings

    def _import_effects(self, tree, test):
        """(gated, kind, label, node) for effects executed at import
        time: the top-level statement walk descends class bodies but
        never function bodies, tracking flag gates on the way."""
        out: List[Tuple[bool, str, str, ast.AST]] = []

        def stmts(body, gate: bool):
            for st in body:
                if isinstance(st, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                    continue
                if isinstance(st, ast.ClassDef):
                    stmts(st.body, gate)
                    continue
                if isinstance(st, ast.If):
                    stmts(st.body, gate or test(st.test))
                    stmts(st.orelse, gate)
                    continue
                if isinstance(st, ast.Try):
                    stmts(st.body, gate)
                    for h in st.handlers:
                        stmts(h.body, gate)
                    stmts(st.orelse, gate)
                    stmts(st.finalbody, gate)
                    continue
                for kind, label, n in self._node_effects(st):
                    out.append((gate, kind, label, n))

        stmts(tree.body, False)
        return out
