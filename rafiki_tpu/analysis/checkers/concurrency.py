"""RTA104-106 — whole-program concurrency: the cross-object races the
per-class RTA1xx checkers cannot see.

Historical bugs this encodes (docs/analysis.md):

- the r14 persist-pipeline circuit-breaker reset and the r12
  promote double-allocation were both *cross-object* bugs that
  survived review precisely because RTA1xx reasoned one class at a
  time;
- the r12 promote path deliberately blocks under a node-wide lock
  (waived), and review had to find every accidental sibling by hand.

All three codes ride :class:`analysis.program.Program` — the shared
symbol table / call graph / lock graph built once per run:

RTA104: interprocedural lock-order cycle whose locks live in MORE THAN
ONE class (the intra-class form stays RTA103). Method A of class X
holding ``X._lock`` while a helper three frames down takes
``Y._lock``, while some path orders them the other way, deadlocks the
moment both run concurrently — across classes and modules.

RTA105: blocking call (the RTA102 predicate, plus bus/broker
round-trips through typed receivers) reached THROUGH the call graph
while a lock is held. RTA102 flags ``time.sleep`` under ``with
self._lock:`` in the same method; RTA105 flags the same sleep three
frames down in another module.

RTA106: an attribute written from one THREAD ROOT and accessed from
another with NO lock held on either side anywhere (``Thread(target=)``
bodies, executor-submitted closures, HTTP route handlers — the
program's thread-root inventory). Attributes that are guarded
*somewhere* stay RTA101 territory; RTA106 exists for state nobody ever
locks — the unguarded-cross-thread-write class.

Known blind spots (documented in docs/analysis.md): dynamic dispatch
(``getattr``/callables in containers), receivers whose type does not
resolve through the bounded alias rules, locks passed as arguments,
and chains deeper than ``program.MAX_CHAIN_DEPTH`` frames.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..core import Checker, Finding, RepoContext, register
from ..program import Program


def _sccs(graph: Dict[str, Set[str]]) -> List[Set[str]]:
    """Tarjan, iterative; returns the strongly connected components of
    the lock digraph (singletons included — callers filter)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    def strongconnect(root: str) -> None:
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
            if low[v] == index[v]:
                comp: Set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.add(w)
                    if w == v:
                        break
                out.append(comp)

    for node in sorted(graph):
        if node not in index:
            strongconnect(node)
    return out


@register
class ConcurrencyChecker(Checker):
    name = "concurrency"
    codes = ("RTA104", "RTA105", "RTA106")
    #: Interprocedural facts need the full symbol table, so this is a
    #: repo-scope checker: it runs whole-program whenever any package
    #: file changed.
    scope = "repo"

    def run(self, ctx: RepoContext) -> List[Finding]:
        program = ctx.program()
        findings: List[Finding] = []
        findings.extend(self._lock_cycles(program))
        findings.extend(self._blocking_chains(program))
        findings.extend(self._cross_root_state(program))
        return findings

    # --- RTA104: cross-class lock-order cycles ---

    def _lock_edges(self, program: Program
                    ) -> Dict[Tuple[str, str], Tuple[tuple, int, str]]:
        """(outer, inner) -> (method key, line, how). Edges come from a
        direct acquisition under a held lock and from a call made under
        a held lock into a method whose transitive closure acquires
        more locks."""
        closure = program.locks_closure()
        edges: Dict[Tuple[str, str], Tuple[tuple, int, str]] = {}
        for key, s in program.summaries().items():
            for lock_id, held, line in s.direct_locks:
                for outer in held:
                    edges.setdefault((outer, lock_id),
                                     (key, line, "acquires"))
            for held, target, line, label in s.calls:
                if not held or target is None:
                    continue
                for inner in closure.get(target, ()):
                    for outer in held:
                        if outer != inner:
                            edges.setdefault(
                                (outer, inner),
                                (key, line, f"calls {label or '?'}"))
        return edges

    def _lock_cycles(self, program: Program) -> List[Finding]:
        edges = self._lock_edges(program)
        findings: List[Finding] = []
        paired: Set[str] = set()
        for (a, b), (key, line, how) in sorted(edges.items()):
            if not (a < b and (b, a) in edges):
                continue
            owner = program.lock_owner(a)
            if owner == program.lock_owner(b) and \
                    program.by_modname.get(owner) is None:
                continue  # intra-class: RTA103's territory. Two locks
                # of one MODULE stay ours: free functions have no class
                # walk, so nothing else would ever see the cycle.
            anchor = f"{a}<->{b}"
            if anchor in paired:
                continue
            paired.add(anchor)
            paired.update((a, b))
            okey, oline, _ohow = edges[(b, a)]
            chain_ab = " -> ".join(program.lock_chain(key, b))
            chain_ba = " -> ".join(program.lock_chain(okey, a))
            findings.append(Finding(
                code="RTA104", path=key[0], line=line,
                message=f"cross-class lock-order cycle: {a} -> {b} "
                        f"({program.describe(key)} {how}; chain "
                        f"{chain_ab}) vs {b} -> {a} "
                        f"({program.describe(okey)} in "
                        f"{okey[0]}:{oline}; chain {chain_ba})",
                hint="pick ONE acquisition order for the two classes "
                     "and restructure the other path (snapshot under "
                     "one lock, act under the other)",
                anchor=anchor))
        # Longer cycles (A->B->C->A with no opposing pair) reduce to a
        # strongly connected component of the lock digraph. Report each
        # multi-class SCC not already covered by a pair finding.
        for scc in _sccs({a: {b for (x, b) in edges if x == a}
                          for (a, _b) in edges}):
            if len(scc) < 3 or any(lock in paired for lock in scc):
                continue
            owners = {program.lock_owner(x) for x in scc}
            if len(owners) < 2 and \
                    program.by_modname.get(next(iter(owners))) is None:
                continue
            cyc = sorted(scc)
            key, line, how = edges[next(
                (a, b) for a in cyc for b in cyc if (a, b) in edges)]
            findings.append(Finding(
                code="RTA104", path=key[0], line=line,
                message=f"cross-class lock-order cycle over "
                        f"{len(cyc)} locks: {' / '.join(cyc)} "
                        f"(first edge in {program.describe(key)}; "
                        f"every lock here is reachable from every "
                        f"other while held)",
                hint="pick ONE global acquisition order for these "
                     "classes and restructure the off-order paths",
                anchor="cycle:" + "|".join(cyc)))
        return findings

    # --- RTA105: blocking reached through the call graph under a lock ---

    def _blocking_chains(self, program: Program) -> List[Finding]:
        blocking = program.blocking_closure()
        # One DEFECT = one finding: a chain A -> B -> C -> sleep with
        # the lock held across every frame (the caller-holds fixpoint
        # makes each frame a candidate) must not demand a waiver per
        # frame. Group by (held locks, terminal blocking method,
        # label) and keep the frame CLOSEST to the block — the most
        # precise site, and the one a fix/waiver naturally anchors to.
        # rank is all-str/int (method keys contain None for module
        # functions and would TypeError under tuple comparison).
        best: Dict[tuple, Tuple[tuple, tuple, tuple, int]] = {}
        for key, s in program.summaries().items():
            for held, target, line, label in s.calls:
                if not held or target is None:
                    continue
                entry = blocking.get(target)
                if entry is None:
                    continue
                blabel = entry[0]
                terminal = target
                for _ in range(16):
                    nxt = blocking.get(terminal)
                    if nxt is None or nxt[2] is None:
                        break
                    terminal = nxt[2]
                depth = len(program.blocking_chain(target))
                group = (held, terminal, blabel)
                rank = (depth, key[0], program.describe(key), line)
                if group not in best or rank < best[group][0]:
                    best[group] = (rank, key, target, line)
        findings: List[Finding] = []
        for (held, _terminal, blabel), \
                (_rank, key, target, line) in sorted(
                    best.items(),
                    key=lambda kv: (kv[1][1][0], kv[1][0])):
            chain = [program.describe(key)] + \
                program.blocking_chain(target)
            locks = "/".join(sorted(held))
            findings.append(Finding(
                code="RTA105", path=key[0], line=line,
                message=f"{program.describe(key)}() holds {locks} "
                        f"while the call chain "
                        f"{' -> '.join(chain)} reaches blocking "
                        f"{blabel}",
                hint="release the lock before the call (snapshot "
                     "state under the lock, do the slow work "
                     "after), or waive with why the stall is "
                     "acceptable",
                anchor=(f"{program.describe(key)}->"
                        f"{program.describe(target)}:{blabel}")))
        # Direct blocking under a held lock in a FREE function — the
        # module-global-lock case. Inside a method the same shape is
        # RTA102's (per-class) territory; module-level code has no
        # class, so this is the only checker that can see it.
        seen: Set[Tuple[tuple, str]] = set()
        for key, s in sorted(program.summaries().items(),
                             key=lambda kv: (kv[0][0],
                                             str(kv[0][1]),
                                             kv[0][2])):
            if s.cls_key is not None:
                continue
            for held, blabel, line in s.held_blocking:
                if (key, blabel) in seen:
                    continue
                seen.add((key, blabel))
                findings.append(Finding(
                    code="RTA105", path=key[0], line=line,
                    message=f"{program.describe(key)}() holds "
                            f"{'/'.join(sorted(held))} while calling "
                            f"blocking {blabel} directly",
                    hint="move the blocking call outside the `with` "
                         "block, or waive with why the stall under "
                         "the module lock is acceptable",
                    anchor=f"{program.describe(key)}:{blabel}:direct"))
        return findings

    # --- RTA106: cross-thread-root unguarded shared state ---

    def _cross_root_state(self, program: Program) -> List[Finding]:
        findings: List[Finding] = []
        for mi in program.modules.values():
            for cname, cnode in sorted(mi.classes.items()):
                findings.extend(self._class_roots(
                    program, mi.rel, cname, cnode))
        return findings

    def _class_roots(self, program: Program, rel: str, cname: str,
                     cnode) -> List[Finding]:
        info = program.class_info(cnode)
        roots = dict(info.thread_roots())
        # Roots registered FROM OTHER classes (or free functions):
        # Thread(target=self.consumer.loop) in an owner makes loop a
        # root HERE — the bus-consumer shape, where the class that
        # owns the loop never constructs the thread itself.
        roots.update(program.extra_class_roots((rel, cname)))
        if not roots:
            return []
        graph = info.self_call_graph()
        extra = info.held_extra()

        def reach(starts: Set[str]) -> Set[str]:
            out = set(starts)
            frontier = list(starts)
            while frontier:
                m = frontier.pop()
                for callee in graph.get(m, ()):
                    if callee not in out:
                        out.add(callee)
                        frontier.append(callee)
            return out

        #: side name -> (reachable method set, closure-root or None).
        #: A closure root "meth/fn" owns accesses whose fn_stack
        #: contains fn inside meth; a method root owns its reach set.
        sides: Dict[str, Tuple[Set[str], Optional[Tuple[str, str]]]] = {}
        root_methods: Set[str] = set()
        for rid, (_kind, detail) in roots.items():
            if "/" in detail:
                meth, fn = detail.split("/", 1)
                sides[rid] = (set(), (meth, fn))
            else:
                sides[rid] = (reach({detail}), None)
                root_methods.add(detail)
        public = {m.name for m in info.methods()
                  if not m.name.startswith("_")} - root_methods
        caller_reach = reach(public)
        sides["caller"] = (caller_reach, None)

        def side_of(acc) -> List[str]:
            out = []
            for sid, (methods, closure) in sides.items():
                if closure is not None:
                    meth, fn = closure
                    if acc.method == meth and fn in acc.fn_stack:
                        out.append(sid)
                elif acc.method in methods and not acc.fn_stack:
                    out.append(sid)
            return out

        def effective(acc) -> frozenset:
            if acc.nested:
                return acc.held
            return acc.held | extra.get(acc.method, frozenset())

        candidates = (info.state_attrs - info.lock_attrs
                      - info.atomic_attrs - info.thread_attrs)
        # Guarded-somewhere attrs are RTA101's job; RTA106 is for state
        # nobody ever locks.
        ever_locked = {acc.attr for acc in info.accesses
                       if effective(acc)}
        findings: List[Finding] = []
        for attr in sorted(candidates - ever_locked):
            accs = [a for a in info.accesses
                    if a.attr == attr and a.method != "__init__"]
            by_side: Dict[str, List] = {}
            for a in accs:
                for sid in side_of(a):
                    by_side.setdefault(sid, []).append(a)
            if len(by_side) < 2:
                continue
            write_sides = {sid for sid, lst in by_side.items()
                           if any(a.is_write for a in lst)}
            if not write_sides:
                continue
            wsid = sorted(write_sides)[0]
            wacc = next(a for a in by_side[wsid] if a.is_write)
            osid = next(s for s in sorted(by_side) if s != wsid)
            oacc = by_side[osid][0]
            root_desc = {sid: roots[sid][1] if sid in roots else "callers"
                         for sid in (wsid, osid)}
            findings.append(Finding(
                code="RTA106", path=rel, line=wacc.line,
                message=f"{cname}.{attr} is written from thread root "
                        f"{root_desc[wsid]!r} ({wacc.method}:"
                        f"{wacc.line}) and "
                        f"{'written' if oacc.is_write else 'read'} "
                        f"from {root_desc[osid]!r} ({oacc.method}:"
                        f"{oacc.line}) with no lock held on either "
                        f"side",
                hint="guard both sides with one lock, hand the value "
                     "over through a Queue/Event, or waive with why "
                     "the race is benign (e.g. monotonic flag, "
                     "GIL-atomic scalar)",
                anchor=f"{cname}.{attr}:cross-root"))
        return findings
