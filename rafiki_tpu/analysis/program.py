"""Whole-program model: repo-wide symbol table, call graph, lock
graph, and thread-root inventory — built ONCE per run and shared by
every checker (the single-parse contract: ``RepoContext.program()``).

Before r15 each checker reasoned one class at a time, which is exactly
why the r14 circuit-breaker reset and the r12 promote double-allocation
survived review: both were *cross-object* races. This module gives the
RTA1xx family (and the new RTA104-106) the global view:

- **Symbol table.** Repo-relative path -> dotted module name, the
  module-level import map (absolute + relative imports resolved to
  repo modules), every top-level class and function.
- **Attribute types, bounded.** ``self.x = ServingStats(...)`` (any
  call inside the RHS, so ``stats or ServingStats()`` resolves too),
  ``self.x = param`` where the parameter is annotated with a repo
  class, and one level of local aliasing (``s = self.stats``) inside a
  method. Class names resolve through the import map first, then by
  globally-unique simple name. Anything fancier (``getattr``, dicts of
  objects, factory indirection) is deliberately out of scope — the
  documented blind spots in docs/analysis.md.
- **Method summaries.** Per method: locks acquired directly (OWN locks
  and foreign ones taken via a typed attribute, both as
  class-qualified ids), resolved call sites with the lexically-held
  lock set, and whether the body makes a blocking call (the RTA102
  predicate, plus bus/cache round-trips via typed receivers).
- **Transitive closures, bounded.** Locks a method may acquire through
  its callees (fixpoint, capped at ``MAX_FIXPOINT_ROUNDS``) and the
  nearest blocking call reachable through the call graph (reverse BFS,
  capped at ``MAX_CHAIN_DEPTH`` frames) — with enough breadcrumbs to
  print the actual frame chain in a finding.
- **Thread roots.** Every ``Thread(target=...)``, executor
  ``submit(...)`` (method or locally-defined closure), and HTTP route
  handler (the repo's ``("GET", "/path", self._handler)`` route-tuple
  idiom) per class, plus intra-class reachability from each root — the
  basis of the RTA106 cross-thread shared-state inference.

Everything is stdlib ``ast`` over the already-parsed trees; nothing is
imported or executed.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: Lock/sync primitive construction, shared with guarded_state.
LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
ATOMIC_FACTORIES = {"Event", "Semaphore", "BoundedSemaphore", "Barrier",
                    "local", "Queue", "SimpleQueue", "LifoQueue",
                    "PriorityQueue"}
MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
            "pop", "popleft", "popitem", "remove", "discard", "clear",
            "update", "setdefault", "add"}

#: Module roots whose calls block (network, processes, disk trees).
BLOCKING_MODULES = {"subprocess", "socket", "requests", "urllib"}

#: Modules whose classes do a bus/broker round-trip per method call —
#: a call on a receiver typed to one of these blocks (network I/O).
BUS_MODULE_MARKERS = ("rafiki_tpu/bus/", "rafiki_tpu/cache.py")

#: Interprocedural bounds (the suite is a pre-commit gate: predictable
#: wall time beats completeness — anything deeper than these is a
#: documented blind spot, not a hang).
MAX_FIXPOINT_ROUNDS = 30
MAX_CHAIN_DEPTH = 8


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _dotted(node: ast.AST) -> List[str]:
    """``a.b.c(...)`` -> ["a", "b", "c"]; best effort."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return list(reversed(parts))


class _Access:
    __slots__ = ("attr", "held", "method", "line", "is_write", "nested",
                 "fn_stack")

    def __init__(self, attr, held, method, line, is_write, nested,
                 fn_stack=()):
        self.attr = attr
        self.held = held
        self.method = method
        self.line = line
        self.is_write = is_write
        self.nested = nested
        #: Names of the nested defs enclosing this access (innermost
        #: last) — empty for depth-0 method-body accesses. Lets RTA106
        #: attribute a closure's accesses to the thread root the
        #: closure was submitted to.
        self.fn_stack = fn_stack


def _foreign_lock_token(expr: ast.AST) -> Optional[str]:
    """``with self.stats._lock:`` — a lock REACHED through another
    object. Held-set token ``"stats._lock"`` (renders as
    ``self.stats._lock``): consistently guarding own state with a
    collaborator's lock is a real guard, and RTA101/102/106 must see
    it. Name-based (lock/cond/mutex leaf) because the per-class walk
    has no type information; the typed form feeds RTA104/105 via
    ``_QualifiedWalker``."""
    if isinstance(expr, ast.Attribute):
        owner = _self_attr(expr.value)
        leaf = expr.attr.lower()
        if owner is not None and ("lock" in leaf or "cond" in leaf
                                  or "mutex" in leaf):
            return f"{owner}.{expr.attr}"
    return None


#: Held-set tokens for module-GLOBAL locks taken inside a class method
#: (``with _REG_LOCK:``) carry this prefix + the module-qualified lock
#: id, so they can never collide with a self-attribute token and so
#: the same lock unifies across every class/function that shares it.
MODULE_LOCK_TOKEN = "::"


def held_display(token: str) -> str:
    """Render a held-set token the way the source spells it:
    ``self.<attr>`` for own/foreign attribute locks, the module-
    qualified name for module-global ones."""
    if token.startswith(MODULE_LOCK_TOKEN):
        return token[len(MODULE_LOCK_TOKEN):]
    return f"self.{token}"


class _MethodWalker(ast.NodeVisitor):
    """Walks one method body tracking the lexically-held lock set."""

    def __init__(self, cls: "_ClassInfo", method: str):
        self.cls = cls
        self.method = method
        self.held: Tuple[str, ...] = ()
        self.depth = 0  # nested function depth (closures run later)
        self.fn_stack: Tuple[str, ...] = ()

    # --- lock context ---

    def visit_With(self, node: ast.With) -> None:
        entered = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr in self.cls.lock_attrs:
                entered.append(attr)
                self.cls.lock_entries.append(
                    (frozenset(self.held), attr, item.context_expr.lineno,
                     self.method, self.depth))
            else:
                # Foreign and module-global locks enter the HELD set
                # (they guard) but not lock_entries (RTA103's ordering
                # stays own-lock).
                token = _foreign_lock_token(item.context_expr)
                if token is None and \
                        isinstance(item.context_expr, ast.Name) and \
                        item.context_expr.id in self.cls.module_locks:
                    token = MODULE_LOCK_TOKEN + \
                        self.cls.module_locks[item.context_expr.id]
                if token is None and \
                        isinstance(item.context_expr, ast.Attribute) \
                        and isinstance(item.context_expr.value,
                                       ast.Name):
                    # Dotted module-global lock (``with mod._LOCK:``)
                    # — the module_lock_names map keys the dotted
                    # spelling, so it yields the same qualified token
                    # as the bare-name form.
                    dotted = (f"{item.context_expr.value.id}."
                              f"{item.context_expr.attr}")
                    if dotted in self.cls.module_locks:
                        token = MODULE_LOCK_TOKEN + \
                            self.cls.module_locks[dotted]
                if token is not None:
                    entered.append(token)
                else:
                    self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        prior = self.held
        self.held = tuple(self.held) + tuple(entered)
        for stmt in node.body:
            self.visit(stmt)
        self.held = prior

    # --- scope boundaries ---

    def _enter_nested(self, node) -> None:
        prior, self.held = self.held, ()
        self.depth += 1
        name = getattr(node, "name", "<lambda>")
        prior_stack, self.fn_stack = \
            self.fn_stack, self.fn_stack + (name,)
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.fn_stack = prior_stack
        self.depth -= 1
        self.held = prior

    def visit_FunctionDef(self, node):
        self.cls.nested_defs.append((self.method, self.fn_stack,
                                     node.name, node))
        self._enter_nested(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._enter_nested(node)

    # --- accesses ---

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None:
            self.cls.accesses.append(_Access(
                attr, frozenset(self.held), self.method, node.lineno,
                isinstance(node.ctx, (ast.Store, ast.Del)),
                self.depth > 0, self.fn_stack))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        self.cls.calls.append(
            (node, frozenset(self.held), self.method, self.depth,
             self.fn_stack))
        # A container-mutator call on a self attribute is a WRITE of
        # that attribute (RTA106 cares about writes, and `x.append` is
        # how most shared containers are written).
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in MUTATORS:
            owner = _self_attr(node.func.value)
            if owner is not None:
                self.cls.accesses.append(_Access(
                    owner, frozenset(self.held), self.method,
                    node.lineno, True, self.depth > 0, self.fn_stack))
        self.generic_visit(node)


class _ClassInfo:
    """One class's locks, state attributes, accesses and intra-class
    call graph — the unit the RTA1xx checkers (and the whole-program
    pass) share. Walked at most once per run via ``Program``."""

    def __init__(self, node: ast.ClassDef,
                 module_locks: Optional[Dict[str, str]] = None):
        self.node = node
        self.name = node.name
        #: local name -> module-qualified id for the module-global
        #: sync primitives visible where this class is defined: a
        #: ``with _REG_LOCK:`` in a method guards exactly like an own
        #: lock (the workload-recorder shape, r18) — without this the
        #: guarded-state family reads such classes as lock-free.
        self.module_locks: Dict[str, str] = module_locks or {}
        self.lock_attrs: Set[str] = set()
        self.lock_kind: Dict[str, str] = {}      # attr -> factory name
        self.atomic_attrs: Set[str] = set()
        self.thread_attrs: Set[str] = set()
        self.state_attrs: Set[str] = set()
        self.accesses: List[_Access] = []
        # (node, held, method, nested-depth, fn_stack)
        self.calls: List[Tuple[ast.Call, frozenset, str, int, tuple]] = []
        # (outer_held, lock, line, method, nested-depth)
        self.lock_entries: List[Tuple[frozenset, str, int, str, int]] = []
        # (method, enclosing fn_stack, def name, node)
        self.nested_defs: List[Tuple[str, tuple, str, ast.AST]] = []
        self._walked = False

    # -- pass 1: classify attributes --

    def classify(self) -> None:
        for method in self._methods():
            in_init = method.name == "__init__"
            for sub in ast.walk(method):
                if isinstance(sub, (ast.Assign, ast.AnnAssign,
                                    ast.AugAssign)):
                    targets = (sub.targets
                               if isinstance(sub, ast.Assign)
                               else [sub.target])
                    for tgt in targets:
                        self._classify_target(tgt, sub, in_init)
                elif isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute):
                    owner = _self_attr(sub.func.value)
                    if owner is not None and sub.func.attr in MUTATORS:
                        self.state_attrs.add(owner)

    def _classify_target(self, tgt: ast.AST, stmt, in_init: bool) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._classify_target(el, stmt, in_init)
            return
        if isinstance(tgt, ast.Subscript):
            owner = _self_attr(tgt.value)
            if owner is not None:
                self.state_attrs.add(owner)
            return
        attr = _self_attr(tgt)
        if attr is None:
            return
        value = getattr(stmt, "value", None)
        factory = self._factory_of(value)
        if factory in LOCK_FACTORIES:
            self.lock_attrs.add(attr)
            self.lock_kind[attr] = factory
            return
        if factory in ATOMIC_FACTORIES:
            self.atomic_attrs.add(attr)
            return
        if factory == "Thread":
            self.thread_attrs.add(attr)
        if not in_init:
            self.state_attrs.add(attr)

    @staticmethod
    def _factory_of(value) -> Optional[str]:
        if isinstance(value, ast.Call):
            parts = _dotted(value.func)
            if parts:
                return parts[-1]
        return None

    def _methods(self):
        for item in self.node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield item

    def methods(self) -> List[ast.FunctionDef]:
        return list(self._methods())

    # -- pass 2: walk --

    def walk(self) -> None:
        if self._walked:
            return
        self._walked = True
        for method in self._methods():
            walker = _MethodWalker(self, method.name)
            for stmt in method.body:
                walker.visit(stmt)

    # -- held-by-callers fixpoint --

    def held_extra(self) -> Dict[str, frozenset]:
        """Locks a private method may assume held because every
        intra-class call site holds them."""
        cached = getattr(self, "_held_extra", None)
        if cached is not None:
            return cached
        sites: Dict[str, List[Tuple[frozenset, str, int]]] = {}
        for call, held, method, depth, _fns in self.calls:
            callee = _self_attr(call.func) \
                if isinstance(call.func, ast.Attribute) else None
            if callee and callee.startswith("_") and depth == 0:
                sites.setdefault(callee, []).append(
                    (held, method, depth))
        extra: Dict[str, frozenset] = {}
        for _ in range(3):  # call chains are shallow; 3 is plenty
            changed = False
            for callee, callsites in sites.items():
                effective = [held | extra.get(method, frozenset())
                             for held, method, _ in callsites]
                new = frozenset.intersection(*effective) if effective \
                    else frozenset()
                if new != extra.get(callee, frozenset()):
                    extra[callee] = new
                    changed = True
            if not changed:
                break
        self._held_extra = extra
        return extra

    # -- acquired-locks fixpoint (intra-class, for RTA103) --

    def acquired(self) -> Dict[str, Set[str]]:
        direct: Dict[str, Set[str]] = {}
        callees: Dict[str, Set[str]] = {}
        for held, lock, _line, method, depth in self.lock_entries:
            if depth == 0:
                direct.setdefault(method, set()).add(lock)
        for call, _held, method, depth, _fns in self.calls:
            callee = _self_attr(call.func) \
                if isinstance(call.func, ast.Attribute) else None
            if callee and depth == 0:
                callees.setdefault(method, set()).add(callee)
        acq = {m: set(locks) for m, locks in direct.items()}
        for _ in range(3):
            changed = False
            for method, cs in callees.items():
                cur = acq.setdefault(method, set())
                for c in cs:
                    extra = acq.get(c, set()) - cur
                    if extra:
                        cur.update(extra)
                        changed = True
            if not changed:
                break
        return acq

    # -- intra-class self-call graph + thread roots (RTA106 basis) --

    def self_call_graph(self) -> Dict[str, Set[str]]:
        """method -> self-methods it calls at depth 0 (closures are
        attributed to the root that RUNS them, not the method that
        defines them)."""
        graph: Dict[str, Set[str]] = {}
        for call, _held, method, depth, _fns in self.calls:
            callee = _self_attr(call.func) \
                if isinstance(call.func, ast.Attribute) else None
            if callee and depth == 0:
                graph.setdefault(method, set()).add(callee)
        return graph

    def thread_roots(self) -> Dict[str, Tuple[str, str]]:
        """root id -> (kind, detail). Roots are the entrypoints OTHER
        threads run:

        - ``thread:<m>`` — ``Thread(target=self.m)`` anywhere in the
          class (also ``run_in_thread``-style wrappers taking a bound
          method as ``target=``);
        - ``submit:<m>`` / ``submit:<meth>/<fn>`` — an executor
          ``submit`` of a bound method or of a closure defined in
          ``<meth>``;
        - ``handler:<m>`` — the repo's HTTP route-tuple idiom
          ``("GET", "/path", self.m)`` (JsonHttpServer dispatches on
          per-request server threads).
        """
        roots: Dict[str, Tuple[str, str]] = {}
        local_defs = {(m, name) for m, _stack, name, _n
                      in self.nested_defs}

        def root_of(arg: ast.AST, method: str) -> Optional[str]:
            attr = _self_attr(arg)
            if attr is not None:
                return attr
            if isinstance(arg, ast.Name) and \
                    (method, arg.id) in local_defs:
                return f"{method}/{arg.id}"
            return None

        for call, _held, method, _depth, _fns in self.calls:
            func = call.func
            leaf = func.attr if isinstance(func, ast.Attribute) else \
                (func.id if isinstance(func, ast.Name) else "")
            if leaf == "Thread":
                for kw in call.keywords:
                    if kw.arg == "target":
                        r = root_of(kw.value, method)
                        if r:
                            roots[f"thread:{r}"] = ("thread", r)
            elif leaf == "submit" and call.args:
                # Only executor-shaped receivers: self.<pool>.submit /
                # <local>.submit — predictor.predict_submit-style app
                # methods are not thread hops.
                owner = func.value if isinstance(func, ast.Attribute) \
                    else None
                ownername = (_self_attr(owner) or
                             (owner.id if isinstance(owner, ast.Name)
                              else "")) if owner is not None else ""
                if "pool" in ownername or "executor" in ownername \
                        or "exec" in ownername:
                    r = root_of(call.args[0], method)
                    if r:
                        roots[f"submit:{r}"] = ("submit", r)
        # Route tuples: ("GET", "/path", self.m) anywhere in the class.
        for node in ast.walk(self.node):
            if isinstance(node, (ast.Tuple, ast.List)) and \
                    len(node.elts) == 3 and \
                    all(isinstance(e, ast.Constant) and
                        isinstance(e.value, str)
                        for e in node.elts[:2]):
                attr = _self_attr(node.elts[2])
                if attr is not None and \
                        node.elts[0].value.upper() in (
                            "GET", "POST", "PUT", "DELETE", "PATCH"):
                    roots[f"handler:{attr}"] = ("handler", attr)
        return roots


# --- whole-program model ----------------------------------------------


class ModuleInfo:
    """One module's place in the program: dotted name, import map,
    top-level classes and functions."""

    def __init__(self, rel: str, tree: Optional[ast.AST]):
        self.rel = rel
        self.modname = rel[:-3].replace("/", ".")
        if self.modname.endswith(".__init__"):
            self.modname = self.modname[: -len(".__init__")]
        self.tree = tree
        #: local name -> (modname, symbol-or-None): `import a.b as c`
        #: -> {"c": ("a.b", None)}; `from a import X` -> {"X": ("a","X")}
        self.imports: Dict[str, Tuple[str, Optional[str]]] = {}
        #: dotted module names imported AT MODULE LEVEL (import-time
        #: executed), for the RTA602 reachability pass. Excludes
        #: TYPE_CHECKING / __main__ guarded blocks.
        self.import_time: List[Tuple[str, int]] = []
        self.classes: Dict[str, ast.ClassDef] = {}
        self.functions: Dict[str, ast.FunctionDef] = {}
        #: Names bound at PLAIN top level to a lock factory
        #: (``_lock = threading.Lock()``) — the module-global sync
        #: primitives free functions guard with. Deliberately not the
        #: recursive ``_toplevel_stmts`` walk: that descends into class
        #: bodies, and a class-attribute lock is the class's, not the
        #: module's.
        self.global_locks: Set[str] = set()
        if tree is None:
            return
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                parts = _dotted(node.value.func)
                if parts and parts[-1] in LOCK_FACTORIES:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.global_locks.add(tgt.id)
        pkg = self.modname if rel.endswith("__init__.py") else \
            self.modname.rsplit(".", 1)[0] if "." in self.modname else ""
        for node, guarded in _toplevel_stmts(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else \
                        alias.name.split(".")[0]
                    self.imports[local] = (target, None)
                    if not guarded:
                        self.import_time.append((alias.name,
                                                 node.lineno))
            elif isinstance(node, ast.ImportFrom):
                base = _resolve_from(pkg, node)
                if base is None:
                    continue
                for alias in node.names:
                    local = alias.asname or alias.name
                    self.imports[local] = (base, alias.name)
                    if not guarded:
                        self.import_time.append(
                            (f"{base}.{alias.name}", node.lineno))
                if not guarded:
                    self.import_time.append((base, node.lineno))


def _resolve_from(pkg: str, node: ast.ImportFrom) -> Optional[str]:
    """Absolute dotted base of a ``from X import ...``. ``pkg`` is the
    module's OWN package (for a package ``__init__`` that is the
    package itself): level=1 resolves against it, each extra level
    climbs one parent. None when the climb leaves the repo."""
    if node.level == 0:
        return node.module or ""
    parts = pkg.split(".") if pkg else []
    climb = node.level - 1
    if climb > len(parts):
        return None
    base = ".".join(parts[: len(parts) - climb] if climb else parts)
    if node.module:
        base = f"{base}.{node.module}" if base else node.module
    return base


def _guard_polarity(test: ast.AST) -> Optional[str]:
    """Which branch of an If does NOT execute on a bare import:
    ``"body"`` for ``if __name__ == "__main__":`` / ``if
    TYPE_CHECKING:``, ``"orelse"`` for the inverted spellings
    (``__name__ != ...``, ``not TYPE_CHECKING``), None for an
    ordinary If. The OTHER branch still runs at import — a
    ``TYPE_CHECKING: ... else: X = Any`` else-arm must stay in
    scope."""
    def is_tc_name(n: ast.AST) -> bool:
        return (isinstance(n, ast.Name) and n.id == "TYPE_CHECKING") \
            or (isinstance(n, ast.Attribute) and
                n.attr == "TYPE_CHECKING")

    if is_tc_name(test):
        return "body"
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
            and is_tc_name(test.operand):
        return "orelse"
    if isinstance(test, ast.Compare) and \
            isinstance(test.left, ast.Name) and \
            test.left.id == "__name__" and len(test.ops) == 1:
        if isinstance(test.ops[0], ast.Eq):
            return "body"
        if isinstance(test.ops[0], ast.NotEq):
            return "orelse"
    return None


def _toplevel_stmts(tree: ast.AST):
    """Yield (stmt, guarded) for every statement that EXECUTES at
    import time: module body recursively through if/try/with/for
    blocks and class bodies, never into function bodies. ``guarded``
    is True only for the branch a ``__name__ == "__main__"`` /
    ``TYPE_CHECKING`` test keeps off the bare-import path (polarity
    respected: the else-arm of a guard, and the body of an inverted
    guard, still run at import)."""
    # LIFO stack with reversed pushes = document order out, which the
    # thread-name tracking in import_hygiene relies on (the Thread
    # assignment must be seen before its .start()).
    stack: List[Tuple[ast.AST, bool]] = \
        [(s, False) for s in reversed(tree.body)]
    while stack:
        node, guarded = stack.pop()
        yield node, guarded
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.ClassDef):
            for s in reversed(node.body):
                if not isinstance(s, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    stack.append((s, guarded))
            continue
        polarity = _guard_polarity(node.test) \
            if isinstance(node, ast.If) else None
        children: List[Tuple[ast.AST, bool]] = []
        for field in ("body", "orelse", "finalbody", "handlers",
                      "cases"):
            g = guarded or polarity == field
            for child in getattr(node, field, []):
                if isinstance(child, ast.ExceptHandler):
                    for s in child.body:
                        children.append((s, g))
                elif child.__class__.__name__ == "match_case":
                    # match arms execute at import like any branch.
                    for s in child.body:
                        children.append((s, g))
                else:
                    children.append((child, g))
        stack.extend(reversed(children))


class MethodSummary:
    __slots__ = ("key", "node", "cls_key", "direct_locks", "calls",
                 "call_nodes", "blocking", "held_blocking")

    def __init__(self, key, node, cls_key):
        self.key = key          # (rel, clsname-or-None, methodname)
        self.node = node
        self.cls_key = cls_key  # (rel, clsname) or None
        #: (qualified lock id, frozenset of qualified outer held, line)
        self.direct_locks: List[Tuple[str, frozenset, int]] = []
        #: (frozenset of qualified held, target key or None, line, label)
        self.calls: List[Tuple[frozenset, Optional[tuple], int, str]] = []
        #: (target key or None, the ast.Call node) — the cross-process
        #: checkers (RTA7xx) re-examine resolved call sites with their
        #: actual argument expressions (queue-name forwarding, flag-gate
        #: classification); the lock-graph tuple above stays lean.
        self.call_nodes: List[Tuple[Optional[tuple], ast.Call]] = []
        #: (label, line) of the first direct blocking call, or None.
        self.blocking: Optional[Tuple[str, int]] = None
        #: Direct blocking calls made WITH a qualified lock held:
        #: (held, label, line). For free functions this is the only
        #: blocking-under-lock signal there is — the per-class RTA102
        #: never sees module-level code.
        self.held_blocking: List[Tuple[frozenset, str, int]] = []


class Program:
    """The built model. Construction is bounded and pure-AST; see the
    module docstring for exactly what resolves and what is a blind
    spot."""

    def __init__(self, modules: Sequence):
        # `modules` are core.Module objects (rel/tree/text); typed
        # loosely so this file keeps zero imports from core.
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_modname: Dict[str, ModuleInfo] = {}
        self._class_infos: Dict[int, _ClassInfo] = {}
        self._mods = list(modules)
        for m in self._mods:
            mi = ModuleInfo(m.rel, m.tree)
            self.modules[m.rel] = mi
            self.by_modname[mi.modname] = mi
        # Globally-unique simple-name class index (resolution fallback)
        # + node -> defining module (class_info needs the module's
        # global-lock names to walk `with <MODULE_LOCK>:` correctly).
        self._classes_by_name: Dict[str, List[Tuple[str, ast.ClassDef]]] = {}
        self._class_module: Dict[int, str] = {}
        for mi in self.modules.values():
            for cname, cnode in mi.classes.items():
                self._classes_by_name.setdefault(cname, []).append(
                    (mi.rel, cnode))
                self._class_module[id(cnode)] = mi.rel
        self._attr_types: Dict[Tuple[str, str], Dict[str, Tuple[str, str]]] = {}
        self._module_locks: Dict[str, Dict[str, str]] = {}
        self._module_state: Dict[str, "ModuleState"] = {}
        self._extra_roots: Optional[
            Dict[Tuple[str, str], Dict[str, Tuple[str, str]]]] = None
        self._summaries: Optional[Dict[tuple, MethodSummary]] = None
        self._locks_closure: Optional[Dict[tuple, Set[str]]] = None
        self._lock_via: Dict[tuple, Dict[str, tuple]] = {}
        self._blocking_closure: Optional[
            Dict[tuple, Tuple[str, int, tuple]]] = None

    # -- shared per-class analysis (guarded_state + concurrency) --

    def class_info(self, node: ast.ClassDef) -> _ClassInfo:
        """The classified+walked :class:`_ClassInfo` for this ClassDef,
        computed at most once per run regardless of how many checkers
        ask."""
        info = self._class_infos.get(id(node))
        if info is None:
            rel = self._class_module.get(id(node))
            locks = self.module_lock_names(rel) if rel else {}
            info = _ClassInfo(node, module_locks=locks)
            info.classify()
            info.walk()
            self._class_infos[id(node)] = info
        return info

    # -- class resolution --

    def resolve_class(self, rel: str,
                      name: str) -> Optional[Tuple[str, str]]:
        """(rel, classname) a simple name refers to in module ``rel``:
        import-map first, globally-unique simple name second."""
        mi = self.modules.get(rel)
        if mi is None:
            return None
        if name in mi.classes:
            return (rel, name)
        imp = mi.imports.get(name)
        if imp is not None:
            modname, symbol = imp
            target = self.by_modname.get(modname)
            if target is not None and symbol is None and \
                    name in target.classes:
                return (target.rel, name)
            if symbol is not None and target is not None and \
                    symbol in target.classes:
                return (target.rel, symbol)
        hits = self._classes_by_name.get(name, [])
        if len(hits) == 1:
            return (hits[0][0], name)
        return None

    def class_display(self, cls_key: Tuple[str, str]) -> str:
        rel, name = cls_key
        if len(self._classes_by_name.get(name, [])) > 1:
            stem = rel.rsplit("/", 1)[-1][:-3]
            return f"{stem}.{name}"
        return name

    def lock_id(self, cls_key: Tuple[str, str], attr: str) -> str:
        return f"{self.class_display(cls_key)}.{attr}"

    def lock_owner(self, lock_id: str) -> str:
        return lock_id.rsplit(".", 1)[0]

    # -- attribute types (bounded alias following) --

    def attr_types(self, cls_key: Tuple[str, str]) -> Dict[str, Tuple[str, str]]:
        """attr -> (rel, classname) for attributes whose constructed /
        annotated type resolves to a repo class."""
        cached = self._attr_types.get(cls_key)
        if cached is not None:
            return cached
        rel, cname = cls_key
        mi = self.modules.get(rel)
        node = mi.classes.get(cname) if mi else None
        out: Dict[str, Tuple[str, str]] = {}
        if node is not None:
            info = self.class_info(node)
            for meth in info.methods():
                ann: Dict[str, Tuple[str, str]] = {}
                for a in meth.args.args + meth.args.kwonlyargs:
                    t = self._annotation_class(rel, a.annotation)
                    if t is not None:
                        ann[a.arg] = t
                for stmt in ast.walk(meth):
                    if not isinstance(stmt, ast.Assign):
                        continue
                    for tgt in stmt.targets:
                        attr = _self_attr(tgt)
                        if attr is None or attr in out:
                            continue
                        t = self._rhs_class(rel, stmt.value, ann)
                        if t is not None:
                            out[attr] = t
        self._attr_types[cls_key] = out
        return out

    def _annotation_class(self, rel: str,
                          ann) -> Optional[Tuple[str, str]]:
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            name = ann.value.strip().lstrip("\"'").split("[")[0]
            name = name.split(".")[-1]
            return self.resolve_class(rel, name)
        parts = _dotted(ann)
        if parts:
            return self.resolve_class(rel, parts[-1])
        return None

    def _rhs_class(self, rel: str, value: ast.AST,
                   ann: Dict[str, Tuple[str, str]]
                   ) -> Optional[Tuple[str, str]]:
        """Type of an assignment RHS: the first constructor call of a
        resolvable repo class anywhere in the expression (covers
        ``stats or ServingStats()``), or an annotated parameter."""
        if isinstance(value, ast.Name) and value.id in ann:
            return ann[value.id]
        if isinstance(value, ast.BoolOp):
            for v in value.values:
                t = self._rhs_class(rel, v, ann)
                if t is not None:
                    return t
            return None
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                parts = _dotted(sub.func)
                if parts:
                    t = self.resolve_class(rel, parts[-1])
                    if t is not None:
                        return t
        return None

    # -- module-global locks --

    def module_lock_names(self, rel: str) -> Dict[str, str]:
        """local name -> module-qualified lock id for the module-global
        sync primitives visible in ``rel``: its own top-level
        ``NAME = threading.Lock()`` binds plus ``from x import NAME``
        of another repo module's. Qualified as ``<modname>.<NAME>`` so
        ``lock_owner`` yields the module — the cross-owner filters
        treat a module exactly like a class."""
        cached = self._module_locks.get(rel)
        if cached is not None:
            return cached
        out: Dict[str, str] = {}
        mi = self.modules.get(rel)
        if mi is not None:
            for local, (modname, symbol) in mi.imports.items():
                if symbol is None:
                    # ``import pkg.mod as m`` — m's locks are only
                    # reachable as DOTTED ``m.LOCK`` references; the
                    # dotted spelling is the map key so both walkers
                    # resolve it with one lookup.
                    target = self.by_modname.get(modname)
                    if target is not None:
                        for name in target.global_locks:
                            out[f"{local}.{name}"] = \
                                f"{target.modname}.{name}"
                    continue
                target = self.by_modname.get(modname)
                if target is not None and \
                        symbol in target.global_locks:
                    out[local] = f"{target.modname}.{symbol}"
                # ``from pkg import mod`` — a module imported as a
                # SYMBOL: its locks are dotted ``mod.LOCK`` references
                # exactly like the aliased-import case.
                sub = self.by_modname.get(f"{modname}.{symbol}"
                                          if modname else symbol)
                if sub is not None:
                    for name in sub.global_locks:
                        out[f"{local}.{name}"] = \
                            f"{sub.modname}.{name}"
            for name in mi.global_locks:
                out[name] = f"{mi.modname}.{name}"
        self._module_locks[rel] = out
        return out

    # -- module-global mutable state (free-function RTA101) --

    def module_state(self, rel: str) -> "ModuleState":
        """The module-level analog of ``_ClassInfo`` state tracking:
        names bound at top level AND rebound via ``global`` in at
        least one free function are the module's mutable state; every
        free-function access is recorded with the module-lock held
        set. Names a function assigns WITHOUT declaring ``global`` are
        that function's locals (Python scoping) and are skipped there.
        Depth-0 only — closures run later and inherit nothing."""
        cached = self._module_state.get(rel)
        if cached is not None:
            return cached
        ms = ModuleState()
        mi = self.modules.get(rel)
        if mi is None or mi.tree is None:
            self._module_state[rel] = ms
            return ms
        top_bound: Set[str] = set()
        for stmt, _guarded in _toplevel_stmts(mi.tree):
            if isinstance(stmt, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                for tgt in targets:
                    if isinstance(tgt, ast.Name):
                        top_bound.add(tgt.id)
        fn_globals: Dict[str, Set[str]] = {}
        declared: Set[str] = set()
        for fname, fnode in mi.functions.items():
            g: Set[str] = set()
            for sub in ast.walk(fnode):
                if isinstance(sub, ast.Global):
                    g.update(sub.names)
            fn_globals[fname] = g
            declared.update(g)
        locks = self.module_lock_names(rel)
        ms.candidates = (declared & top_bound) - mi.global_locks
        if ms.candidates and locks:
            for fname, fnode in mi.functions.items():
                stored: Set[str] = set()
                for sub in ast.walk(fnode):
                    if isinstance(sub, ast.Name) and \
                            isinstance(sub.ctx, (ast.Store, ast.Del)):
                        stored.add(sub.id)
                skip = (stored - fn_globals[fname]) & ms.candidates
                walker = _ModuleStateWalker(locks, ms.candidates, skip,
                                            fname, ms.accesses)
                for stmt in fnode.body:
                    walker.visit(stmt)
        self._module_state[rel] = ms
        return ms

    # -- cross-class thread roots --

    def extra_class_roots(self, cls_key: Tuple[str, str]
                          ) -> Dict[str, Tuple[str, str]]:
        """Thread roots REGISTERED FROM OUTSIDE the class:
        ``Thread(target=self.consumer.loop)`` or an executor
        ``pool.submit(self.consumer.drain)`` in an owner (or a free
        function, through a local alias) makes the method a root ON
        the consumer's class — the bus-consumer / decode-scheduler
        shape, where the object that OWNS the loop never constructs
        the thread and so ``_ClassInfo.thread_roots`` is blind to it.
        Only executor-shaped submit receivers (pool/executor/exec in
        the name), receivers whose type resolves through the bounded
        alias rules, and methods the target class actually defines,
        register. Handler classes passed to a ``*Server`` ctor
        (the socketserver shape — ``handle()`` runs per-connection
        threads) register through the same inventory."""
        if self._extra_roots is None:
            self._extra_roots = {}
            for mi in self.modules.values():
                for cname, cnode in mi.classes.items():
                    info = self.class_info(cnode)
                    atypes = self.attr_types((mi.rel, cname))
                    for m in info.methods():
                        self._collect_foreign_targets(
                            mi.rel, (mi.rel, cname), atypes,
                            self._local_types(mi.rel, (mi.rel, cname),
                                              m, atypes), m)
                for fnode in mi.functions.values():
                    self._collect_foreign_targets(
                        mi.rel, None, {},
                        self._local_types(mi.rel, None, fnode, {}),
                        fnode)
        return self._extra_roots.get(cls_key, {})

    def spawn_params(self) -> Dict[tuple, Dict[str, str]]:
        """method/function key -> {param name: kind} for SPAWNER
        helpers: functions whose body hands one of their own
        parameters to ``Thread(target=param)`` or an executor
        ``submit(param)``. A callable passed to such a parameter runs
        on another thread — the ``register_consumer`` shape, where the
        class that OWNS the loop method hands it to a different
        class's spawn helper and neither per-class walk sees a root."""
        cached = getattr(self, "_spawn_params", None)
        if cached is not None:
            return cached
        out: Dict[tuple, Dict[str, str]] = {}
        for key, s in self.summaries().items():
            params = {a.arg for a in s.node.args.args +
                      s.node.args.kwonlyargs}
            if not params:
                continue
            for node in ast.walk(s.node):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                leaf = func.attr if isinstance(func, ast.Attribute) \
                    else (func.id if isinstance(func, ast.Name) else "")
                if leaf == "Thread":
                    for kw in node.keywords:
                        if kw.arg == "target" and \
                                isinstance(kw.value, ast.Name) and \
                                kw.value.id in params:
                            out.setdefault(key, {})[kw.value.id] = \
                                "thread"
                elif leaf == "submit" and node.args and \
                        isinstance(node.args[0], ast.Name) and \
                        node.args[0].id in params:
                    owner = func.value \
                        if isinstance(func, ast.Attribute) else None
                    ownername = (_self_attr(owner) or
                                 (owner.id if isinstance(owner, ast.Name)
                                  else "")) if owner is not None else ""
                    if "pool" in ownername or "executor" in ownername \
                            or "exec" in ownername:
                        out.setdefault(key, {})[node.args[0].id] = \
                            "submit"
        self._spawn_params = out
        return out

    def _spawned_args(self, rel, cls_key, node: ast.Call, atypes,
                      local_types) -> List[Tuple[str, ast.AST]]:
        """(kind, callable expression) for arguments this call hands
        to a spawner helper's spawn parameter (``spawn_params``)."""
        target, _label = self._resolve_call(rel, cls_key, node, atypes,
                                            local_types)
        if target is None:
            return []
        spawn = self.spawn_params().get(target)
        if not spawn:
            return []
        s = self.summaries().get(target)
        if s is None:
            return []
        params = [a.arg for a in s.node.args.args]
        offset = 1 if target[1] is not None and params and \
            params[0] == "self" else 0
        out: List[Tuple[str, ast.AST]] = []
        for i, arg in enumerate(node.args):
            j = offset + i
            if j < len(params) and params[j] in spawn:
                out.append((spawn[params[j]], arg))
        for kw in node.keywords:
            if kw.arg in spawn:
                out.append((spawn[kw.arg], kw.value))
        return out

    def _collect_foreign_targets(self, rel, cls_key, atypes,
                                 local_types, fnode) -> None:
        for node in ast.walk(fnode):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            leaf = func.attr if isinstance(func, ast.Attribute) else \
                (func.id if isinstance(func, ast.Name) else "")
            # (kind, target expression) candidates this call registers.
            targets: List[Tuple[str, ast.AST]] = []
            if leaf == "Thread":
                targets.extend(
                    ("thread", kw.value) for kw in node.keywords
                    if kw.arg == "target")
            elif leaf == "submit" and node.args:
                # Executor-shaped receivers only (same vocabulary as
                # _ClassInfo.thread_roots): pool.submit(c.loop) is a
                # thread hop; app.submit(self.x.m) is an app method.
                owner = func.value \
                    if isinstance(func, ast.Attribute) else None
                ownername = (_self_attr(owner) or
                             (owner.id if isinstance(owner, ast.Name)
                              else "")) if owner is not None else ""
                if "pool" in ownername or "executor" in ownername \
                        or "exec" in ownername:
                    targets.append(("submit", node.args[0]))
            # A callable handed to ANOTHER function's spawn parameter
            # (``helper.register_consumer(self.consumer.loop)``) is a
            # root exactly like a direct Thread(target=...) here.
            targets.extend(self._spawned_args(rel, cls_key, node,
                                              atypes, local_types))
            for kind, value in targets:
                if not isinstance(value, ast.Attribute):
                    continue
                recv, meth = value.value, value.attr
                attr = _self_attr(recv)
                fk = atypes.get(attr) if attr is not None else None
                if fk is None and isinstance(recv, ast.Name):
                    fk = local_types.get(recv.id)
                if fk is None:
                    continue
                finfo = self._class_info_of(fk)
                if finfo is None or not any(m.name == meth
                                            for m in finfo.methods()):
                    continue
                self._extra_roots.setdefault(fk, {})[
                    f"{kind}:{meth}"] = (kind, meth)
            self._collect_handler_roots(rel, node, func, leaf)

    def _collect_handler_roots(self, rel, node: ast.Call, func,
                               leaf: str) -> None:
        """The socketserver shape: ``_Server((host, port), _Handler)``
        — the server ctor takes the handler CLASS and calls its
        ``handle()`` on a per-connection thread, so the handler class
        never constructs a thread and both thread-root walks are blind
        to it. Bounded: the called name must resolve to a repo class
        with a ``*Server*`` base (socketserver.ThreadingTCPServer and
        repo subclasses), the argument to a repo class that defines
        ``handle``."""
        called = self.resolve_class(rel, leaf) if leaf else None
        if called is None or not self._is_server_class(called):
            return
        candidates = list(node.args) + [
            kw.value for kw in node.keywords
            if kw.arg and "handler" in kw.arg.lower()]
        for arg in candidates:
            if not isinstance(arg, ast.Name):
                continue
            hk = self.resolve_class(rel, arg.id)
            if hk is None:
                continue
            hinfo = self._class_info_of(hk)
            if hinfo is None or not any(m.name == "handle"
                                        for m in hinfo.methods()):
                continue
            self._extra_roots.setdefault(hk, {})[
                "handler:handle"] = ("handler", "handle")

    def _is_server_class(self, cls_key: Tuple[str, str]) -> bool:
        mi = self.modules.get(cls_key[0])
        cnode = mi.classes.get(cls_key[1]) if mi else None
        if cnode is None:
            return False
        return any("Server" in part
                   for base in cnode.bases for part in _dotted(base))

    # -- method summaries + call resolution --

    def summaries(self) -> Dict[tuple, MethodSummary]:
        if self._summaries is None:
            self._summaries = {}
            # Phase 1: register EVERY method/function key first —
            # resolution during the fill phase must see the whole
            # program, not the build-order prefix.
            for mi in self.modules.values():
                for cname, cnode in mi.classes.items():
                    info = self.class_info(cnode)
                    for m in info.methods():
                        self._summaries[(mi.rel, cname, m.name)] = \
                            MethodSummary((mi.rel, cname, m.name), m,
                                          (mi.rel, cname))
                for fname, fnode in mi.functions.items():
                    self._summaries[(mi.rel, None, fname)] = \
                        MethodSummary((mi.rel, None, fname), fnode,
                                      None)
            # Phase 2: fill.
            for mi in self.modules.values():
                for cname, cnode in mi.classes.items():
                    self._build_class_summaries(mi.rel, cname, cnode)
                for fname, fnode in mi.functions.items():
                    self._build_function_summary(mi.rel, fname, fnode)
        return self._summaries

    def method(self, cls_key: Tuple[str, str],
               name: str) -> Optional[MethodSummary]:
        return self.summaries().get((cls_key[0], cls_key[1], name))

    def _build_function_summary(self, rel: str, fname: str,
                                fnode) -> None:
        """Module-level functions: no self, but module-GLOBAL locks
        (top-level ``NAME = threading.Lock()``, own or from-imported)
        qualify and their ``with NAME:`` holds track, so free-function
        acquisitions feed the cross-owner lock graph (RTA104) and the
        blocking closure (RTA105) exactly like class locks do."""
        s = self._summaries[(rel, None, fname)]
        walker = _QualifiedWalker(self, rel, None, _FREE_CONTEXT, {},
                                  s, frozenset())
        for stmt in fnode.body:
            walker.visit(stmt)

    def _build_class_summaries(self, rel: str, cname: str,
                               cnode: ast.ClassDef) -> None:
        info = self.class_info(cnode)
        cls_key = (rel, cname)
        atypes = self.attr_types(cls_key)
        for mnode in info.methods():
            s = self._summaries[(rel, cname, mnode.name)]
            extra_q = frozenset(
                self.lock_id(cls_key, h)
                for h in info.held_extra().get(mnode.name, ()))
            walker = _QualifiedWalker(self, rel, cls_key, info, atypes,
                                      s, extra_q)
            for stmt in mnode.body:
                walker.visit(stmt)

    def _class_info_of(self,
                       cls_key: Tuple[str, str]) -> Optional[_ClassInfo]:
        mi = self.modules.get(cls_key[0])
        node = mi.classes.get(cls_key[1]) if mi else None
        return self.class_info(node) if node is not None else None

    def _local_types(self, rel, cls_key, mnode, atypes):
        """One level of local alias following inside a method:
        ``s = self.stats`` / ``s = ServingStats(...)`` / annotated
        params. Flow-insensitive, last-writer-wins-free (first binding
        recorded) — bounded by design."""
        out: Dict[str, Tuple[str, str]] = {}
        if mnode is None:
            return out
        cached = getattr(mnode, "_rta_local_types", None)
        if cached is not None:
            return cached
        for a in mnode.args.args + mnode.args.kwonlyargs:
            t = self._annotation_class(rel, a.annotation)
            if t is not None:
                out[a.arg] = t
        for stmt in ast.walk(mnode):
            if not isinstance(stmt, ast.Assign) or \
                    len(stmt.targets) != 1 or \
                    not isinstance(stmt.targets[0], ast.Name):
                continue
            name = stmt.targets[0].id
            if name in out:
                continue
            v = stmt.value
            attr = _self_attr(v)
            if attr is not None and attr in atypes:
                out[name] = atypes[attr]
            elif isinstance(v, ast.Call):
                parts = _dotted(v.func)
                if parts:
                    t = self.resolve_class(rel, parts[-1])
                    if t is not None:
                        out[name] = t
        mnode._rta_local_types = out
        return out

    def _resolve_call(self, rel, cls_key, call, atypes, local_types
                      ) -> Tuple[Optional[tuple], str]:
        """(target method key or None, display label)."""
        func = call.func
        if isinstance(func, ast.Name):
            # Constructor or module-level function.
            ck = self.resolve_class(rel, func.id)
            if ck is not None:
                init = self.summaries_key(ck, "__init__")
                return init, f"{self.class_display(ck)}()"
            fk = self._module_function(rel, func.id)
            return fk, f"{func.id}()"
        if not isinstance(func, ast.Attribute):
            return None, ""
        meth = func.attr
        recv = func.value
        attr = _self_attr(recv)
        if attr is not None:
            # self.attr.m() through a typed attribute.
            fk = atypes.get(attr)
            if fk is not None:
                return (self.summaries_key(fk, meth),
                        f"self.{attr}.{meth}()")
            return None, f"self.{attr}.{meth}()"
        if isinstance(recv, ast.Name):
            if recv.id == "self":
                key = self._self_method(cls_key, meth)
                return key, f"self.{meth}()"
            fk = local_types.get(recv.id)
            if fk is not None:
                return (self.summaries_key(fk, meth),
                        f"{recv.id}.{meth}()")
            imp = self.modules[rel].imports.get(recv.id) \
                if rel in self.modules else None
            if imp is not None:
                target = self.by_modname.get(
                    imp[0] if imp[1] is None else f"{imp[0]}.{imp[1]}")
                if target is None:
                    target = self.by_modname.get(imp[0])
                if target is not None and meth in target.functions:
                    return ((target.rel, None, meth),
                            f"{recv.id}.{meth}()")
        return None, ""

    def _self_method(self, cls_key, meth) -> Optional[tuple]:
        """``self.m()`` — own class first, then resolvable repo base
        classes (single-level MRO-by-name)."""
        if cls_key is None:
            return None
        key = (cls_key[0], cls_key[1], meth)
        if key in self.summaries():
            return key
        mi = self.modules.get(cls_key[0])
        node = mi.classes.get(cls_key[1]) if mi else None
        if node is None:
            return None
        for base in node.bases:
            parts = _dotted(base)
            if not parts:
                continue
            bk = self.resolve_class(cls_key[0], parts[-1])
            if bk is not None:
                bkey = (bk[0], bk[1], meth)
                if bkey in self.summaries():
                    return bkey
        return None

    def summaries_key(self, cls_key, meth) -> Optional[tuple]:
        key = (cls_key[0], cls_key[1], meth)
        return key if key in self.summaries() else \
            self._self_method(cls_key, meth)

    def _module_function(self, rel, name) -> Optional[tuple]:
        mi = self.modules.get(rel)
        if mi is None:
            return None
        if name in mi.functions:
            return (rel, None, name)
        imp = mi.imports.get(name)
        if imp is not None and imp[1] is not None:
            target = self.by_modname.get(imp[0])
            if target is not None and imp[1] in target.functions:
                return (target.rel, None, imp[1])
        return None

    def _bus_blocking_label(self, rel, call, atypes,
                            local_types) -> Optional[str]:
        """A method call on a receiver typed to a bus/cache class is a
        broker round-trip — blocking by construction."""
        func = call.func
        if not isinstance(func, ast.Attribute):
            return None
        recv = func.value
        fk = None
        attr = _self_attr(recv)
        if attr is not None:
            fk = atypes.get(attr)
        elif isinstance(recv, ast.Name):
            fk = local_types.get(recv.id)
        if fk is None:
            return None
        if any(fk[0].startswith(m) or fk[0] == m
               for m in BUS_MODULE_MARKERS):
            return (f"bus round-trip {self.class_display(fk)}."
                    f"{func.attr}()")
        return None

    # -- transitive closures --

    def locks_closure(self) -> Dict[tuple, Set[str]]:
        """method key -> every qualified lock the method may acquire,
        directly or through resolvable callees. Monotone fixpoint,
        bounded at MAX_FIXPOINT_ROUNDS (beyond that: blind spot, not a
        hang)."""
        if self._locks_closure is not None:
            return self._locks_closure
        summ = self.summaries()
        acq: Dict[tuple, Set[str]] = {}
        via: Dict[tuple, Dict[str, tuple]] = {}
        for key, s in summ.items():
            locks = {lid for lid, _h, _l in s.direct_locks}
            acq[key] = set(locks)
            via[key] = {lid: (None, line)
                        for lid, _h, line in s.direct_locks}
        for _ in range(MAX_FIXPOINT_ROUNDS):
            changed = False
            for key, s in summ.items():
                cur = acq[key]
                for _held, target, line, _label in s.calls:
                    if target is None or target not in acq:
                        continue
                    extra = acq[target] - cur
                    if extra:
                        cur.update(extra)
                        for lid in extra:
                            via[key].setdefault(lid, (target, line))
                        changed = True
            if not changed:
                break
        self._locks_closure = acq
        self._lock_via = via
        return acq

    def lock_chain(self, key: tuple, lock_id: str) -> List[str]:
        """Human-readable frame chain from ``key`` to where ``lock_id``
        is acquired, depth-capped."""
        self.locks_closure()
        chain: List[str] = []
        cur = key
        for _ in range(MAX_CHAIN_DEPTH):
            chain.append(self.describe(cur))
            step = self._lock_via.get(cur, {}).get(lock_id)
            if step is None or step[0] is None:
                break
            cur = step[0]
        return chain

    def blocking_closure(self) -> Dict[tuple, Tuple[str, int, tuple]]:
        """method key -> (blocking label, line, via-callee-or-None):
        the nearest blocking call reachable through the call graph.
        Reverse BFS from directly-blocking methods, depth-capped at
        MAX_CHAIN_DEPTH frames."""
        if self._blocking_closure is not None:
            return self._blocking_closure
        summ = self.summaries()
        callers: Dict[tuple, List[Tuple[tuple, int]]] = {}
        for key, s in summ.items():
            for _held, target, line, _label in s.calls:
                if target is not None:
                    callers.setdefault(target, []).append((key, line))
        out: Dict[tuple, Tuple[str, int, tuple]] = {}
        frontier: List[tuple] = []
        for key, s in summ.items():
            if s.blocking is not None:
                out[key] = (s.blocking[0], s.blocking[1], None)
                frontier.append(key)
        for _ in range(MAX_CHAIN_DEPTH):
            nxt: List[tuple] = []
            for key in frontier:
                for caller, line in callers.get(key, []):
                    if caller in out:
                        continue
                    out[caller] = (out[key][0], line, key)
                    nxt.append(caller)
            if not nxt:
                break
            frontier = nxt
        self._blocking_closure = out
        return out

    def blocking_chain(self, key: tuple) -> List[str]:
        bc = self.blocking_closure()
        chain: List[str] = []
        cur = key
        for _ in range(MAX_CHAIN_DEPTH + 1):
            chain.append(self.describe(cur))
            entry = bc.get(cur)
            if entry is None or entry[2] is None:
                break
            cur = entry[2]
        return chain

    def describe(self, key: tuple) -> str:
        rel, cls, meth = key
        return f"{cls}.{meth}" if cls else meth

    # -- import-time reachability (RTA602) --

    def import_reach(self, roots: Iterable[str]) -> Dict[str, Tuple[str, int]]:
        """rel -> (importer rel, line) for every repo module executed
        at import time when the root modules load, including package
        ``__init__`` chains."""
        reach: Dict[str, Tuple[str, int]] = {}
        frontier: List[str] = []

        def note(rel: str, via: Tuple[str, int]) -> None:
            if rel not in reach:
                reach[rel] = via
                frontier.append(rel)

        for rel in roots:
            if rel in self.modules:
                note(rel, (rel, 0))
                # Importing a.b.c executes a/__init__ and a.b/__init__.
                for pkg_rel in self._pkg_inits(rel):
                    note(pkg_rel, (rel, 0))
        while frontier:
            rel = frontier.pop()
            mi = self.modules[rel]
            for modname, line in mi.import_time:
                target = self._nearest_module(modname)
                if target is None:
                    continue
                note(target.rel, (rel, line))
                for pkg_rel in self._pkg_inits(target.rel):
                    note(pkg_rel, (rel, line))
        return reach

    def _pkg_inits(self, rel: str) -> List[str]:
        out = []
        parts = rel.split("/")[:-1]
        for i in range(1, len(parts) + 1):
            cand = "/".join(parts[:i]) + "/__init__.py"
            if cand in self.modules and cand != rel:
                out.append(cand)
        return out

    def _nearest_module(self, modname: str) -> Optional[ModuleInfo]:
        """``a.b.symbol`` -> the repo module a.b (or a.b.symbol when
        that is itself a module)."""
        while modname:
            mi = self.by_modname.get(modname)
            if mi is not None:
                return mi
            if "." not in modname:
                return None
            modname = modname.rsplit(".", 1)[0]
        return None


class ModuleState:
    """Module-global mutable names + free-function accesses with the
    module-lock held set — :meth:`Program.module_state`."""

    __slots__ = ("candidates", "accesses")

    def __init__(self) -> None:
        self.candidates: Set[str] = set()
        #: (name, held qualified lock ids, function, line, is_write)
        self.accesses: List[Tuple[str, frozenset, str, int, bool]] = []


class _ModuleStateWalker(ast.NodeVisitor):
    """Depth-0 walk of one free function tracking module-lock holds
    (bare ``with _LOCK:`` and dotted ``with mod._LOCK:`` spellings)
    and recording accesses to the module's mutable globals."""

    def __init__(self, locks: Dict[str, str], candidates: Set[str],
                 skip: Set[str], func: str, out: list):
        self.locks = locks
        self.candidates = candidates
        self.skip = skip
        self.func = func
        self.out = out
        self.held: Tuple[str, ...] = ()

    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return self.locks.get(expr.id)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            return self.locks.get(f"{expr.value.id}.{expr.attr}")
        return None

    def visit_With(self, node: ast.With) -> None:
        entered = []
        for item in node.items:
            qid = self._lock_of(item.context_expr)
            if qid is not None:
                entered.append(qid)
            else:
                self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        prior = self.held
        self.held = tuple(self.held) + tuple(entered)
        for stmt in node.body:
            self.visit(stmt)
        self.held = prior

    def visit_FunctionDef(self, node) -> None:
        pass  # closures run later, inherit nothing — out of scope

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def _record(self, name: str, line: int, is_write: bool) -> None:
        self.out.append((name, frozenset(self.held), self.func, line,
                         is_write))

    def visit_Name(self, node: ast.Name) -> None:
        if node.id in self.candidates and node.id not in self.skip:
            self._record(node.id, node.lineno,
                         isinstance(node.ctx, (ast.Store, ast.Del)))

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, (ast.Store, ast.Del)) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in self.candidates and \
                node.value.id not in self.skip:
            self._record(node.value.id, node.lineno, True)
            self.visit(node.slice)
            return
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # Container-mutator call on a global is a WRITE of it.
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in MUTATORS and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in self.candidates and \
                node.func.value.id not in self.skip:
            self._record(node.func.value.id, node.lineno, True)
        self.generic_visit(node)


class _QualifiedWalker(ast.NodeVisitor):
    """Walks one method filling its :class:`MethodSummary` with
    CLASS-QUALIFIED lock ids: own locks (``with self._cond:``) and
    foreign ones taken through a typed attribute (``with
    self.stats._lock:``) both enter the held set, so cross-class
    ordering edges exist in BOTH directions. ``extra_q`` is the
    caller-holds fixpoint (private method whose every intra-class call
    site holds L), applied at depth 0 only — closures run later and
    inherit nothing."""

    def __init__(self, program: "Program", rel: str, cls_key, info,
                 atypes, summary: MethodSummary, extra_q: frozenset):
        self.program = program
        self.rel = rel
        self.cls_key = cls_key
        self.info = info
        self.atypes = atypes
        self.summary = summary
        self.extra_q = extra_q
        self.held: Tuple[str, ...] = ()
        self.depth = 0
        self._local_types = program._local_types(
            rel, cls_key, summary.node, atypes)
        self._module_locks = program.module_lock_names(rel)

    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and attr in self.info.lock_attrs:
            return self.program.lock_id(self.cls_key, attr)
        if isinstance(expr, ast.Name):
            # ``with _LOCK:`` — a module-global primitive (methods and
            # free functions alike reach them by bare name).
            return self._module_locks.get(expr.id)
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name):
                # ``with state._LOCK:`` — a module-global primitive
                # reached through its module (import alias or
                # from-imported module); same qualified id as the bare
                # spelling, so the lock unifies across call styles.
                qid = self._module_locks.get(
                    f"{expr.value.id}.{expr.attr}")
                if qid is not None:
                    return qid
            owner = _self_attr(expr.value)
            fk = self.atypes.get(owner) if owner is not None else None
            if fk is None and isinstance(expr.value, ast.Name):
                fk = self._local_types.get(expr.value.id)
            if fk is not None:
                finfo = self.program._class_info_of(fk)
                if finfo is not None and expr.attr in finfo.lock_attrs:
                    return self.program.lock_id(fk, expr.attr)
        return None

    def _effective(self) -> frozenset:
        held = frozenset(self.held)
        return held if self.depth > 0 else held | self.extra_q

    def visit_With(self, node: ast.With) -> None:
        entered = []
        for item in node.items:
            qid = self._lock_of(item.context_expr)
            if qid is not None:
                entered.append(qid)
                self.summary.direct_locks.append(
                    (qid, self._effective(),
                     item.context_expr.lineno))
            else:
                self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        prior = self.held
        self.held = tuple(self.held) + tuple(entered)
        for stmt in node.body:
            self.visit(stmt)
        self.held = prior

    def _enter_nested(self, node) -> None:
        prior, self.held = self.held, ()
        self.depth += 1
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.depth -= 1
        self.held = prior

    def visit_FunctionDef(self, node):
        self._enter_nested(node)

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        target, label = self.program._resolve_call(
            self.rel, self.cls_key, node, self.atypes,
            self._local_types)
        self.summary.calls.append(
            (self._effective(), target, node.lineno, label))
        self.summary.call_nodes.append((target, node))
        blabel = _blocking_label(self.info, node)
        if blabel is None:
            blabel = self.program._bus_blocking_label(
                self.rel, node, self.atypes, self._local_types)
        if blabel is not None:
            if self.summary.blocking is None:
                self.summary.blocking = (blabel, node.lineno)
            held = self._effective()
            if held:
                self.summary.held_blocking.append(
                    (held, blabel, node.lineno))
        self.generic_visit(node)


class _FreeContext:
    """Empty class context for module-level functions: the blocking
    predicate needs lock/thread/atomic attr sets to special-case
    ``self.X.wait()`` etc.; free functions have none."""

    lock_attrs: Set[str] = frozenset()
    atomic_attrs: Set[str] = frozenset()
    thread_attrs: Set[str] = frozenset()


_FREE_CONTEXT = _FreeContext()


def _blocking_label(cls, call: ast.Call) -> Optional[str]:
    """The RTA102 blocking predicate, shared by guarded_state (direct,
    intra-method) and the whole-program blocking closure."""
    func = call.func
    if isinstance(func, ast.Name):
        return "open()" if func.id == "open" else None
    if not isinstance(func, ast.Attribute):
        return None
    parts = _dotted(func)
    root, leaf = parts[0], parts[-1]
    if root in BLOCKING_MODULES:
        return ".".join(parts) + "()"
    if root == "time" and leaf == "sleep":
        return "time.sleep()"
    if root == "os" and leaf == "system":
        return "os.system()"
    if root == "shutil" and leaf in ("rmtree", "copytree"):
        return f"shutil.{leaf}()"
    if leaf == "sleep":
        return ".".join(parts) + "()"
    owner = _self_attr(func.value)
    if leaf == "wait":
        # Condition/Lock .wait releases the lock — the idiom, not a
        # bug. Applies to a collaborator's condition too (`with
        # self.owner._cond: self.owner._cond.wait()` — the foreign
        # token that entered the held set). A wait on anything else
        # (Event, future) blocks with the lock held.
        if owner in cls.lock_attrs or \
                _foreign_lock_token(func.value) is not None:
            return None
        return ".".join(parts) + "()"
    if leaf == "join" and owner is not None and \
            owner in cls.thread_attrs:
        return f"self.{owner}.join()"
    if leaf == "result":
        return ".".join(parts) + "()"
    if leaf in ("get", "put") and owner in cls.atomic_attrs:
        return f"self.{owner}.{leaf}()"
    return None
