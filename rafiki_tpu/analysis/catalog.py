"""The checker catalog, machine-readable: one entry per code with what
it flags, the historical bug it encodes, and the fix hint.

``python -m rafiki_tpu.analysis --explain RTA104`` prints an entry so
a builder staring at a red gate can self-serve without opening
docs/analysis.md (which carries the same catalog as prose — that file
is the reviewed narrative, this dict is the CLI's source).
"""

from __future__ import annotations

from typing import Dict

CATALOG: Dict[str, Dict[str, str]] = {
    "RTA000": {
        "title": "unparseable file",
        "flags": "A file under rafiki_tpu/ the suite cannot ast.parse.",
        "bug": "A syntax error would otherwise silently shrink the "
               "analyzed surface — every checker would just skip the "
               "file.",
        "hint": "Fix the syntax error; the finding carries the parser "
                "message.",
    },
    "RTA001": {
        "title": "waiver without a reason",
        "flags": "`# rta: disable=CODE` with no reason text.",
        "bug": "A waiver is a reviewed decision; a bare disable is an "
               "escape hatch. Not waivable, by design.",
        "hint": "Say WHY the invariant doesn't apply, on the same "
                "comment.",
    },
    "RTA002": {
        "title": "baseline entry without a reviewed reason",
        "flags": "A baseline.json entry whose reason is empty or still "
                 "the UNREVIEWED placeholder.",
        "bug": "`--update-baseline` must never be silently green; the "
               "placeholder keeps failing until a human writes the "
               "real reason.",
        "hint": "Replace the placeholder with why the finding is "
                "accepted.",
    },
    "RTA003": {
        "title": "stale waiver",
        "flags": "A reasoned `# rta: disable=CODE` comment whose "
                 "finding no longer fires (full runs; under "
                 "--checker scoping only codes a ran checker covers).",
        "bug": "A dead disable rots silently and pre-waives the NEXT "
               "regression on that line — found live in r16: a "
               "second RTA301 waiver for a label whose one "
               "per-module finding was already anchored (and waived) "
               "elsewhere. Not waivable, by design.",
        "hint": "Delete the comment (the defect was fixed), or fix "
                "the code list if it was a typo.",
    },
    "RTA101": {
        "title": "guarded attribute accessed without its lock",
        "flags": "A class attribute accessed under `with self._lock:` "
                 "somewhere, but read/written lock-free elsewhere "
                 "(outside __init__).",
        "bug": "The ParamStore write-behind row-before-file race (r6): "
               "cross-thread invariants held 'by convention' rot.",
        "hint": "Wrap the access in the guarding lock, or waive with "
                "why the race is benign.",
    },
    "RTA102": {
        "title": "blocking call under a lock (direct)",
        "flags": "sleep/subprocess/socket/open/join/result/queue-op "
                 "called IN the method while a lock is held.",
        "bug": "One time.sleep under the batcher's admission lock "
               "stalls every concurrent client for the duration.",
        "hint": "Snapshot state under the lock, do the slow work after "
                "release. The call-chain form is RTA105.",
    },
    "RTA103": {
        "title": "intra-class lock-order cycle",
        "flags": "Method A takes lock1→lock2, method B takes "
                 "lock2→lock1, within one class (incl. a self-cycle "
                 "on a non-reentrant Lock).",
        "bug": "The two-lock deadlock this class of code grows by "
               "accretion; the cross-class form is RTA104.",
        "hint": "Pick ONE acquisition order and restructure the other "
                "path.",
    },
    "RTA104": {
        "title": "cross-class lock-order cycle (interprocedural)",
        "flags": "Two classes' locks acquired in opposite orders on "
                 "two program paths — followed through the repo-wide "
                 "call graph, across modules, any number of frames "
                 "deep (bounded).",
        "bug": "The r14 breaker reset and r12 promote double-alloc "
               "were cross-OBJECT races invisible to per-class "
               "analysis; this is the deadlock-shaped sibling.",
        "hint": "Pick one global order for the two classes (document "
                "it), or hand off through a queue so one side never "
                "holds its lock into the other.",
    },
    "RTA105": {
        "title": "blocking reached through the call graph under a lock",
        "flags": "A method holds a lock while calling a chain that — "
                 "frames later, possibly in another module — sleeps, "
                 "does disk/socket I/O, or a bus round-trip.",
        "bug": "The r12 promote path blocks under the node-wide "
               "promote lock ACROSS a registration wait (deliberate, "
               "waived) — review had to find every accidental sibling "
               "by hand until this code existed.",
        "hint": "Release the lock before the slow call (snapshot "
                "under the lock, act after), or waive with why the "
                "stall is acceptable; the finding prints the frame "
                "chain.",
    },
    "RTA106": {
        "title": "cross-thread-root unguarded shared state",
        "flags": "An attribute written from one thread root "
                 "(Thread target / executor submit / HTTP handler) "
                 "and accessed from another, with NO lock anywhere on "
                 "that attribute.",
        "bug": "The r14 circuit-breaker class: state shared between "
               "the persist thread and the trial loop with nothing "
               "enforcing the ordering either side assumed.",
        "hint": "Guard both sides with one lock or hand over through "
                "a Queue/Event; waive only with the reason the race "
                "is benign (monotonic flag, GIL-atomic scalar).",
    },
    "RTA201": {
        "title": "thread neither daemonized nor joined",
        "flags": "threading.Thread(...) without daemon=True and "
                 "without a .join() on any stop/close/drain path.",
        "bug": "The _PersistStage/batcher/write-behind pattern "
               "(r6-r9): a non-daemon, never-joined thread wedges "
               "interpreter shutdown.",
        "hint": "Pass daemon=True, or join from stop()/close()/"
                "drain().",
    },
    "RTA202": {
        "title": "executor never shut down",
        "flags": "A concurrent.futures executor bound to self.X with "
                 "no self.X.shutdown(...) in the class.",
        "bug": "Same lifecycle class as RTA201, executor flavor.",
        "hint": "Add shutdown(wait=True) to the close/stop path.",
    },
    "RTA301": {
        "title": "dynamic metric label without .remove()",
        "flags": "A series sample with a non-literal label value and "
                 "no matching .remove(...) in the module.",
        "bug": "The r7 leak: per-trial/per-instance series lived "
               "forever in the process registry.",
        "hint": "Call <metric>.remove(label=value) from the owner's "
                "stop/close/trial-end path, or waive with the bounded "
                "label vocabulary.",
    },
    "RTA401": {
        "title": "cache-resident value donated",
        "flags": "A value that came from a staging/residency cache "
                 "passed at a donate_argnums position (taint flows "
                 "through helper returns).",
        "bug": "The r9 staged-arrays hazard: XLA frees the cached "
               "buffer under every later trial.",
        "hint": "Donate only per-call state (train/optimizer state), "
                "never cache-resident arrays.",
    },
    "RTA402": {
        "title": "use after donate",
        "flags": "A name passed at a donated position read again with "
                 "no rebind in between.",
        "bug": "Reading a donated array errors at runtime — on TPU "
               "only, i.e. never in CPU CI.",
        "hint": "Rebind the result (x, ... = f(x, ...)) or pass a "
                "copy.",
    },
    "RTA501": {
        "title": "metric name off-contract",
        "flags": "A registered name not matching "
                 "rafiki_tpu_<subsystem>_<name>_<unit>.",
        "bug": "One typo'd name forks the namespace forever (r7).",
        "hint": "Fix the name, or extend the vocabulary in "
                "checkers/drift.py deliberately.",
    },
    "RTA502": {
        "title": "dashboard references unregistered metric",
        "flags": "A rafiki_tpu_* token in a Grafana JSON no code "
                 "registers.",
        "bug": "A renamed series silently blanks a panel (r8).",
        "hint": "Update the dashboard (or restore the name).",
    },
    "RTA503": {
        "title": "undocumented NodeConfig knob",
        "flags": "A NodeConfig env var missing from docs/ops.md's "
                 "knob table.",
        "bug": "The r9 audit found three generations of undocumented "
               "knobs.",
        "hint": "Add the docs/ops.md row.",
    },
    "RTA504": {
        "title": "ad-hoc env knob",
        "flags": "A RAFIKI_TPU_* literal read anywhere that is not a "
                 "NodeConfig field or injected identity var.",
        "bug": "Ad-hoc os.environ knobs bypass validation, precedence "
               "and the docs gate — how the r9 audit's three "
               "undocumented generations happened.",
        "hint": "Promote to a NodeConfig field (validation + "
                "apply_env + ops.md row), or baseline with why it "
                "must stay env-only.",
    },
    "RTA505": {
        "title": "knob read by workers but not exported",
        "flags": "A NodeConfig knob read at worker construction that "
                 "apply_env() never exports.",
        "bug": "Spawned children resolve different values than the "
               "node validated.",
        "hint": "Export it in apply_env() like the other tunables.",
    },
    "RTA506": {
        "title": "SLO plane references unregistered metric",
        "flags": "A metric name in the SLO consumed-series vocabulary "
                 "(observe/slo.py, admin/slo_engine.py) or in a "
                 "docs/slo/ rules file that no code path registers.",
        "bug": "A renamed source series silently blanks every "
               "objective that reads it — no data means no burn, "
               "which reads as 'SLO healthy' during an outage (r19; "
               "the RTA502 class, pointed at the judgment layer).",
        "hint": "Fix the consumed-series name / rules file (or "
                "restore the registered name).",
    },
    "RTA601": {
        "title": "side effect at import time",
        "flags": "A thread built/started, socket/server bound, "
                 "process spawned, or environment variable read by "
                 "module-level (or class-body) code.",
        "bug": "Every subprocess service runner re-executes module "
               "import effects in ITS process; the NODE_LEASE "
               "class-attribute read froze its value at first import, "
               "BEFORE apply_env could export the validated one "
               "(fixed r15).",
        "hint": "Move the effect into the function/constructor that "
                "needs it; env belongs in NodeConfig or a "
                "construction-time read.",
    },
    "RTA602": {
        "title": "eager jax import on the bus/broker path",
        "flags": "A module-level jax/jaxlib/flax/optax import in any "
                 "module import-time-reachable from rafiki_tpu/bus/.",
        "bug": "PR 2 made observe/__init__ lazy-load the jax "
               "profiling symbols precisely so brokers never pay a "
               "jax import (seconds + a device runtime they must not "
               "touch); nothing enforced the discipline until now.",
        "hint": "Import inside the function that needs it (the "
                "observe/__init__ pattern), or break the module edge "
                "from the bus path; the finding prints the import "
                "chain.",
    },
    "RTA701": {
        "title": "bus queue-flow drift (orphan producer / dead "
                 "consumer)",
        "flags": "A queue-name family (the literal, or an f-string's "
                 "literal prefix, through the first ':') pushed with "
                 "no in-tree popper, or popped with no in-tree "
                 "pusher; and a control-frame op token (__drain__ "
                 "style) produced without a dispatcher or vice "
                 "versa. Names forwarded through a helper's `queue` "
                 "parameter resolve through the call graph to the "
                 "real producer/consumer.",
        "bug": "The bus is stringly-typed: renaming the worker input "
               "queue on ONE side (cache push vs worker pop) "
               "deadlocks serving with every unit test green — the "
               "exact defect class the continuous-batching reply "
               "queues (`r:`) and advisor RPC queues (`adv:`) ship "
               "more of every PR.",
        "hint": "Spell both sides from one shared helper/constant; "
                "fully dynamic names (empty f-string prefix) are "
                "exempt by design — prefer a literal family prefix "
                "so the checker can see the seam.",
    },
    "RTA702": {
        "title": "HTTP route drift (caller vs served route table)",
        "flags": "An in-tree HTTP caller (client SDK `_call`, "
                 "autoscaler/SLO `fetch` scrapes, peer "
                 "urlopen/Request probes, session uploads, dashboard "
                 "`api(...)`) whose method+path matches no served "
                 "route tuple; or a served route no in-tree caller "
                 "ever hits (waivable for operator-only surfaces). "
                 "Dynamic path segments are wildcards on both sides.",
        "bug": "The predictor admin split moved `/services/<id>/...` "
               "handlers between apps more than once; a typo'd "
               "client path 404s only at runtime, and a dead route "
               "is untested attack surface that drifts silently.",
        "hint": "Fix the caller's spelling or register the route; "
                "for deliberately caller-less routes (health/debug "
                "surfaces) waive at the route tuple with the reason.",
    },
    "RTA703": {
        "title": "feature-flag off-path side effect",
        "flags": "For a declared default-off flag (flow.FLAG_REGISTRY"
                 "; seeded with RAFIKI_TPU_CLUSTER_FABRIC): a thread "
                 "spawn, metric-series registration, bus subscription "
                 "loop, or socket open reachable from import or "
                 "construction without passing the flag gate — an "
                 "ungated import-time effect in a flag-owned module, "
                 "an ungated constructor call of a flag-owned class, "
                 "an effect in an unprotected flag-owned function, "
                 "or a flag-owned metric series registered ungated.",
        "bug": "Disabled-means-free is a hard invariant (r11): a "
               "scrape with the fabric flag off must show ZERO "
               "fabric series and spawn zero fabric threads; one "
               "ungated NodeRegistry construction silently puts the "
               "whole fleet's off-path on the fabric heartbeat.",
        "hint": "Gate the effect (or every call site of its "
                "function) with the flag; new default-off subsystems "
                "must add their entry to flow.FLAG_REGISTRY.",
    },
}


def explain(code: str) -> str:
    """The --explain rendering for one code (KeyError on unknown —
    the CLI validates first)."""
    e = CATALOG[code]
    return (f"{code} — {e['title']}\n\n"
            f"  flags : {e['flags']}\n"
            f"  bug   : {e['bug']}\n"
            f"  fix   : {e['hint']}\n")
