"""Repo-native static analysis: machine-checked concurrency,
resource-lifecycle and drift invariants as tier-1 gates.

Every checker here encodes a bug class this reproduction actually
shipped and found by hand:

- **RTA1xx guarded-state** — the ParamStore write-behind
  row-before-file race (r6) was a cross-thread invariant nobody's eyes
  caught until a cross-process reader crashed. The checker infers each
  class's lock-guarded attribute set from ``with self._lock:`` bodies
  and flags accesses outside any guarding lock, blocking calls made
  while holding a lock, and lock-order cycles.
- **RTA2xx thread-lifecycle** — the ``_PersistStage``/batcher/
  write-behind pattern: every ``threading.Thread`` must be daemonized
  or joined on some stop/close/drain path, every executor shut down.
- **RTA3xx series-lifecycle** — the r7 leaked per-trial/per-instance
  metric series: dynamically-labeled series need a matching
  ``.remove(...)`` in the same module.
- **RTA4xx donation/aliasing** — the r9 staged-arrays hazard: values
  that escape into caches must never be passed at ``donate_argnums``
  positions, and a donated name must not be read after the call.
- **RTA5xx drift** — the former ``scripts/check_metrics_names.py``
  and ``scripts/check_knob_docs.py``, folded in and extended: metric
  naming, dashboard references, knob documentation, and every
  ``RAFIKI_TPU_*`` env literal read anywhere must be a NodeConfig
  field with ``apply_env`` parity.

Stdlib-only (``ast``; no jax import — the suite runs in any
environment that can run pytest). Entry points:

    python -m rafiki_tpu.analysis [--changed] [--json] [--update-baseline]

and programmatically :func:`run_suite`. Pre-existing findings are
frozen in ``baseline.json`` next to this package (each with a reason);
CI enforces **zero new findings**. One-off accepted findings are
waived inline: ``# rta: disable=RTA101 <reason>`` (reason required).

See ``docs/analysis.md`` for the checker catalog, the historical bug
behind each code, and the waiver/baseline policy.
"""

from .core import (  # noqa: F401
    Checker,
    Finding,
    RepoContext,
    all_checkers,
    baseline_path,
    load_baseline,
    register,
    run_suite,
)

__all__ = ["Checker", "Finding", "RepoContext", "all_checkers",
           "baseline_path", "load_baseline", "register", "run_suite"]
