"""Service entrypoints: env contract → running worker object.

Parity: SURVEY.md §3.1 — upstream's worker image has one entrypoint that
reads ``SERVICE_TYPE`` and friends from the container env and starts the
right loop. ``build_service`` is that entrypoint as a function; the
``ProcessContainerManager`` wraps it in ``python -m
rafiki_tpu.container.services`` with the env vars set, while the
``ThreadContainerManager`` calls it in-process against shared stores.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

from ..bus import BaseBus, connect
from ..constants import EnvVars, ServiceType
from ..parallel.chips import ChipGroup
from ..store import MetaStore, ParamStore


@dataclass
class SystemContext:
    """The shared substrate every service programs against."""

    meta: MetaStore
    params: ParamStore
    bus: BaseBus

    @staticmethod
    def from_env(env: Dict[str, str]) -> "SystemContext":
        return SystemContext(
            meta=MetaStore(env[EnvVars.META_URI]),
            params=ParamStore(env[EnvVars.PARAMS_DIR]),
            bus=connect(env.get(EnvVars.BUS_URI, "")))


def build_service(env: Dict[str, str], ctx: Optional[SystemContext] = None,
                  ) -> Any:
    """Construct (not start) the worker object for a service env."""
    ctx = ctx or SystemContext.from_env(env)
    service_type = env[EnvVars.SERVICE_TYPE]
    service_id = env[EnvVars.SERVICE_ID]
    chips = (ChipGroup.from_env(env[EnvVars.CHIPS])
             if env.get(EnvVars.CHIPS) else None)
    service = _build(service_type, service_id, env, ctx, chips)
    # Thread-mode log capture: the worker binds its own thread to this
    # file at run() start (utils/service_logs; dashboard log view).
    if env.get(EnvVars.LOG_DIR):
        from ..utils.service_logs import service_log_path

        service.log_path = service_log_path(env[EnvVars.LOG_DIR],
                                            service_id)
    return service


def _build(service_type: str, service_id: str, env: Dict[str, str],
           ctx: SystemContext, chips: Optional[ChipGroup]) -> Any:
    if service_type == ServiceType.TRAIN:
        from ..worker.train import TrainWorker

        return TrainWorker(service_id, env[EnvVars.SUB_TRAIN_JOB_ID],
                           ctx.meta, ctx.params, ctx.bus, chips=chips)
    if service_type == ServiceType.ADVISOR:
        return _build_advisor_service(service_id,
                                      env[EnvVars.SUB_TRAIN_JOB_ID], ctx,
                                      env)
    if service_type == ServiceType.INFERENCE:
        from ..worker.inference import InferenceWorker

        return InferenceWorker(service_id, env[EnvVars.INFERENCE_JOB_ID],
                               env[EnvVars.TRIAL_ID], ctx.meta, ctx.params,
                               ctx.bus, chips=chips)
    if service_type == ServiceType.PREDICT:
        from ..predictor.app import PredictorService

        return PredictorService(service_id, env[EnvVars.INFERENCE_JOB_ID],
                                ctx.meta, ctx.bus,
                                port=int(env.get("RAFIKI_TPU_PORT", "0")))
    raise ValueError(f"unknown service type: {service_type!r}")


def _build_advisor_service(service_id: str, sub_id: str,
                           ctx: SystemContext,
                           env: Optional[Dict[str, str]] = None) -> Any:
    """AdvisorWorker wired to the sub-train-job's model + budget."""
    from ..advisor import make_advisor
    from ..advisor.worker import AdvisorWorker
    from ..constants import BudgetOption
    from ..utils.model_loader import load_model_class

    sub = ctx.meta.get_sub_train_job(sub_id)
    job = ctx.meta.get_train_job(sub["train_job_id"])
    model_row = ctx.meta.get_model(sub["model_id"])
    model_class = load_model_class(model_row["model_class"],
                                   model_row.get("model_source"))
    total = job["budget"].get(BudgetOption.MODEL_TRIAL_COUNT)
    advisor = make_advisor(model_class.get_knob_config(),
                           advisor_type=sub.get("advisor_type"),
                           total_trials=total)
    import os

    from ..config import _parse_bool

    # The SERVICE env dict is the contract every tunable here rides
    # (docker children never inherit the admin's os.environ); the
    # process env is the fallback for direct construction.
    raw = (env or {}).get("RAFIKI_TPU_ADVISOR_PREFETCH") \
        or os.environ.get("RAFIKI_TPU_ADVISOR_PREFETCH", "1")
    if _parse_bool(raw):
        # The bus-hosted advisor serves MANY workers, whose proposals
        # already race feedback — prefetching the next proposal (so a
        # GP refit never blocks a requesting TrainWorker's chip) adds
        # no staleness that fan-out hasn't already introduced.
        # RAFIKI_TPU_ADVISOR_PREFETCH=0 opts out.
        from ..advisor import PrefetchAdvisor

        advisor = PrefetchAdvisor(advisor)
    worker = AdvisorWorker(advisor, ctx.bus, sub_id)
    worker.service_id = service_id
    return worker


def main() -> None:
    """Subprocess entrypoint: build from os.environ, run in the
    foreground until the process is signalled."""
    import logging
    import os
    import signal

    from ..jaxenv import ensure_platform

    # Honor the platform the parent node resolved (or JAX_PLATFORMS=cpu)
    # before any backend touch — the site hook's latch would otherwise
    # send this child to the accelerator even when it is unreachable.
    ensure_platform()
    # Subprocess/docker mode: the whole process IS the service, so its
    # log file captures every thread via a root FileHandler (the
    # thread-bound handler is for resident-runner mode).
    env = dict(os.environ)
    if env.get(EnvVars.LOG_DIR):
        from ..observe import trace
        from ..utils.service_logs import attach_process_log, \
            service_log_path

        attach_process_log(service_log_path(
            env[EnvVars.LOG_DIR], env[EnvVars.SERVICE_ID]))
        # Span sink: the SHARED <log_dir>/spans.jsonl (O_APPEND lines
        # interleave safely with the admin process and sibling
        # services), so Admin.get_trace sees this worker's spans.
        trace.configure(env[EnvVars.LOG_DIR])
        # Workload-recorder sink (dormant unless the env gate is on):
        # a subprocess predictor's arrival records land in the same
        # shared log dir the capacity engine replays from.
        from ..observe import workload as _workload

        _workload.configure(env[EnvVars.LOG_DIR])
        # The root FileHandler above now owns the file; dropping the
        # env var stops build_service from ALSO binding the thread-
        # routing handler to it (every record would land twice).
        env.pop(EnvVars.LOG_DIR)
    # Worker runners (train/inference) have no HTTP surface of their
    # own; RAFIKI_TPU_METRICS_PORT starts a metrics-only JsonHttpServer
    # so every subprocess/docker service is scrapable. Port 0 picks a
    # free port (logged); the resident runner doesn't need this — the
    # admin frontend already exposes the shared process registry.
    metrics_port = env.get("RAFIKI_TPU_METRICS_PORT")
    if metrics_port is not None and metrics_port != "":
        from ..observe import metrics as obs_metrics

        if not obs_metrics.metrics_enabled():
            # RAFIKI_TPU_METRICS=0 suppresses the /metrics route, so a
            # server here would answer 404 to the very scrape the port
            # was configured for — refuse loudly instead.
            logging.getLogger(__name__).warning(
                "RAFIKI_TPU_METRICS_PORT=%s ignored: RAFIKI_TPU_METRICS "
                "disables metrics for this process", metrics_port)
        else:
            try:
                server = obs_metrics.serve_metrics(
                    port=int(metrics_port),
                    name=f"metrics-{env.get(EnvVars.SERVICE_ID, '?')[:8]}")
                logging.getLogger(__name__).info(
                    "metrics server on port %d", server.port)
                # Advertise the BOUND address (port 0 picks one) so
                # this worker's bus registration can carry it and the
                # admin's SLO engine can scrape worker-owned families
                # (serving_bin_device_seconds lives in THIS process's
                # registry, invisible to the frontend's exposition —
                # docs/observability.md). gethostname covers docker
                # networks; loopback covers same-host subprocesses.
                import socket

                try:
                    host = socket.gethostbyname(socket.gethostname())
                except OSError:
                    host = "127.0.0.1"
                os.environ[EnvVars.METRICS_ADDR] = \
                    f"{host}:{server.port}"
            except (OSError, ValueError):
                # A node-wide fixed port collides when several services
                # share one host (or the value is garbage): metrics are
                # a convenience and must degrade to "none", never kill
                # the worker before it starts.
                logging.getLogger(__name__).warning(
                    "metrics server on port %s unavailable; continuing "
                    "without", metrics_port, exc_info=True)
    service = build_service(env)
    stop = getattr(service, "stop", None)
    if stop is not None:
        signal.signal(signal.SIGTERM, lambda *_: stop())
    service.run()


if __name__ == "__main__":
    main()
