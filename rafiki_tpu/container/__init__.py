"""Container runtime abstraction: how services actually run.

Parity: SURVEY.md §2 "Container manager" — upstream abstracts docker swarm
behind ``ContainerManager.create_service(image, env, replicas, gpus)``.
Here the contract is the same but the default runtime is the
**resident runner** (SURVEY.md §7 hard-parts): services are threads inside
one process that owns all TPU chips, each bound to its chip group via a
thread-local — the idiomatic TPU replacement for per-container
``CUDA_VISIBLE_DEVICES`` isolation. A subprocess runtime
(``ProcessContainerManager``) gives OS-level isolation for multi-host
deployments; a docker/K8s manager can implement the same interface
unchanged.
"""

from .manager import (ContainerManager, DockerContainerManager,
                      ProcessContainerManager, ThreadContainerManager)
from .services import SystemContext, build_service

__all__ = ["ContainerManager", "ThreadContainerManager",
           "ProcessContainerManager", "DockerContainerManager",
           "SystemContext", "build_service"]
