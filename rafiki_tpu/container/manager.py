"""Container managers: thread (resident runner) and subprocess runtimes.

Parity: SURVEY.md §2 "Container manager". The interface mirrors upstream's
``create_service/destroy_service`` contract so the Admin/ServicesManager
is runtime-agnostic; a DockerSwarm/K8s implementation slots in behind the
same three methods.
"""

from __future__ import annotations

import abc
import logging
import os
import subprocess
import sys
import threading
from typing import Any, Dict, Optional

from .services import SystemContext, build_service

_log = logging.getLogger(__name__)


class ContainerManager(abc.ABC):
    # Whether two services this manager launches may co-own a chip
    # (time-sliced tenancy). Only resident-runner threads can: they
    # share one process and one jax backend, so their dispatches
    # interleave on the device queue. Separate processes (subprocess /
    # docker modes) cannot both open a TPU chip — sharing stays off.
    supports_chip_sharing = False

    @abc.abstractmethod
    def create_service(self, service_id: str, environ: Dict[str, str]) -> str:
        """Launch a service; returns a runtime container id."""

    @abc.abstractmethod
    def destroy_service(self, container_id: str) -> None:
        pass

    def kill_service(self, container_id: str) -> None:
        """HARD kill (the chaos plane's ``node.kill`` site): the
        service must die leaving its meta row RUNNING and its bus
        registration stale — the wreckage a real node death leaves —
        so the supervise sweep's detection path is what recovery
        exercises. For process/docker runtimes ``destroy_service`` IS
        hard already (the dying process cannot update meta rows; the
        manager-side ``_stop_service`` meta update is simply not
        called); thread mode overrides this."""
        self.destroy_service(container_id)

    @abc.abstractmethod
    def service_alive(self, container_id: str) -> bool:
        pass


class ThreadContainerManager(ContainerManager):
    """Resident-runner mode: every service is a thread in this process.

    One process owns all TPU chips; per-service chip isolation is the
    thread-local ``ChipGroup`` binding. This is the default deployment on
    a single host/slice and the substrate for integration tests
    (SURVEY.md §4: real multi-worker tests on one host, no mocks).
    """

    supports_chip_sharing = True  # threads share one jax backend

    def __init__(self, ctx: SystemContext):
        self.ctx = ctx
        self._services: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def create_service(self, service_id: str, environ: Dict[str, str]) -> str:
        service = build_service(environ, self.ctx)
        service.start()
        with self._lock:
            self._services[service_id] = service
        return service_id

    def destroy_service(self, container_id: str) -> None:
        with self._lock:
            service = self._services.pop(container_id, None)
        if service is not None:
            service.stop()

    def kill_service(self, container_id: str) -> None:
        """Thread-mode hard kill: a service exposing ``kill()`` (the
        inference worker) dies through its injected-crash path — meta
        row left RUNNING, registration stale. Services without one
        (HTTP frontends, advisors) fall back to a graceful stop: a
        thread can't be SIGKILLed, so this is the closest honest
        emulation, and the chaos tests target the worker case."""
        with self._lock:
            service = self._services.pop(container_id, None)
        if service is None:
            return
        kill = getattr(service, "kill", None)
        if kill is not None:
            kill()
        else:
            service.stop()

    def service_alive(self, container_id: str) -> bool:
        with self._lock:
            service = self._services.get(container_id)
        if service is None:
            return False
        running = getattr(service, "running", None)
        if running is None:  # services without a thread handle (e.g. HTTP)
            return True
        return bool(running)

    def get(self, container_id: str) -> Optional[Any]:
        with self._lock:
            return self._services.get(container_id)


class ProcessContainerManager(ContainerManager):
    """Subprocess mode: one OS process per service.

    Requires file/tcp-backed stores (the env URIs must be reachable from
    a fresh process). On TPU, use one process per chip group only when the
    runtime supports subslicing; otherwise prefer the resident runner.
    """

    def __init__(self, python: str = sys.executable):
        self.python = python
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def create_service(self, service_id: str, environ: Dict[str, str]) -> str:
        env = dict(os.environ)
        env.update(environ)
        proc = subprocess.Popen(
            [self.python, "-m", "rafiki_tpu.container.services"], env=env)
        with self._lock:
            self._procs[service_id] = proc
        return service_id

    def destroy_service(self, container_id: str) -> None:
        with self._lock:
            proc = self._procs.pop(container_id, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    def service_alive(self, container_id: str) -> bool:
        with self._lock:
            proc = self._procs.get(container_id)
        return proc is not None and proc.poll() is None


class DockerContainerManager(ContainerManager):
    """Docker runtime: one container per service, via the docker CLI.

    Parity: SURVEY.md §2 "Container manager" (upstream
    ``DockerSwarmContainerManager`` schedules worker/predictor services
    as swarm services with env + GPU reservations). Here each service
    runs the node image (``dockerfiles/node.Dockerfile``) with the
    service env injected and the generic service entrypoint
    (``rafiki_tpu.container.services``); chip assignment rides the
    ``RAFIKI_TPU_CHIPS`` env var exactly as in the other runtimes — no
    nvidia-docker anywhere. Host networking by default so bus/admin
    ports behave like the process runtime.

    The docker CLI is invoked through an injectable ``runner`` (tests
    use a fake; no docker SDK dependency).
    """

    def __init__(self, image: str = "rafiki-tpu", network: str = "host",
                 extra_args: Optional[list] = None,
                 volumes: Optional[list] = None, runner=None):
        self.image = image
        self.network = network
        self.extra_args = list(extra_args or [])
        self.volumes = list(volumes or [])
        self._run = runner or self._run_docker

    @staticmethod
    def _run_docker(args: list) -> str:
        out = subprocess.run(["docker", *args], check=True,
                             capture_output=True, text=True)
        return out.stdout.strip()

    @staticmethod
    def _normalize_store_env(environ: Dict[str, str]) -> Dict[str, str]:
        """Absolutise file-backed store paths: a relative META_URI /
        PARAMS_DIR would resolve against the image's own workdir inside
        the container and silently diverge from the host store."""
        from ..constants import EnvVars

        env = dict(environ)
        meta = env.get(EnvVars.META_URI, "")
        if meta and meta != ":memory:" and "://" not in meta:
            env[EnvVars.META_URI] = os.path.abspath(meta)
        params = env.get(EnvVars.PARAMS_DIR, "")
        if params:
            env[EnvVars.PARAMS_DIR] = os.path.abspath(params)
        return env

    @staticmethod
    def _auto_mounts(environ: Dict[str, str]) -> list:
        """The file-backed stores the env URIs point at must exist
        INSIDE the container: mount them host-path = container-path so
        the (absolutised) env values stay valid verbatim."""
        from ..constants import EnvVars

        mounts = []
        meta = environ.get(EnvVars.META_URI, "")
        if meta and meta != ":memory:" and "://" not in meta:
            parent = os.path.dirname(meta)
            if parent and parent != "/":
                mounts.append(parent)
        params = environ.get(EnvVars.PARAMS_DIR, "")
        if params:
            mounts.append(params)
        return mounts

    def create_service(self, service_id: str, environ: Dict[str, str]) -> str:
        environ = self._normalize_store_env(environ)
        args = ["run", "-d", "--name", f"rafiki-{service_id[:12]}",
                "--network", self.network]
        for key, value in environ.items():
            args += ["-e", f"{key}={value}"]
        seen_targets = set()  # docker rejects duplicate mount points
        for mount in self._auto_mounts(environ) + self.volumes:
            spec = mount if ":" in mount else f"{mount}:{mount}"
            target = spec.split(":")[1]
            if target in seen_targets:
                continue
            seen_targets.add(target)
            args += ["-v", spec]
        args += self.extra_args
        args += [self.image, "python", "-m",
                 "rafiki_tpu.container.services"]
        return self._run(args)  # stdout = container id

    def destroy_service(self, container_id: str) -> None:
        try:
            self._run(["rm", "-f", container_id])
        except subprocess.CalledProcessError:
            _log.warning("docker rm -f %s failed", container_id,
                         exc_info=True)

    def service_alive(self, container_id: str) -> bool:
        try:
            out = self._run(["inspect", "-f", "{{.State.Running}}",
                             container_id])
        except subprocess.CalledProcessError as e:
            # Only a definitive "the container is gone" counts as dead.
            # Any other CLI failure (daemon restarting, socket blip) must
            # NOT read as death: the supervisor would tear down healthy
            # services and double-schedule their chip ranges.
            stderr = (e.stderr or "") if hasattr(e, "stderr") else ""
            if "No such" in stderr:
                return False
            _log.warning("docker inspect %s failed transiently; assuming "
                         "alive", container_id)
            return True
        return out.strip() == "true"
