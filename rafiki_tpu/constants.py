"""Platform-wide enums and constants.

Parity: SURVEY.md §2 "Constants" (upstream ``rafiki/constants.py``): service
types, user types, budget keys, job/trial statuses, task types. The one
deliberate change is hardware vocabulary: the GPU budget key becomes
``CHIP_COUNT`` (TPU chips), with ``GPU_COUNT`` kept as an accepted alias so
reference client scripts run unchanged.
"""


class ServiceType:
    TRAIN = "TRAIN"
    INFERENCE = "INFERENCE"
    PREDICT = "PREDICT"
    ADVISOR = "ADVISOR"


class UserType:
    SUPERADMIN = "SUPERADMIN"
    ADMIN = "ADMIN"
    MODEL_DEVELOPER = "MODEL_DEVELOPER"
    APP_DEVELOPER = "APP_DEVELOPER"


class BudgetOption:
    MODEL_TRIAL_COUNT = "MODEL_TRIAL_COUNT"
    TIME_HOURS = "TIME_HOURS"
    CHIP_COUNT = "CHIP_COUNT"
    # Accepted alias for reference-script compatibility; normalised to
    # CHIP_COUNT at the Admin boundary.
    GPU_COUNT = "GPU_COUNT"


DEFAULT_BUDGET = {
    BudgetOption.MODEL_TRIAL_COUNT: 5,
    BudgetOption.TIME_HOURS: 1.0,
    BudgetOption.CHIP_COUNT: 0,
}


class TrainJobStatus:
    STARTED = "STARTED"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    ERRORED = "ERRORED"


class TrialStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"
    ERRORED = "ERRORED"
    TERMINATED = "TERMINATED"


class InferenceJobStatus:
    STARTED = "STARTED"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    ERRORED = "ERRORED"


class ServiceStatus:
    STARTED = "STARTED"
    DEPLOYING = "DEPLOYING"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    ERRORED = "ERRORED"


class TaskType:
    IMAGE_CLASSIFICATION = "IMAGE_CLASSIFICATION"
    POS_TAGGING = "POS_TAGGING"
    LANGUAGE_MODELING = "LANGUAGE_MODELING"
    TABULAR_CLASSIFICATION = "TABULAR_CLASSIFICATION"
    TABULAR_REGRESSION = "TABULAR_REGRESSION"


class ModelAccessRight:
    PUBLIC = "PUBLIC"
    PRIVATE = "PRIVATE"


class ParamsType:
    """Which shared parameters a trial proposal asks to warm-start from.

    Parity: SURVEY.md §2 "Param store" sharing policies (recent/best
    params; used heavily by ENAS weight sharing).
    """

    NONE = "NONE"
    LOCAL_RECENT = "LOCAL_RECENT"
    LOCAL_BEST = "LOCAL_BEST"
    GLOBAL_RECENT = "GLOBAL_RECENT"
    GLOBAL_BEST = "GLOBAL_BEST"


# Environment variable names injected into worker services by the
# ServicesManager (SURVEY.md §3.1). RAFIKI_TPU_CHIPS is the
# CUDA_VISIBLE_DEVICES replacement: a comma-separated list of chip indices
# forming this service's chip group.
class EnvVars:
    SERVICE_ID = "RAFIKI_TPU_SERVICE_ID"
    SERVICE_TYPE = "RAFIKI_TPU_SERVICE_TYPE"
    SUB_TRAIN_JOB_ID = "RAFIKI_TPU_SUB_TRAIN_JOB_ID"
    INFERENCE_JOB_ID = "RAFIKI_TPU_INFERENCE_JOB_ID"
    TRIAL_ID = "RAFIKI_TPU_TRIAL_ID"
    CHIPS = "RAFIKI_TPU_CHIPS"
    WORKDIR = "RAFIKI_TPU_WORKDIR"
    META_URI = "RAFIKI_TPU_META_URI"
    BUS_URI = "RAFIKI_TPU_BUS_URI"
    PARAMS_DIR = "RAFIKI_TPU_PARAMS_DIR"
    LOG_DIR = "RAFIKI_TPU_LOG_DIR"
    # Set by the subprocess/docker entrypoint AFTER it binds its
    # metrics server (container/services.py): the scrapable host:port
    # this service advertises in its bus registration so the SLO
    # engine can read worker-owned families (never a config knob —
    # the bound port is only known at runtime).
    METRICS_ADDR = "RAFIKI_TPU_METRICS_ADDR"
    # Identity of the node that placed this service (ServicesManager
    # node_id, injected at spawn like SERVICE_ID): workers echo it in
    # their bus registration so frontends can route shards and prefer
    # same-node replicas (docs/cluster.md). Never a config knob — the
    # placing node decides it.
    NODE_ID = "RAFIKI_TPU_NODE_ID"
