"""Chip groups: the TPU replacement for per-service GPU assignment.

Parity: SURVEY.md §2 "ServicesManager / GPU scheduler" + §7 hard-part
"chip-range multi-tenancy". The reference Admin assigns device indices to
worker containers via ``CUDA_VISIBLE_DEVICES``; here the scheduler assigns a
**chip range** — a contiguous slice of ``jax.devices()`` — communicated to
the worker process via the ``RAFIKI_TPU_CHIPS`` env var (comma-separated
global device indices). The worker builds its ``jax.sharding.Mesh`` from
exactly those devices, so every trial's collectives ride ICI within its own
group and groups never contend.

Two placement regimes (SURVEY.md §7):

- **resident runner** (default here): one process owns all chips of the host
  and schedules trials onto ``Mesh`` subsets — no process isolation needed,
  works on any slice topology.
- **process-per-group**: workers are separate processes; each sees the full
  device list but only *uses* its assigned range. (True device isolation à
  la ``TPU_VISIBLE_CHIPS`` is runtime-dependent; the allocator's contract is
  identical either way.)
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..constants import EnvVars


@dataclass(frozen=True)
class ChipGroup:
    """An ordered set of global device indices assigned to one service."""

    indices: tuple  # tuple[int, ...] into jax.devices()
    name: str = ""

    @property
    def n_chips(self) -> int:
        return len(self.indices)

    def devices(self) -> List:
        import jax

        all_devs = jax.devices()
        return [all_devs[i] for i in self.indices]

    def to_env(self) -> str:
        return ",".join(str(i) for i in self.indices)

    @staticmethod
    def from_env(value: Optional[str] = None) -> "ChipGroup":
        """Build the group from ``RAFIKI_TPU_CHIPS`` (or all devices)."""
        import jax

        if value is None:
            value = os.environ.get(EnvVars.CHIPS, "")
        if value:
            idx = tuple(int(x) for x in value.split(",") if x != "")
        else:
            idx = tuple(range(len(jax.devices())))
        return ChipGroup(indices=idx)

    # --- Thread-scoped binding (resident-runner mode) ---
    #
    # Worker threads sharing one process cannot partition devices via the
    # process-wide env var; each service thread binds its group here and
    # models resolve it via ``ChipGroup.current()`` (thread-local → env →
    # all devices).

    _tls = threading.local()

    def bind_to_thread(self) -> None:
        ChipGroup._tls.group = self

    @staticmethod
    def unbind_thread() -> None:
        ChipGroup._tls.group = None

    @staticmethod
    def current() -> "ChipGroup":
        group = getattr(ChipGroup._tls, "group", None)
        return group if group is not None else ChipGroup.from_env()


class ChipAllocator:
    """Carves a device list into non-overlapping chip groups.

    The Admin-side resource manager: thread-safe, contiguous-first-fit so
    groups stay physically adjacent (contiguous ranges on a v5e slice keep
    intra-group ICI hops minimal). ``allocate`` returns None when the
    request cannot be satisfied — callers queue and retry (scheduler
    fairness is handled one level up, in the ServicesManager).
    """

    def __init__(self, n_chips: Optional[int] = None):
        if n_chips is None:
            import jax

            n_chips = len(jax.devices())
        self.n_chips = n_chips
        self._lock = threading.Lock()
        self._owner: List[Optional[str]] = [None] * n_chips
        self._groups: Dict[str, ChipGroup] = {}

    def allocate(self, n: int, name: str) -> Optional[ChipGroup]:
        """First-fit allocation of ``n`` contiguous chips; None if full."""
        if n <= 0:
            raise ValueError("n must be positive")
        with self._lock:
            if name in self._groups:
                raise ValueError(
                    f"group {name!r} already holds chips; release it first")
            run_start, run_len = None, 0
            for i in range(self.n_chips):
                if self._owner[i] is None:
                    run_start = i if run_len == 0 else run_start
                    run_len += 1
                    if run_len == n:
                        idx = tuple(range(run_start, run_start + n))
                        for j in idx:
                            self._owner[j] = name
                        group = ChipGroup(indices=idx, name=name)
                        self._groups[name] = group
                        return group
                else:
                    run_len = 0
            return None

    def release(self, name: str) -> None:
        with self._lock:
            group = self._groups.pop(name, None)
            if group:
                for i in group.indices:
                    if self._owner[i] == name:
                        self._owner[i] = None

    @property
    def free_chips(self) -> int:
        with self._lock:
            return sum(1 for o in self._owner if o is None)

    def utilization(self) -> float:
        return 1.0 - self.free_chips / self.n_chips
