"""Chip groups: the TPU replacement for per-service GPU assignment.

Parity: SURVEY.md §2 "ServicesManager / GPU scheduler" + §7 hard-part
"chip-range multi-tenancy". The reference Admin assigns device indices to
worker containers via ``CUDA_VISIBLE_DEVICES``; here the scheduler assigns a
**chip range** — a contiguous slice of ``jax.devices()`` — communicated to
the worker process via the ``RAFIKI_TPU_CHIPS`` env var (comma-separated
global device indices). The worker builds its ``jax.sharding.Mesh`` from
exactly those devices, so every trial's collectives ride ICI within its own
group and groups never contend.

Two placement regimes (SURVEY.md §7):

- **resident runner** (default here): one process owns all chips of the host
  and schedules trials onto ``Mesh`` subsets — no process isolation needed,
  works on any slice topology.
- **process-per-group**: workers are separate processes; each sees the full
  device list but only *uses* its assigned range. (True device isolation à
  la ``TPU_VISIBLE_CHIPS`` is runtime-dependent; the allocator's contract is
  identical either way.)
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..constants import EnvVars


@dataclass(frozen=True)
class ChipGroup:
    """An ordered set of global device indices assigned to one service."""

    indices: tuple  # tuple[int, ...] into jax.devices()
    name: str = ""

    @property
    def n_chips(self) -> int:
        return len(self.indices)

    def devices(self) -> List:
        import jax

        all_devs = jax.devices()
        return [all_devs[i] for i in self.indices]

    def to_env(self) -> str:
        return ",".join(str(i) for i in self.indices)

    @staticmethod
    def from_env(value: Optional[str] = None) -> "ChipGroup":
        """Build the group from ``RAFIKI_TPU_CHIPS`` (or all devices)."""
        import jax

        if value is None:
            value = os.environ.get(EnvVars.CHIPS, "")
        if value:
            idx = tuple(int(x) for x in value.split(",") if x != "")
        else:
            idx = tuple(range(len(jax.devices())))
        return ChipGroup(indices=idx)

    # --- Thread-scoped binding (resident-runner mode) ---
    #
    # Worker threads sharing one process cannot partition devices via the
    # process-wide env var; each service thread binds its group here and
    # models resolve it via ``ChipGroup.current()`` (thread-local → env →
    # all devices).

    _tls = threading.local()

    def bind_to_thread(self) -> None:
        ChipGroup._tls.group = self

    @staticmethod
    def unbind_thread() -> None:
        ChipGroup._tls.group = None

    @staticmethod
    def current() -> "ChipGroup":
        group = getattr(ChipGroup._tls, "group", None)
        return group if group is not None else ChipGroup.from_env()


def discover_topology(devices: Sequence) -> Optional[List[tuple]]:
    """Per-device physical coords, or None when the backend has none.

    TPU devices expose ``.coords`` — ``(x, y, z)`` position on the slice's
    ICI torus (v5e: a 2-D torus, z == 0). Virtual CPU devices don't; the
    allocator then falls back to linear index adjacency.
    """
    coords = []
    for d in devices:
        c = getattr(d, "coords", None)
        if c is None or len(c) < 2:
            return None
        coords.append(tuple(int(v) for v in c))
    return coords if len(set(coords)) == len(coords) else None


def _rect_shapes(n: int):
    """(h, w) factorizations of n, squarest first (minimal ICI diameter)."""
    shapes = [(h, n // h) for h in range(1, n + 1) if n % h == 0]
    return sorted(shapes, key=lambda s: (max(s), abs(s[0] - s[1])))


def _box_shapes(n: int):
    """(d, h, w) factorizations of n, most cube-like first.

    Ordering minimizes the box's ICI diameter: smallest max extent,
    then smallest extent sum. On a z-flat (2-D) grid the d>1 shapes
    simply never fit and the search degrades to the rectangle order.
    """
    shapes = []
    for d in range(1, n + 1):
        if n % d:
            continue
        for h in range(1, n // d + 1):
            if (n // d) % h == 0:
                shapes.append((d, h, n // (d * h)))
    return sorted(shapes, key=lambda s: (max(s), sum(s)))


class ChipAllocator:
    """Carves a device list into non-overlapping chip groups.

    The Admin-side resource manager: thread-safe. Placement is
    **topology-aware** when the backend exposes device coords (TPU): a
    group of ``n`` chips is placed as the most cube-like free
    axis-aligned box on the slice's ICI torus — a rectangle on 2-D
    slices (v5e), a genuine d×h×w box on 3-D tori (v4/v5p) — so every
    intra-group collective rides single-hop ICI links (a linear index
    range can straddle torus rows — adjacent indices, distant chips).
    When fragmentation or an awkward size blocks every box, the group
    falls back to a connected free blob (still ICI-internal, larger
    diameter). Without coords (virtual CPU meshes) placement is
    contiguous-first-fit on the device index. ``allocate`` returns None
    when the request cannot be satisfied — callers queue and retry
    (scheduler fairness is handled one level up, in the
    ServicesManager).

    **Chip sharing (single-chip multi-tenancy).** ``allocate(...,
    shared_ok=True)`` adds a fallback tier: when no exclusive placement
    exists, the group may be placed on already-owned chips — least-
    subscribed cells first, never exceeding ``max_share`` owners per
    chip. In resident-runner mode every worker is a thread of ONE
    process sharing one jax backend, so co-owned chips are legal: the
    co-owners' dispatches interleave on the device queue (time-sliced
    tenancy — how a v5e-1 runs two concurrent jobs, BASELINE config[5]).
    Process/docker workers must NOT share (two processes cannot open
    one TPU chip); the ServicesManager gates ``shared_ok`` on the
    container manager's ``supports_chip_sharing``.
    """

    def __init__(self, n_chips: Optional[int] = None,
                 topology: Optional[Sequence[tuple]] = None):
        if n_chips is None:
            from ..jaxenv import (backend_initialized, ensure_platform,
                                  resolved_platform)

            # Sizing from jax.devices() requires a backend; resolve the
            # platform first so a dead accelerator tunnel degrades to
            # CPU behind a deadline instead of hanging construction.
            if not backend_initialized() and resolved_platform() is None:
                ensure_platform()
            import jax

            devices = jax.devices()
            n_chips = len(devices)
            if topology is None:
                topology = discover_topology(devices)
        elif topology is None:
            # Explicit chip limit (serve --chips): still discover — but
            # ONLY when touching the backend is known-safe: a live
            # backend, or a platform THIS process resolved through
            # jaxenv.ensure_platform (an env marker inherited from a
            # parent is not fresh enough — the tunnel can die between
            # processes, and raw library construction must never be the
            # call that hangs on backend init).
            from ..jaxenv import backend_initialized, resolved_platform

            if backend_initialized() or resolved_platform() is not None:
                import jax

                topology = discover_topology(jax.devices()[:n_chips])
        self.n_chips = n_chips
        if topology is not None and len(topology) != n_chips:
            raise ValueError(f"topology has {len(topology)} entries for "
                             f"{n_chips} chips")
        # Normalize coords to (x, y, z): v5e slices report z == 0
        # everywhere; v4/v5p report a genuine 3-D torus position. The
        # box search below handles both (a z-flat grid only ever fits
        # depth-1 boxes, i.e. plain rectangles).
        self._topology = ([tuple(c[:3]) + (0,) * (3 - min(len(c), 3))
                           for c in topology] if topology else None)
        self._lock = threading.Lock()
        # Co-ownership: each chip carries a list of owner names (shared
        # tenancy appends; exclusive placement requires an empty list).
        self._owners: List[List[str]] = [[] for _ in range(n_chips)]
        self._groups: Dict[str, ChipGroup] = {}

    def allocate(self, n: int, name: str, *, shared_ok: bool = False,
                 max_share: Optional[int] = None) -> Optional[ChipGroup]:
        """Allocate ``n`` chips as an ICI-compact group; None if full.

        ``shared_ok`` adds the time-sliced fallback tier (docstring
        above): exclusive placement first, then least-subscribed shared
        placement up to ``max_share`` owners per chip (default 4;
        ``RAFIKI_TPU_MAX_CHIP_SHARE`` overrides — a dense box serving
        many replica workers per chip may deliberately oversubscribe).
        The env var is ``NodeConfig.max_chip_share`` (promoted from the
        env-only expert baseline in r14: the autoscaler's scale-up
        leans on time-sliced placement, making the cap a sizing
        decision); the allocator keeps reading env per call so it
        works without a NodeConfig and honors mid-run overrides.
        """
        if n <= 0:
            raise ValueError("n must be positive")
        if max_share is None:
            import os

            try:
                max_share = int(os.environ.get(
                    "RAFIKI_TPU_MAX_CHIP_SHARE", "4"))
            except ValueError:
                max_share = 4
        with self._lock:
            if name in self._groups:
                raise ValueError(
                    f"group {name!r} already holds chips; release it first")
            # With a known topology, placements must be ICI-connected:
            # a linear index run can straddle torus rows, putting one
            # group's collectives on other groups' ICI links. Axis-
            # aligned boxes first (minimal diameter); when no box fits
            # — the size has no box factorization (5 or 7 on a 2x4) or
            # fragmentation blocks every feasible box — fall back to a
            # connected free blob, which keeps every collective on
            # group-internal links at the cost of a non-minimal
            # diameter. Only a grid with no connected free region of n
            # cells returns None -> callers queue/retry. With
            # ``shared_ok``, ever-more-subscribed cells are admitted one
            # load tier at a time, so a shared group lands on the
            # least-loaded chips that fit it.
            idx = None
            caps = range(max_share if shared_ok else 1)
            for cap in caps:
                allowed = {i for i, o in enumerate(self._owners)
                           if len(o) <= cap}
                if len(allowed) < n:
                    continue
                if self._topology is not None:
                    idx = self._find_box(n, allowed)
                    if idx is None:
                        idx = self._find_blob(n, allowed)
                else:
                    idx = self._find_linear(n, allowed)
                if idx is not None:
                    break
            if idx is None:
                return None
            for j in idx:
                self._owners[j].append(name)
            group = ChipGroup(indices=idx, name=name)
            self._groups[name] = group
            return group

    def _find_box(self, n: int, allowed: set) -> Optional[tuple]:
        """Most cube-like free d×h×w box on the (x, y, z) coord grid.

        Returned indices are in BOUSTROPHEDON (snake) order — each row
        reversed relative to the previous, and each z-plane's whole
        traversal reversed relative to the plane below — so devices
        adjacent in group order are physically adjacent on the torus at
        every hop including row turns and plane turns; ``build_mesh``'s
        ring (``sp``) axis ppermutes between group-order neighbours,
        and plain row-major order would make those boundaries
        multi-hop diagonals. On a z-flat grid (v5e) only d == 1 boxes
        fit and this is exactly the 2-D rectangle search.
        """
        grid = {c: i for i, c in enumerate(self._topology)}
        free = {c for c, i in grid.items() if i in allowed}
        for d, h, w in _box_shapes(n):
            for (x0, y0, z0) in sorted(free, key=lambda c: (c[2], c[1],
                                                            c[0])):
                cells = []
                for dz in range(d):
                    plane = []
                    for dy in range(h):
                        xs = (range(w) if dy % 2 == 0
                              else range(w - 1, -1, -1))
                        plane.extend((x0 + dx, y0 + dy, z0 + dz)
                                     for dx in xs)
                    if dz % 2 == 1:
                        plane.reverse()
                    cells.extend(plane)
                if all(c in free for c in cells):
                    return tuple(grid[c] for c in cells)
        return None

    def _find_blob(self, n: int, allowed: set) -> Optional[tuple]:
        """Connected free region of n cells (BFS, 6-neighbour).

        Fallback when no axis-aligned box fits — whether because the
        size has no feasible factorization or because fragmentation
        blocks every feasible box: the group stays ICI-connected (every
        member reachable through group-internal links) even though its
        diameter is not minimal.
        """
        grid = {c: i for i, c in enumerate(self._topology)}
        free = {c for c, i in grid.items() if i in allowed}
        for anchor in sorted(free):
            blob, frontier = [anchor], [anchor]
            seen = {anchor}
            while frontier and len(blob) < n:
                x, y, z = frontier.pop(0)
                for nxt in ((x + 1, y, z), (x - 1, y, z), (x, y + 1, z),
                            (x, y - 1, z), (x, y, z + 1), (x, y, z - 1)):
                    if nxt in free and nxt not in seen:
                        seen.add(nxt)
                        blob.append(nxt)
                        frontier.append(nxt)
                        if len(blob) == n:
                            break
            if len(blob) == n:
                return tuple(grid[c] for c in sorted(blob,
                                                     key=lambda c:
                                                     (c[2], c[1], c[0])))
        return None

    def _find_linear(self, n: int, allowed: set) -> Optional[tuple]:
        """First-fit contiguous index range (no-topology fallback)."""
        run_start, run_len = None, 0
        for i in range(self.n_chips):
            if i in allowed:
                run_start = i if run_len == 0 else run_start
                run_len += 1
                if run_len == n:
                    return tuple(range(run_start, run_start + n))
            else:
                run_len = 0
        return None

    def release(self, name: str) -> None:
        with self._lock:
            group = self._groups.pop(name, None)
            if group:
                for i in group.indices:
                    if name in self._owners[i]:
                        self._owners[i].remove(name)

    @property
    def free_chips(self) -> int:
        with self._lock:
            return sum(1 for o in self._owners if not o)

    def utilization(self) -> float:
        return 1.0 - self.free_chips / self.n_chips
