"""Packed device→host transfer for pytrees.

``jax.device_get`` on a pytree transfers LEAF BY LEAF, and on a
proxied/tunneled TPU transport every readback pays a flush window
(measured ~28 ms per leaf on the shared v5e tunnel). A ~220-leaf
supernet therefore costs ~6 s per ``dump_parameters`` — which was the
dominant cost of an ENAS trial (r5 profile: 37.7 of 43.3 s across six
trials inside ``Array._value``).

``device_get_tree`` packs instead: one jitted concat per dtype group
(compiled once per tree signature, cached), ONE readback per dtype,
then a host-side split. The same ~30 MB moves in 1-3 transfers instead
of hundreds.

``make_host_stager`` is the host→device counterpart for the generative
decode loop's per-step token upload: it probes whether the runtime can
route the hop through a genuinely pinned (page-locked) host staging
buffer (TPU runtimes expose it as the ``pinned_host`` memory kind;
a pageable source forces the runtime to bounce through its own pinned
pool first) and falls back silently to a plain ``device_put`` where
the memory space doesn't exist. The worker records which path is live
in its bus registration (``staging``) so bench artifacts can tell what
was measured.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_PACK_CACHE: "OrderedDict[Any, Any]" = OrderedDict()
_PACK_CACHE_MAX = 32


def device_get_tree(tree: Any) -> Any:
    """Device→host for a whole pytree in one transfer per dtype group.

    Returns a tree of numpy arrays with identical structure/shapes.
    Host-side (numpy) leaves pass through unchanged.
    """
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    dev_idx = [i for i, leaf in enumerate(leaves)
               if isinstance(leaf, jax.Array)]
    if not dev_idx:
        return jax.tree.map(np.asarray, tree)
    sig = tuple((tuple(leaves[i].shape), str(leaves[i].dtype))
                for i in dev_idx)
    # WHICH leaves are device-resident is part of the signature: two
    # trees with the same treedef and coinciding device-leaf
    # (shape, dtype) sequences but a different device/host mix must not
    # share a cached pack plan (the cached groups would pack the wrong
    # leaves, leaving None holes in the output tree).
    key = (treedef, tuple(dev_idx), sig)
    entry = _PACK_CACHE.get(key)
    if entry is None:
        groups: Dict[str, List[int]] = {}
        for i in dev_idx:
            groups.setdefault(str(leaves[i].dtype), []).append(i)

        def pack_fn(ls):
            return {dt: jnp.concatenate(
                        [ls[i].reshape(-1) for i in idxs])
                    for dt, idxs in groups.items()}

        entry = (jax.jit(pack_fn), groups)
        _PACK_CACHE[key] = entry
        _PACK_CACHE.move_to_end(key)
        while len(_PACK_CACHE) > _PACK_CACHE_MAX:
            _PACK_CACHE.popitem(last=False)
    pack_fn, groups = entry
    packed = pack_fn(leaves)
    out: List[Any] = [np.asarray(leaf) if i not in set(dev_idx)
                      else None for i, leaf in enumerate(leaves)]
    for dt, idxs in groups.items():
        flat = np.asarray(packed[dt])  # ONE readback per dtype
        offset = 0
        for i in idxs:
            shape: Tuple[int, ...] = tuple(leaves[i].shape)
            n = int(np.prod(shape)) if shape else 1
            out[i] = flat[offset:offset + n].reshape(shape)
            offset += n
    return jax.tree.unflatten(treedef, out)


def make_host_stager(sharding) -> Tuple[Any, str]:
    """Build the host→device staging callable for small per-step
    uploads (the decode loop's next-token ids).

    Returns ``(stage, mode)``: ``stage(np_array)`` places the array
    under ``sharding``; ``mode`` is ``"pinned"`` when the hop rides a
    page-locked host buffer (``pinned_host`` memory kind, probed once
    here with a real round-trip so a runtime that ADVERTISES the space
    but can't transfer through it still falls back) or ``"pageable"``
    for the plain ``device_put`` path. The probe is deliberately
    silent on failure — CPU meshes and older runtimes simply don't
    have the memory space, and that is not an error.
    """
    try:
        pinned = sharding.with_memory_kind("pinned_host")
        probe = jax.device_put(
            jax.device_put(np.zeros((4,), np.int32), pinned), sharding)
        jax.block_until_ready(probe)

        def stage(arr):
            return jax.device_put(jax.device_put(arr, pinned), sharding)

        return stage, "pinned"
    except Exception:
        def stage(arr):
            return jax.device_put(arr, sharding)

        return stage, "pageable"
