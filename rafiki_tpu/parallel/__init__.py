"""Parallel execution layer: chip groups, meshes, sharding rules."""

from .chips import ChipAllocator, ChipGroup
from .transfer import device_get_tree
from .mesh import (DP_AXIS, EP_AXIS, PP_AXIS, SP_AXIS, TP_AXIS,
                   batch_sharding,
                   build_mesh,
                   param_spec, replicated, shard_variables,
                   variables_shardings)

__all__ = [
    "ChipAllocator", "ChipGroup",
    "DP_AXIS", "EP_AXIS", "PP_AXIS", "SP_AXIS", "TP_AXIS", "build_mesh",
    "batch_sharding",
    "replicated", "param_spec", "shard_variables", "variables_shardings",
    "device_get_tree",
]
