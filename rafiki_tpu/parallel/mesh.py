"""Mesh construction and sharding rules for trial execution.

The platform's intra-trial parallelism (SURVEY.md §2.9): each trial trains
under ``jax.jit`` over a 3-D ``Mesh`` with axes ``("dp", "sp", "tp")``
built from its chip group — batch data-parallel over ``dp``, sequence /
context parallelism over ``sp`` (long sequences split across chips; the
ring-attention op in ``rafiki_tpu.ops`` rotates K/V shards over ICI), and
optional tensor-parallel sharding of large kernels over ``tp``. XLA
inserts the ICI collectives (psum for grads on ``dp``, all-gather /
reduce-scatter on ``tp``); only the ring schedule issues a collective
(``ppermute``) by hand.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
SP_AXIS = "sp"
TP_AXIS = "tp"

# Kernels smaller than this are cheaper to replicate than to shard+gather.
_TP_MIN_FEATURES = 256


# Interned meshes: jit/AOT caches key on NamedSharding equality, which
# includes the Mesh object — handing out a fresh Mesh per trial would
# defeat the compiled-step cache (a recompile per trial with identical
# shapes). One process-wide Mesh per (devices, tp) keeps shardings equal.
_MESH_CACHE: dict = {}


def build_mesh(devices: Optional[Sequence[Any]] = None, tp: int = 1,
               sp: int = 1) -> Mesh:
    """Arrange ``devices`` into a (dp, sp, tp) mesh; dp = n / (sp * tp).

    Axis order puts ``tp`` fastest-varying (adjacent devices — its
    all-gathers are the most latency-sensitive collectives), then ``sp``:
    with ``tp == 1`` (the common case) ring-attention's ``ppermute``
    hops between devices adjacent in device order; with ``tp > 1`` the
    sp ring hops stride ``tp``.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if n % (tp * sp) != 0:
        raise ValueError(f"{n} devices not divisible by tp*sp={tp * sp}")
    key = (tuple(devices), tp, sp)
    mesh = _MESH_CACHE.get(key)
    if mesh is None:
        arr = np.asarray(devices, dtype=object).reshape(
            n // (sp * tp), sp, tp)
        mesh = Mesh(arr, (DP_AXIS, SP_AXIS, TP_AXIS))
        _MESH_CACHE[key] = mesh
    return mesh


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis batch sharding over dp (tp replicates the batch)."""
    return NamedSharding(mesh, P(DP_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def param_spec(arr: Any, tp: int) -> P:
    """Partition rule for one parameter.

    Dense/conv kernels with a large output-feature axis shard that axis
    over ``tp`` (column parallelism — each tp shard computes a slice of the
    output features; XLA all-gathers activations where needed). Biases,
    norms, and small kernels replicate.
    """
    shape = getattr(arr, "shape", ())
    if tp <= 1 or len(shape) < 2:
        return P()
    out_features = shape[-1]
    if out_features % tp == 0 and out_features >= _TP_MIN_FEATURES:
        return P(*([None] * (len(shape) - 1)), TP_AXIS)
    return P()


def shard_variables(variables: Any, mesh: Mesh) -> Any:
    """Device-put a variables pytree with per-leaf NamedShardings."""
    tp = mesh.shape[TP_AXIS]
    return jax.tree_util.tree_map(
        lambda leaf: jax.device_put(
            leaf, NamedSharding(mesh, param_spec(leaf, tp))),
        variables)


def variables_shardings(variables: Any, mesh: Mesh) -> Any:
    """The NamedSharding pytree matching ``shard_variables``' placement."""
    tp = mesh.shape[TP_AXIS]
    return jax.tree_util.tree_map(
        lambda leaf: NamedSharding(mesh, param_spec(leaf, tp)), variables)
