"""Mesh construction and sharding rules for trial execution.

The platform's intra-trial parallelism (SURVEY.md §2.9): each trial
trains under ``jax.jit`` over a ``Mesh`` with axes
``("dp", "pp", "ep", "sp", "tp")`` built from its chip group — batch
data-parallel over ``dp``, GPipe pipeline stages over ``pp``
(``rafiki_tpu.ops.pipeline``), mixture-of-experts expert parallelism
over ``ep`` (each chip subset holds a slice of the expert stack; XLA
turns the routing einsums into all-to-alls), sequence / context
parallelism over ``sp`` (long sequences split across chips; the ring /
all-to-all attention schedules in ``rafiki_tpu.ops`` move K/V or heads
over ICI), and optional tensor-parallel sharding of large kernels over
``tp``. XLA inserts the ICI collectives (psum for grads on ``dp``,
all-gather / reduce-scatter on ``tp``, all-to-all + psum on ``ep``);
only the ring and pipeline schedules issue collectives (``ppermute``)
by hand.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"
PP_AXIS = "pp"
EP_AXIS = "ep"
SP_AXIS = "sp"
TP_AXIS = "tp"

# Kernels smaller than this are cheaper to replicate than to shard+gather.
_TP_MIN_FEATURES = 256


# Interned meshes: jit/AOT caches key on NamedSharding equality, which
# includes the Mesh object — handing out a fresh Mesh per trial would
# defeat the compiled-step cache (a recompile per trial with identical
# shapes). One process-wide Mesh per (devices, tp) keeps shardings equal.
_MESH_CACHE: dict = {}


def build_mesh(devices: Optional[Sequence[Any]] = None, tp: int = 1,
               sp: int = 1, ep: int = 1, pp: int = 1) -> Mesh:
    """Arrange ``devices`` into a (dp, pp, ep, sp, tp) mesh;
    dp = n / (pp * ep * sp * tp).

    Axis order puts ``tp`` fastest-varying (adjacent devices — its
    all-gathers are the most latency-sensitive collectives), then ``sp``
    (with ``tp == 1``, the common case, ring-attention's ``ppermute``
    hops between devices adjacent in device order), then ``ep``/``pp``
    (expert all-to-alls and per-tick pipeline hops tolerate longer hops
    than the per-layer tp/sp traffic).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if n % (tp * sp * ep * pp) != 0:
        raise ValueError(f"{n} devices not divisible by pp*ep*sp*tp="
                         f"{pp * ep * sp * tp}")
    key = (tuple(devices), tp, sp, ep, pp)
    mesh = _MESH_CACHE.get(key)
    if mesh is None:
        arr = np.asarray(devices, dtype=object).reshape(
            n // (pp * ep * sp * tp), pp, ep, sp, tp)
        mesh = Mesh(arr, (DP_AXIS, PP_AXIS, EP_AXIS, SP_AXIS, TP_AXIS))
        _MESH_CACHE[key] = mesh
    return mesh


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis batch sharding over dp (tp replicates the batch)."""
    return NamedSharding(mesh, P(DP_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def param_spec(arr: Any, tp: int, ep: int = 1, name: str = "",
               pp: int = 1) -> P:
    """Partition rule for one parameter.

    Stage-stacked parameters — leaves whose tree path contains
    ``stage`` with a leading axis of length ``pp`` — shard that axis
    over ``pp`` (each pipeline stage holds its layer span's params); a
    stage-stacked EXPERT leaf (pp × ep composition) additionally shards
    its second axis — the expert stack — over ``ep``.
    Expert-stacked parameters — leaves whose tree path contains
    ``expert`` with a leading axis divisible by ``ep`` — shard that
    axis over ``ep`` (each ep group holds a slice of the expert stack).
    Dense/conv kernels with a large output-feature axis shard that axis
    over ``tp`` (column parallelism — each tp shard computes a slice of
    the output features; XLA all-gathers activations where needed).
    Biases, norms, and small kernels replicate.
    """
    shape = getattr(arr, "shape", ())
    if pp > 1 and "stage" in name and shape and shape[0] == pp:
        if ep > 1 and "expert" in name and len(shape) > 1 \
                and shape[1] % ep == 0:
            return P(PP_AXIS, EP_AXIS, *([None] * (len(shape) - 2)))
        return P(PP_AXIS, *([None] * (len(shape) - 1)))
    if ep > 1 and "expert" in name and shape and shape[0] % ep == 0:
        return P(EP_AXIS, *([None] * (len(shape) - 1)))
    if tp <= 1 or len(shape) < 2:
        return P()
    out_features = shape[-1]
    if out_features % tp == 0 and out_features >= _TP_MIN_FEATURES:
        return P(*([None] * (len(shape) - 1)), TP_AXIS)
    return P()


def _path_name(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in path).lower()


def _mesh_axis(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis] if axis in mesh.axis_names else 1


def shard_variables(variables: Any, mesh: Mesh) -> Any:
    """Device-put a variables pytree with per-leaf NamedShardings."""
    tp = mesh.shape[TP_AXIS]
    ep, pp = _mesh_axis(mesh, EP_AXIS), _mesh_axis(mesh, PP_AXIS)
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: jax.device_put(
            leaf, NamedSharding(mesh, param_spec(
                leaf, tp, ep=ep, pp=pp, name=_path_name(path)))),
        variables)


def variables_shardings(variables: Any, mesh: Mesh) -> Any:
    """The NamedSharding pytree matching ``shard_variables``' placement."""
    tp = mesh.shape[TP_AXIS]
    ep, pp = _mesh_axis(mesh, EP_AXIS), _mesh_axis(mesh, PP_AXIS)
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, param_spec(
            leaf, tp, ep=ep, pp=pp, name=_path_name(path))), variables)
