"""rafiki-tpu: a TPU-native AutoML / ML-as-a-service platform.

A ground-up rebuild of the capabilities of the reference platform
(pinpom/rafiki — an Admin-orchestrated multi-tenant AutoML system with an
Advisor proposing hyperparameter trials, TrainWorkers executing them, and a
Predictor serving the learned ensemble), re-designed for TPU hardware:

- Trials execute under ``jax.jit`` with explicit ``NamedSharding`` over a
  ``Mesh`` built from a *chip group* — a contiguous range of TPU chips the
  Admin scheduler allocates per service (the ``CUDA_VISIBLE_DEVICES``
  replacement; see ``rafiki_tpu.parallel.chips``).
- The Model SDK (``rafiki_tpu.model``) keeps the reference's BaseModel
  contract (knob config, train/evaluate/predict/dump/load) and adds a
  first-class JAX path (``JaxModel``): flax modules, optax optimizers,
  bfloat16 MXU-friendly compute, AOT-compiled bucketed inference.
- Serving (``rafiki_tpu.predictor``) ensembles top-k trials on-device,
  with a ``vmap``-over-parameters fast path for same-architecture members.

Reference parity map lives in SURVEY.md at the repo root; the reference
checkout was empty at build time, so docstrings cite SURVEY.md sections
(themselves reconstructions of the upstream layout) instead of file:line.
"""

__version__ = "0.1.0"
