"""Serving wire-format + host-copy accounting (the zero-copy evidence).

The packed serving path (docs/serving.md "Wire format & quantization")
claims two things a throughput number on a noisy box cannot prove: the
bytes that actually ride the bus shrink, and the per-burst host copies
(per-query decode, ``np.stack``, pad-``concatenate``) disappear. These
counters ARE that evidence — `bench.py --config serving-concurrent`
judges its packed A/B on their deltas, per the r9 discipline (counter
breakdowns are the stable signal on a 1-device box; throughput ratios
are noise).

- ``rafiki_tpu_serving_wire_bytes_total{format=packed|perquery,
  direction=scatter|reply}`` — estimated serialized payload bytes at
  every Cache send site (an estimate: b64 length + per-frame framing
  overhead, computed without re-serializing the frame).
- ``rafiki_tpu_serving_host_copies_total{site=encode|decode|stack|pad|assemble}``
  — per-tensor host copies on the serving path: per-query base64
  encodes (predictor), per-query/per-shard decodes (worker and packed
  assembly), ``np.stack`` rows, and pad-``concatenate`` events.
- ``rafiki_tpu_serving_quant_total{mode}`` — queries served by a
  quantized model (worker-side; own lazy family, so a quant-off
  process never grows a series).
- ``rafiki_tpu_serving_stacked_dispatch_total{mode=stacked|fallback}``
  + ``rafiki_tpu_serving_dispatches_per_query_ratio`` — the stacked-
  ensemble dispatch evidence (worker-side; own lazy family gated on
  ``RAFIKI_TPU_SERVING_STACKED``, so the stacked-off side of the
  bench A/B exposes zero stacked series).

Gating (the r11 disabled-means-free discipline): the wire/copies
family exists only while ``RAFIKI_TPU_SERVING_PACKED_WIRE`` is not
``off`` AND metrics are enabled — resolved ONCE at first use, so hot
paths pay one function call + one None check. ``compat`` keeps the
accounting while disabling packed *emission/advertisement* (each
Cache/worker/predictor snapshots the mode at construction), which is
both the bench's measured legacy side and an operational kill switch
that keeps observability. Labels are bounded static vocabularies, so
the series are deliberately process-immortal (no per-instance label to
remove).
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Tuple

from . import metrics as _metrics

PACKED_WIRE_ENV = "RAFIKI_TPU_SERVING_PACKED_WIRE"
QUANT_ENV = "RAFIKI_TPU_SERVING_QUANT"
STACKED_ENV = "RAFIKI_TPU_SERVING_STACKED"

#: The ONE accepted-spelling vocabulary for each knob — NodeConfig
#: validation imports these (rejecting typos loudly at config time),
#: while the lenient mode readers below fail SAFE on anything outside
#: them (a hand-set worker env never passes validation).
PACKED_WIRE_SPELLINGS = ("", "1", "on", "true", "yes",
                         "0", "off", "false", "no", "compat")
QUANT_OFF_SPELLINGS = ("", "0", "off", "none", "no", "false")
QUANT_MODES = ("int8",)
STACKED_SPELLINGS = ("", "1", "on", "true", "yes",
                     "0", "off", "false", "no")


def known_packed_wire_spelling(raw: str) -> bool:
    return raw.strip().lower() in PACKED_WIRE_SPELLINGS


def known_quant_spelling(raw: str) -> bool:
    return raw.strip().lower() in QUANT_OFF_SPELLINGS + QUANT_MODES


def known_stacked_spelling(raw: str) -> bool:
    return raw.strip().lower() in STACKED_SPELLINGS


def packed_wire_mode(raw: Optional[str] = None) -> str:
    """The ONE spelling of the packed-wire tri-mode: ``"on"`` (emit +
    account, the default), ``"off"`` (legacy frames, zero new series),
    ``"compat"`` (legacy frames, accounting kept). NodeConfig
    validation and every construction-time env read resolve through
    here so the spellings cannot drift.

    Unrecognized spellings FAIL SAFE to ``"compat"`` (with a warning):
    NodeConfig rejects typos loudly, but env is the documented
    transport and a hand-set worker env never passes validation — a
    typo'd rollback (``offf``) resolving to "on" would silently keep
    the feature it was meant to kill, while compat is always
    behavior-correct (legacy frames, metrics kept)."""
    if raw is None:
        raw = os.environ.get(PACKED_WIRE_ENV, "on")
    raw = raw.strip().lower()
    if raw == "compat":
        return "compat"
    if raw in ("0", "false", "no", "off"):
        return "off"
    if raw in PACKED_WIRE_SPELLINGS:  # the remaining on-spellings
        return "on"
    import logging

    logging.getLogger(__name__).warning(
        "%s=%r is not one of on/off/compat; failing safe to 'compat' "
        "(legacy frames, wire metrics kept)", PACKED_WIRE_ENV, raw)
    return "compat"


def quant_mode(raw: Optional[str] = None) -> str:
    """``""`` (off) or a member of :data:`QUANT_MODES` — the
    InferenceWorker's construction-time read. Unrecognized spellings
    fail SAFE to ``""`` (serve the trained dtype) with a warning: a
    typo'd hand-set env must degrade to f32 serving, not ERROR every
    worker at model load (same rationale as ``packed_wire_mode``;
    NodeConfig validation still rejects typos loudly)."""
    if raw is None:
        raw = os.environ.get(QUANT_ENV, "")
    raw = raw.strip().lower()
    if raw in QUANT_OFF_SPELLINGS:
        return ""
    if raw in QUANT_MODES:
        return raw
    import logging

    logging.getLogger(__name__).warning(
        "%s=%r is not one of %s; failing safe to unquantized serving",
        QUANT_ENV, raw, ("",) + QUANT_MODES)
    return ""


def stacked_mode(raw: Optional[str] = None) -> bool:
    """Whether stacked-ensemble serving is requested
    (``RAFIKI_TPU_SERVING_STACKED``, default on — stacking is a pure
    dispatch-count win gated by the congruence probe, and parity is
    pinned by tests). Unrecognized spellings fail SAFE to **off** with
    a warning: for a perf feature the behavior-correct fallback is the
    per-member path a typo'd rollback was reaching for (NodeConfig
    validation still rejects typos loudly)."""
    if raw is None:
        raw = os.environ.get(STACKED_ENV, "on")
    raw = raw.strip().lower()
    if raw in ("0", "off", "false", "no"):
        return False
    if raw in STACKED_SPELLINGS:  # the remaining on-spellings
        return True
    import logging

    logging.getLogger(__name__).warning(
        "%s=%r is not one of on/off; failing safe to per-member "
        "serving", STACKED_ENV, raw)
    return False


#: (wire_bytes counter | None, host_copies counter | None); resolved at
#: first use under the lock, then read lock-free.
_state: Optional[Tuple] = None
_quant_counter = None
#: (dispatch counter, dispatches-per-query gauge) | (None, None);
#: lazy own family like the quant counter — registered only when a
#: stacked-capable ensemble actually serves AND the knob is on, so a
#: stacked-off process (the bench A/B's off side) exposes ZERO stacked
#: series.
_stacked_state: Optional[Tuple] = None
_lock = threading.Lock()


def _counters() -> Tuple:
    global _state
    # rta: disable=RTA101 double-checked init: the bare read is the fast path; the write re-checks under _lock
    s = _state
    if s is None:
        with _lock:
            s = _state
            if s is None:
                if packed_wire_mode() != "off" \
                        and _metrics.metrics_enabled():
                    reg = _metrics.registry()
                    s = (
                        reg.counter(
                            "rafiki_tpu_serving_wire_bytes_total",
                            "Estimated serialized serving payload "
                            "bytes (format=packed|perquery, "
                            "direction=scatter|reply)"),
                        reg.counter(
                            "rafiki_tpu_serving_host_copies_total",
                            "Per-tensor host copies on the serving "
                            "path (site=encode|decode|stack|pad|"
                            "assemble)"),
                    )
                else:
                    s = (None, None)
                _state = s
    return s


def counting() -> bool:
    """Whether the wire/copies family is live — callers that must
    COMPUTE a byte estimate check this first so a disabled plane pays
    nothing."""
    return _counters()[0] is not None


def count_bytes(fmt: str, direction: str, nbytes: int) -> None:
    c = _counters()[0]
    if c is not None and nbytes > 0:
        # rta: disable=RTA301 format/direction are a 2x2 fixed vocabulary (packed|perquery x scatter|reply); the family is process-global and deliberately immortal
        c.inc(nbytes, format=fmt, direction=direction)


def count_copies(site: str, n: int = 1) -> None:
    c = _counters()[1]
    if c is not None and n > 0:
        # rta: disable=RTA301 site is the fixed encode|decode|stack|pad|assemble vocabulary; process-global family, deliberately immortal
        c.inc(n, site=site)


def count_quant(n: int, mode: str) -> None:
    """Queries served by a quantized model. Lazy own family: a process
    that never serves quantized registers nothing (the zero-new-series
    guard in tests/test_wire_codec.py pins this)."""
    global _quant_counter
    if n <= 0 or not mode:
        return
    # rta: disable=RTA101 double-checked init: the bare read is the fast path; the write re-checks under _lock
    c = _quant_counter
    if c is None:
        with _lock:
            c = _quant_counter
            if c is None:
                if not _metrics.metrics_enabled():
                    return
                c = _metrics.registry().counter(
                    "rafiki_tpu_serving_quant_total",
                    "Queries served by a quantized ensemble model "
                    "(mode=int8)")
                _quant_counter = c
    # rta: disable=RTA301 mode is the fixed quant vocabulary (int8); registered only while quantized serving is live, deliberately immortal
    c.inc(n, mode=mode)


def _stacked_counters() -> Tuple:
    global _stacked_state
    # rta: disable=RTA101 double-checked init: the bare read is the fast path; the write re-checks under _lock
    s = _stacked_state
    if s is None:
        with _lock:
            s = _stacked_state
            if s is None:
                if stacked_mode() and _metrics.metrics_enabled():
                    reg = _metrics.registry()
                    s = (
                        reg.counter(
                            "rafiki_tpu_serving_stacked_dispatch_total",
                            "Ensemble-burst device dispatches on a "
                            "stacked-capable worker (mode=stacked: one "
                            "vmapped program served the whole member "
                            "group; mode=fallback: per-member "
                            "dispatches of a burst that could not ride "
                            "the stacked program)"),
                        reg.gauge(
                            "rafiki_tpu_serving_dispatches_per_query_ratio",
                            "Device dispatches per served query of the "
                            "last ensemble burst (stacked mode: "
                            "1/queries; per-member fallback: "
                            "members/queries)"),
                    )
                else:
                    s = (None, None)
                _stacked_state = s
    return s


def count_stacked_dispatch(mode: str, n: int = 1) -> None:
    """``mode="stacked"``: one vmapped dispatch served the whole
    member group; ``mode="fallback"``: per-member dispatches of a
    burst a stacked-capable worker had to serve the legacy way."""
    c = _stacked_counters()[0]
    if c is not None and n > 0:
        # No RTA301 waiver needed: the module's one `mode` finding
        # anchors at count_quant's earlier inc, waived there (fixed
        # vocabularies both).
        c.inc(n, mode=mode)


def observe_dispatches_per_query(dispatches: int, queries: int) -> None:
    g = _stacked_counters()[1]
    if g is not None and queries > 0:
        g.set(dispatches / queries)


def reset_for_tests() -> None:
    """Drop the cached enabled-state so a test that flips
    ``RAFIKI_TPU_SERVING_PACKED_WIRE`` / ``RAFIKI_TPU_METRICS`` sees
    its env take effect (production resolves once, by design)."""
    global _state, _quant_counter, _stacked_state
    with _lock:
        _state = None
        _quant_counter = None
        _stacked_state = None
