"""Trial-lifecycle phase metrics, folded into the unified registry.

The trial hot loop (propose -> load -> stage -> train -> eval ->
persist) is where the training plane's trials/hour lives, and BENCH_r05
showed it almost entirely host-bound (``chip_util ~ 0`` for the trials
config). These series make the breakdown measurable the same way the
serving stage histogram did for the frontend:

- ``rafiki_tpu_trial_phase_seconds{phase=}`` — wall time per phase.
  ``propose``/``train``/``eval``/``persist`` are recorded by the
  TrialRunner around the whole lifecycle step; ``load`` (dataset parse
  from disk) and ``stage`` (full-dataset host->device transfer) are
  SUB-SPANS recorded inside ``model.train()``/``model.evaluate()`` —
  they are contained in the train/eval phases, not additive with them.
  With the residency caches warm, load+stage collapse to ~0 for trial
  2..N of a sub-train-job.
- ``rafiki_tpu_trial_dataset_cache_total{event=hit|miss|evict}`` and
  ``rafiki_tpu_trial_stage_cache_total{event=hit|miss|evict}`` — the
  host dataset cache (``model/dataset.py``) and device staging cache
  (``model/jax_model.py``) hit/miss/eviction counters. Trial 2..N of a
  job performing ZERO disk loads and ZERO full-dataset H2D shows up as
  misses staying flat while hits grow (the bench's regression check).
- ``rafiki_tpu_trial_dataset_cache_bytes`` /
  ``rafiki_tpu_trial_stage_cache_bytes`` — current cache occupancy
  against the ``RAFIKI_TPU_DATASET_CACHE_BYTES`` /
  ``RAFIKI_TPU_STAGE_CACHE_BYTES`` budgets.

Stdlib-only (this module is imported by ``model/dataset.py``, which
must stay importable without jax). Labels are bounded: phase names and
cache event kinds only — deliberately NOT per-trial, so the families
never need per-trial series cleanup and the bench can read cumulative
sums across a whole window.
"""

from __future__ import annotations

from typing import Dict, Optional

from . import metrics

PHASES = ("propose", "load", "stage", "train", "eval", "persist")

#: Trial phases span four orders of magnitude more than a bus push:
#: a warm load/stage is sub-millisecond, a real train phase minutes.
PHASE_BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                 10.0, 30.0, 60.0, 300.0, 1800.0)

_m: Optional[Dict[str, object]] = None


def _reg() -> Dict[str, object]:
    global _m
    if _m is None:
        r = metrics.registry()
        _m = {
            "phase": r.histogram(
                "rafiki_tpu_trial_phase_seconds",
                "Wall time of one trial-lifecycle phase (phase="
                "propose|load|stage|train|eval|persist; load/stage are "
                "sub-spans of train/eval)", buckets=PHASE_BUCKETS),
            "dataset_cache": r.counter(
                "rafiki_tpu_trial_dataset_cache_total",
                "Host dataset cache events (event=hit|miss|evict)"),
            "stage_cache": r.counter(
                "rafiki_tpu_trial_stage_cache_total",
                "Device staging cache events (event=hit|miss|evict)"),
            "dataset_cache_bytes": r.gauge(
                "rafiki_tpu_trial_dataset_cache_bytes",
                "Bytes held by the host dataset cache"),
            "stage_cache_bytes": r.gauge(
                "rafiki_tpu_trial_stage_cache_bytes",
                "Bytes held by the device staging cache"),
        }
    return _m


def observe_phase(phase: str, seconds: float) -> None:
    """Record one phase duration. Always-on cheap (one histogram
    observe); ``RAFIKI_TPU_METRICS=0`` disables it wholesale."""
    if metrics.metrics_enabled():
        # rta: disable=RTA301 phase is drawn from the fixed PHASES tuple; deliberately immortal (module docstring)
        _reg()["phase"].observe(seconds, phase=phase)


def cache_event(cache: str, event: str, n: int = 1) -> None:
    """``cache`` is ``"dataset"`` or ``"stage"``; ``event`` one of
    hit/miss/evict."""
    if metrics.metrics_enabled():
        # rta: disable=RTA301 event is hit|miss|evict; deliberately immortal (module docstring)
        _reg()[f"{cache}_cache"].inc(n, event=event)


def set_cache_bytes(cache: str, n_bytes: int) -> None:
    if metrics.metrics_enabled():
        _reg()[f"{cache}_cache_bytes"].set(n_bytes)


def cache_counts(cache: str) -> Dict[str, int]:
    """Current {event: count} for one cache family — what the bench's
    zero-disk-load / zero-H2D regression check reads."""
    m = _reg()[f"{cache}_cache"]
    return {labels.get("event", ""): int(v) for labels, v in m.samples()}


def phase_totals() -> Dict[str, Dict[str, float]]:
    """{phase: {"sum": seconds, "count": n}} — snapshot-diffable, which
    is how ``bench.py --config trials`` derives its per-trial phase
    breakdown."""
    h = _reg()["phase"]
    return {p: {"sum": h.sum(phase=p), "count": h.count(phase=p)}
            for p in PHASES}
