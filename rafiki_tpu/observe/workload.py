"""Workload recorder: the capacity engine's trace of production load.

One JSONL line per ``/predict`` request at the predictor edge — the
arrival record the trace-replay capacity engine (``observe/replay.py``,
``admin/capacity.py``, docs/capacity.md) re-drives against a modeled or
live fleet. Each record captures what the EDGE honestly knows:

- ``off_s``   arrival offset (seconds) from the recorder's epoch (the
  first committed request of this process), plus the absolute wall
  clock ``t`` so multi-process segments can be merged;
- ``tenant``  the HASHED tenant key (``attribution.tenant_key``; never
  the raw client header) or null — replay preserves the tenant mix
  without carrying identities;
- ``job`` / ``bins``  the inference job and the serving-bin vector the
  ensemble scattered across (best-effort: the predictor's most recent
  shard plan);
- ``n`` / ``size``  query count and its power-of-two size class;
- outcome: ``status`` (200 | 429), ``queue_ms`` (admission wait, when
  the micro-batcher dispatched the request), ``compute_ms`` (the
  remainder of the edge duration), ``dur_ms``, and the backpressure
  ``reason`` on 429.

Gating is the r11 disabled-means-free discipline, cloned from the
attribution ledger: ``RAFIKI_TPU_WORKLOAD_RECORD`` (a NodeConfig knob,
default off) is resolved ONCE at first use — off means every hook site
pays one None check and a scrape shows ZERO ``rafiki_tpu_workload_*``
series. The store is the span store's segment discipline in miniature:
the active ``workload.jsonl`` (append, whole lines) rolls to ``.1`` at
``RAFIKI_TPU_WORKLOAD_MAX_MB``, generations shift ``.k`` → ``.k+1``
bounded by ``RAFIKI_TPU_WORKLOAD_RETAIN_SEGMENTS`` — a recorder left on
for a week cannot fill the disk. No sidecar index: replay reads
segments whole, oldest-first, exactly once per simulation.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional

_log = logging.getLogger(__name__)

WORKLOAD_ENV = "RAFIKI_TPU_WORKLOAD_RECORD"
WORKLOAD_MAX_MB_ENV = "RAFIKI_TPU_WORKLOAD_MAX_MB"
WORKLOAD_RETAIN_SEGMENTS_ENV = "RAFIKI_TPU_WORKLOAD_RETAIN_SEGMENTS"

WORKLOAD_FILE = "workload.jsonl"

_lock = threading.Lock()
# None = unresolved; (None,) = resolved off; (_Recorder,) = resolved on.
_state: Optional[tuple] = None
# Sink directory, set by configure() alongside trace.configure — the
# recorder is dormant (records dropped) until both the env gate and a
# log dir are present.
_log_dir: Optional[str] = None


def enabled(raw: Optional[str] = None) -> bool:
    """Truthiness of the workload-record env gate (same spellings as
    the attribution ledger's)."""
    if raw is None:
        raw = os.environ.get(WORKLOAD_ENV, "")
    return raw.strip().lower() not in ("", "0", "false", "no", "off")


def configure(log_dir: Optional[str]) -> None:
    """Point the recorder's sink at ``<log_dir>/workload.jsonl``
    (called next to ``trace.configure`` — resident platform startup and
    the subprocess service entrypoint). ``None``/"" parks the sink."""
    global _log_dir
    with _lock:
        rec = _state[0] if _state is not None else None
        _log_dir = log_dir or None
        if rec is not None:
            rec.repoint(_log_dir)


def configured() -> bool:
    # rta: disable=RTA101 lock-free liveness probe; a reference read is GIL-atomic
    return _log_dir is not None


def _max_bytes() -> int:
    try:
        return int(float(os.environ.get(WORKLOAD_MAX_MB_ENV, "64")
                         or 64) * 1024 * 1024)
    except ValueError:
        return 64 * 1024 * 1024


def retain_segments() -> int:
    try:
        return max(1, int(os.environ.get(WORKLOAD_RETAIN_SEGMENTS_ENV,
                                         "4") or 4))
    except ValueError:
        return 4


class _Recorder:
    """The resolved-on state: sink handle + the request counter family.
    All methods are best-effort — recording must never fail a serve."""

    def __init__(self, log_dir: Optional[str]):
        self._sink_lock = threading.Lock()
        self._path = (os.path.join(log_dir, WORKLOAD_FILE)
                      if log_dir else None)
        self._file = None
        # Offset epoch: the wall clock of the first committed request.
        # Replay treats off_s as the arrival timeline, so one process's
        # segment is self-consistent even across sink rolls.
        self._t0: Optional[float] = None
        self._m_requests = None
        from . import metrics as _metrics

        if _metrics.metrics_enabled():
            self._m_requests = _metrics.registry().counter(
                "rafiki_tpu_workload_requests_total",
                "Requests captured by the workload recorder "
                "(status=ok|backpressure|error)")

    def repoint(self, log_dir: Optional[str]) -> None:
        with self._sink_lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None
            self._path = (os.path.join(log_dir, WORKLOAD_FILE)
                          if log_dir else None)

    def commit(self, req: Dict[str, Any], status: int, dur_s: float,
               reason: str = "", bins: Optional[Iterable] = None,
               ) -> None:
        wall = time.time()
        if self._t0 is None:
            self._t0 = wall
        n = int(req.get("n", 1) or 1)
        queue_ms = float(req.get("queue_ms", 0.0) or 0.0)
        dur_ms = dur_s * 1e3
        record = {
            "off_s": round(max(0.0, wall - self._t0), 6),
            "t": round(wall, 3),
            "job": req.get("job", ""),
            "tenant": req.get("tenant"),
            "n": n,
            "size": size_class(n),
            "queue_ms": round(queue_ms, 3),
            "compute_ms": round(max(0.0, dur_ms - queue_ms), 3),
            "dur_ms": round(dur_ms, 3),
            "status": int(status),
        }
        if reason:
            record["reason"] = str(reason)[:40]
        if bins:
            record["bins"] = sorted(str(b)[:12] for b in bins)
        self._write(json.dumps(record, separators=(",", ":")) + "\n")
        if self._m_requests is not None:
            label = ("ok" if status == 200 else
                     "backpressure" if status == 429 else "error")
            self._m_requests.inc(status=label)

    def _write(self, line: str) -> None:
        with self._sink_lock:
            if self._path is None:
                return
            try:
                if self._file is None or self._file.closed:
                    os.makedirs(os.path.dirname(self._path) or ".",
                                exist_ok=True)
                    # rta: disable=RTA102 the sink lock guards the handle itself; the lazy open is the bind it serializes (trace._write_lines idiom)
                    self._file = open(self._path, "a", encoding="utf-8")
                self._file.write(line)
                self._file.flush()
                # Append mode: tell() is the file size (the span
                # store's size-cap pattern, trace._write_lines).
                if self._file.tell() > _max_bytes():
                    self._file.close()
                    self._file = None
                    _roll_segments(self._path)
            except OSError:  # sink dir vanished (teardown); drop
                self._file = None

    def close(self) -> None:
        with self._sink_lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None
        if self._m_requests is not None:
            self._m_requests.remove()


def _roll_segments(path: str) -> None:
    """Shift the generation chain (``.k`` → ``.k+1``; the one that
    would pass the count bound is deleted) and freeze the active file
    as ``.1`` — the span store's roll, minus the sidecar index."""
    n = retain_segments()
    try:
        os.remove(f"{path}.{n}")
    except OSError:
        pass
    for k in range(n - 1, 0, -1):
        src = f"{path}.{k}"
        if os.path.exists(src):
            try:
                os.replace(src, f"{path}.{k + 1}")
            except OSError:
                pass
    try:
        os.replace(path, f"{path}.1")
    except OSError:
        pass


def _recorder() -> Optional[_Recorder]:
    """Resolve the env gate ONCE (attribution's ``_families`` shape):
    the off path after resolution is a tuple-load and a None check."""
    global _state
    # rta: disable=RTA101 double-checked init: the bare read is the fast path; the write re-checks under _lock
    s = _state
    if s is None:
        with _lock:
            if _state is None:
                _state = ((_Recorder(_log_dir),) if enabled()
                          else (None,))
            s = _state
    return s[0]


def active() -> bool:
    """One cheap check for hook sites (and their construction-time
    snapshots): is the recorder on?"""
    return _recorder() is not None


def size_class(n: int) -> int:
    """Power-of-two size class of a query count (1, 2, 4, 8, ...) —
    the coarse request-size vocabulary replay bins arrivals by."""
    return 1 << max(0, math.ceil(math.log2(max(1, int(n)))))


def open_request(job: str, tenant: Optional[str],
                 n: int) -> Optional[Dict[str, Any]]:
    """Start one request's record at the edge, or None when the
    recorder is off/dormant. The returned dict rides down the dispatch
    path so the micro-batcher can annotate the admission wait
    (``queue_ms``) before :func:`commit` seals the line."""
    rec = _recorder()
    if rec is None:
        return None
    return {"job": str(job)[:12], "tenant": tenant, "n": int(n)}


def note_queue_wait(req: Optional[Dict[str, Any]],
                    wait_s: float) -> None:
    """Micro-batcher annotation: this request's admission wait. A plain
    dict store — the batcher thread writes strictly before the edge
    thread's commit (results only return after dispatch)."""
    if req is not None:
        req["queue_ms"] = round(max(0.0, wait_s) * 1e3, 3)


def commit(req: Optional[Dict[str, Any]], status: int, dur_s: float,
           reason: str = "", bins: Optional[Iterable] = None) -> None:
    """Seal and write one request's record (no-op for ``req=None``,
    the off path)."""
    if req is None:
        return
    rec = _recorder()
    if rec is not None:
        rec.commit(req, status, dur_s, reason=reason, bins=bins)


# --- Readers (replay / capacity CLI) ----------------------------------

def workload_path(log_dir: str) -> str:
    return os.path.join(log_dir, WORKLOAD_FILE)


def segment_paths(log_dir: str) -> List[str]:
    """Store segments oldest-first (rolled ``.N`` .. ``.1``, then the
    active file) — the span store's reader order."""
    path = workload_path(log_dir)
    out = [f"{path}.{k}"
           for k in range(retain_segments(), 0, -1)
           if os.path.exists(f"{path}.{k}")]
    if os.path.exists(path):
        out.append(path)
    return out


def read_segment(path: str) -> List[Dict[str, Any]]:
    """One segment's records, in file order; torn/corrupt lines are
    skipped, never fatal."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path, "rb") as f:
            for raw in f:
                if not raw.endswith(b"\n"):
                    break  # torn tail write
                try:
                    rec = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and "off_s" in rec:
                    out.append(rec)
    except OSError:
        return out
    return out


def load(source: str) -> List[Dict[str, Any]]:
    """A recorded workload trace as one arrival-ordered list.
    ``source`` is either a single trace file or a log dir holding the
    segmented store. Offsets are re-based onto one timeline via the
    absolute ``t`` stamps (segments from different processes / rolls
    each carry their own ``off_s`` epoch)."""
    paths = ([source] if os.path.isfile(source)
             else segment_paths(source))
    records: List[Dict[str, Any]] = []
    for p in paths:
        records.extend(read_segment(p))
    if not records:
        return []
    t0 = min(r.get("t", 0.0) for r in records)
    for r in records:
        r["off_s"] = round(max(0.0, r.get("t", t0) - t0), 6)
    records.sort(key=lambda r: (r["off_s"], r.get("tenant") or ""))
    return records


def reset_for_tests() -> None:
    """Drop the resolved gate (and its series/handle) so a test can
    flip the env and re-resolve — the attribution seam."""
    global _state, _log_dir
    with _lock:
        rec = _state[0] if _state is not None else None
        _state = None
        _log_dir = None
    if rec is not None:
        rec.close()
