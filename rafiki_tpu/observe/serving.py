"""Serving-path counters: queue depth, coalescing, per-stage latency.

The micro-batcher (``rafiki_tpu.predictor.batcher``) turns many
concurrent ``/predict`` requests into few scatter-gather super-batches;
whether that is WORKING is invisible from throughput alone. These
counters make it measurable: how full the admission queue runs, how many
requests each super-batch coalesced (the fill ratio), how long each
stage (fill wait / scatter / gather) takes, and how often backpressure
fired. The predictor frontend exposes a snapshot on ``GET /stats`` and
the ``serving-concurrent`` bench records it next to QPS, so a throughput
win can be attributed to coalescing rather than asserted.

Same spirit as the MFU meter in ``observe.profiling``: cheap enough to
always be on (a lock and a few adds per super-batch, not per query).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class _StageClock:
    """Count / total / max seconds for one pipeline stage."""

    __slots__ = ("count", "total_s", "max_s")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def record(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        self.max_s = max(self.max_s, seconds)

    def snapshot(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "mean_ms": round(self.total_s / self.count * 1e3, 3)
            if self.count else 0.0,
            "max_ms": round(self.max_s * 1e3, 3),
        }


class ServingStats:
    """Thread-safe counters for one predictor frontend.

    ``requests``/``queries`` count admissions; ``rejected`` counts
    backpressure 429s; ``batches``/``batched_requests``/``batched_queries``
    describe dispatched super-batches (their ratio is the coalescing
    factor); ``fill``/``scatter``/``gather`` are per-super-batch stage
    clocks; ``queue_depth``/``inflight`` are point-in-time gauges set by
    the batcher.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.requests = 0
        self.queries = 0
        self.rejected = 0
        self.batches = 0
        self.batched_requests = 0
        self.batched_queries = 0
        self.queue_depth = 0        # queries currently admitted, unsent
        self.queue_depth_peak = 0
        self.inflight = 0           # super-batches scattered, ungathered
        self.inflight_peak = 0
        self.fill = _StageClock()
        self.scatter = _StageClock()
        self.gather = _StageClock()

    # --- Admission ---

    def admitted(self, n_queries: int) -> None:
        with self._lock:
            self.requests += 1
            self.queries += n_queries

    def backpressured(self) -> None:
        with self._lock:
            self.rejected += 1

    def set_queue_depth(self, n_queries: int) -> None:
        with self._lock:
            self.queue_depth = n_queries
            self.queue_depth_peak = max(self.queue_depth_peak, n_queries)

    # --- Super-batch lifecycle ---

    def dispatched(self, n_requests: int, n_queries: int,
                   fill_s: float, scatter_s: float,
                   inflight: Optional[int] = None) -> None:
        with self._lock:
            self.batches += 1
            self.batched_requests += n_requests
            self.batched_queries += n_queries
            self.fill.record(fill_s)
            self.scatter.record(scatter_s)
            if inflight is not None:
                self.inflight = inflight
                self.inflight_peak = max(self.inflight_peak, inflight)

    def gathered(self, gather_s: float,
                 inflight: Optional[int] = None) -> None:
        with self._lock:
            self.gather.record(gather_s)
            if inflight is not None:
                self.inflight = inflight

    # --- Reporting ---

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "requests": self.requests,
                "queries": self.queries,
                "rejected": self.rejected,
                "batches": self.batches,
                "batched_requests": self.batched_requests,
                "batched_queries": self.batched_queries,
                # requests folded into each super-batch on average: 1.0
                # = no cross-request coalescing happened, N = N requests
                # rode one scatter-gather.
                "coalescing_factor": round(
                    self.batched_requests / self.batches, 3)
                if self.batches else None,
                "mean_batch_queries": round(
                    self.batched_queries / self.batches, 2)
                if self.batches else None,
                "queue_depth": self.queue_depth,
                "queue_depth_peak": self.queue_depth_peak,
                "inflight": self.inflight,
                "inflight_peak": self.inflight_peak,
                "fill": self.fill.snapshot(),
                "scatter": self.scatter.snapshot(),
                "gather": self.gather.snapshot(),
            }
