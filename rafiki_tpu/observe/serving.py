"""Serving-path counters, folded into the unified metrics registry.

The micro-batcher (``rafiki_tpu.predictor.batcher``) turns many
concurrent ``/predict`` requests into few scatter-gather super-batches;
whether that is WORKING is invisible from throughput alone. These
counters make it measurable: how full the admission queue runs, how many
requests each super-batch coalesced (the fill ratio), how long each
stage (fill wait / scatter / gather) takes, and how often backpressure
fired.

r6 grew this as a bespoke dict; it is now a facade over
``observe.metrics`` — every number lives in the process registry under
``rafiki_tpu_serving_*`` (labeled by the frontend's short service id,
so two predictors in one resident-runner process stay separable) and
``GET /stats`` and ``GET /metrics`` read the SAME source. ``snapshot``
keeps its r6 shape (the bench and dashboard consume it) and adds
bucket-derived p50/p95 per stage.

Still cheap enough to always be on: a lock and a few adds per
super-batch, not per query.
"""

from __future__ import annotations

import threading
import uuid
from typing import Dict, List, Optional

from . import metrics

_STAGES = ("fill", "scatter", "gather")


def _reg():
    r = metrics.registry()
    return {
        "requests": r.counter(
            "rafiki_tpu_serving_requests_total",
            "Requests admitted by the serving frontend"),
        "queries": r.counter(
            "rafiki_tpu_serving_queries_total",
            "Queries admitted by the serving frontend"),
        "rejected": r.counter(
            "rafiki_tpu_serving_rejected_total",
            "Requests bounced with 429 backpressure"),
        "backpressure": r.counter(
            "rafiki_tpu_serving_backpressure_total",
            "429 rejections split by reason "
            "(reason=queue_full|client_share)"),
        "batches": r.counter(
            "rafiki_tpu_serving_batches_total",
            "Super-batches dispatched"),
        "batched_requests": r.counter(
            "rafiki_tpu_serving_batched_requests_total",
            "Requests carried by dispatched super-batches"),
        "batched_queries": r.counter(
            "rafiki_tpu_serving_batched_queries_total",
            "Queries carried by dispatched super-batches"),
        "queue_depth": r.gauge(
            "rafiki_tpu_serving_queue_depth_queries",
            "Queries currently admitted and unsent"),
        "inflight": r.gauge(
            "rafiki_tpu_serving_inflight_batches",
            "Super-batches scattered but not yet gathered"),
        "stage": r.histogram(
            "rafiki_tpu_serving_stage_seconds",
            "Per-super-batch stage latency (stage=fill|scatter|gather)"),
        "fill_window": r.gauge(
            "rafiki_tpu_serving_fill_window_seconds",
            "Load-adaptive fill window the last super-batch filled "
            "under"),
    }


class ServingStats:
    """Thread-safe counters for one predictor frontend, backed by the
    process metrics registry under a per-instance ``service`` label.

    ``requests``/``queries`` count admissions; ``rejected`` counts
    backpressure 429s; ``batches``/``batched_requests``/``batched_queries``
    describe dispatched super-batches (their ratio is the coalescing
    factor); ``fill``/``scatter``/``gather`` land in the
    ``rafiki_tpu_serving_stage_seconds`` histogram;
    ``queue_depth``/``inflight`` are point-in-time gauges set by the
    batcher. Peaks and per-stage maxima are per-instance extras (a
    Prometheus gauge has no native peak), kept here for ``snapshot``.
    """

    def __init__(self, service: Optional[str] = None):
        # The label must be per-instance unique within the process, or
        # two frontends' series would merge in the registry and each
        # instance's snapshot would read the other's traffic.
        self.service = service or f"svc-{uuid.uuid4().hex[:8]}"
        self._m = _reg()
        self._lock = threading.Lock()
        self.queue_depth_peak = 0
        self.inflight_peak = 0
        self._stage_max: Dict[str, float] = {s: 0.0 for s in _STAGES}

    # --- Registry-backed reads (keep the r6 attribute surface) ---

    def _count(self, key: str) -> int:
        return int(self._m[key].value(service=self.service))

    @property
    def requests(self) -> int:
        return self._count("requests")

    @property
    def queries(self) -> int:
        return self._count("queries")

    @property
    def rejected(self) -> int:
        return self._count("rejected")

    @property
    def batches(self) -> int:
        return self._count("batches")

    @property
    def batched_requests(self) -> int:
        return self._count("batched_requests")

    @property
    def batched_queries(self) -> int:
        return self._count("batched_queries")

    @property
    def queue_depth(self) -> int:
        return self._count("queue_depth")

    @property
    def inflight(self) -> int:
        return self._count("inflight")

    # --- Admission ---

    def admitted(self, n_queries: int) -> None:
        self._m["requests"].inc(service=self.service)
        self._m["queries"].inc(n_queries, service=self.service)

    def backpressured(self, reason: str = "queue_full") -> None:
        self._m["rejected"].inc(service=self.service)
        self._m["backpressure"].inc(service=self.service, reason=reason)

    def set_queue_depth(self, n_queries: int) -> None:
        self._m["queue_depth"].set(n_queries, service=self.service)
        with self._lock:
            self.queue_depth_peak = max(self.queue_depth_peak, n_queries)

    # --- Super-batch lifecycle ---

    def dispatched(self, n_requests: int, n_queries: int,
                   fill_s: float, scatter_s: float,
                   inflight: Optional[int] = None,
                   fill_window: Optional[float] = None) -> None:
        self._m["batches"].inc(service=self.service)
        self._m["batched_requests"].inc(n_requests, service=self.service)
        self._m["batched_queries"].inc(n_queries, service=self.service)
        self._observe_stage("fill", fill_s)
        self._observe_stage("scatter", scatter_s)
        if fill_window is not None:
            self._m["fill_window"].set(fill_window, service=self.service)
        if inflight is not None:
            self._m["inflight"].set(inflight, service=self.service)
            with self._lock:
                self.inflight_peak = max(self.inflight_peak, inflight)

    def gathered(self, gather_s: float,
                 inflight: Optional[int] = None) -> None:
        self._observe_stage("gather", gather_s)
        if inflight is not None:
            self._m["inflight"].set(inflight, service=self.service)

    def _observe_stage(self, stage: str, seconds: float) -> None:
        self._m["stage"].observe(seconds, service=self.service,
                                 stage=stage)
        with self._lock:
            self._stage_max[stage] = max(self._stage_max[stage], seconds)

    def close(self) -> None:
        """Drop this frontend's series from the shared registry. The
        label is per-instance, so a long-lived resident runner that
        deploys/stops predictors repeatedly would otherwise grow the
        registry (and every /metrics payload) one label set per
        deployment, forever."""
        for m in self._m.values():
            m.remove(service=self.service)

    # --- Reporting ---

    def _stage_snapshot(self, stage: str) -> Dict[str, float]:
        hist = self._m["stage"]
        count = hist.count(service=self.service, stage=stage)
        total = hist.sum(service=self.service, stage=stage)
        with self._lock:  # written under _lock by _observe_stage
            stage_max = self._stage_max[stage]

        def ms(v: Optional[float]) -> float:
            return round(v * 1e3, 3) if v is not None else 0.0

        return {
            "count": count,
            "mean_ms": ms(total / count) if count else 0.0,
            "max_ms": ms(stage_max),
            "p50_ms": ms(hist.percentile(0.5, service=self.service,
                                         stage=stage)) if count else 0.0,
            "p95_ms": ms(hist.percentile(0.95, service=self.service,
                                         stage=stage)) if count else 0.0,
        }

    def snapshot(self) -> Dict[str, object]:
        batches = self.batches
        batched_requests = self.batched_requests
        batched_queries = self.batched_queries
        with self._lock:  # peaks are written under _lock
            queue_depth_peak = self.queue_depth_peak
            inflight_peak = self.inflight_peak
        return {
            "service": self.service,
            "requests": self.requests,
            "queries": self.queries,
            "rejected": self.rejected,
            "batches": batches,
            "batched_requests": batched_requests,
            "batched_queries": batched_queries,
            # requests folded into each super-batch on average: 1.0
            # = no cross-request coalescing happened, N = N requests
            # rode one scatter-gather.
            "coalescing_factor": round(batched_requests / batches, 3)
            if batches else None,
            "mean_batch_queries": round(batched_queries / batches, 2)
            if batches else None,
            "rejected_by_reason": {
                labels["reason"]: int(v)
                for labels, v in self._m["backpressure"].samples()
                if labels.get("service") == self.service},
            "queue_depth": self.queue_depth,
            "queue_depth_peak": queue_depth_peak,
            "inflight": self.inflight,
            "inflight_peak": inflight_peak,
            # The last dispatched super-batch's adaptive fill window
            # (seconds) — converges toward the max under load, the min
            # under trickle.
            "fill_window_s": self._m["fill_window"].value(
                service=self.service),
            "fill": self._stage_snapshot("fill"),
            "scatter": self._stage_snapshot("scatter"),
            "gather": self._stage_snapshot("gather"),
        }


__all__: List[str] = ["ServingStats"]
