"""Serving attribution ledger: per-bin / per-tenant request accounting.

Rafiki is a multi-tenant MLaaS, but until r17 nothing attributed
requests, queue time, or device time to a *bin* (a served trial
ensemble member) or a *tenant* (a client key) — the autoscaler read
per-JOB aggregates and the multi-tenant SLO plane had no signal basis
at all. This module is that ledger:

Frontend side (the micro-batcher / predictor scatter):

- ``rafiki_tpu_serving_bin_queries_total{service, bin}`` — queries
  scattered toward each trial bin (every query fans to every serving
  bin, so per-bin totals exceed admissions by design);
- ``rafiki_tpu_serving_bin_queue_seconds_total{service, bin}`` —
  admission-queue wait (fill time) accrued by the work bound for each
  bin: a super-batch that waited ``w`` seconds charges ``w`` to every
  bin it scatters to;
- ``rafiki_tpu_serving_bin_rejected_total{service, reason}`` — 429
  backpressure (pre-bin-binding, so no bin label: a rejected request
  never reached a plan).

Worker side (``InferenceWorker``, which knows its job and bin):

- ``rafiki_tpu_serving_bin_requests_total{job, bin}`` — queries served;
- ``rafiki_tpu_serving_bin_compute_seconds_total{job, bin}`` — burst
  device time (dispatch -> readback);
- ``rafiki_tpu_serving_bin_device_seconds{job, bin, bucket, dtype,
  quant, mode}`` — per-dispatch device time histogram with the serving
  variant breakdown riding the r16 dispatch accounting: ``bucket`` the
  compiled batch bucket (``-`` on the flat path), ``dtype`` the staged
  input dtype, ``quant`` the active quant mode (``-`` unquantized) and
  ``mode`` ``stacked``/``fallback``/``members``/``single``.

Tenant rollup (bounded cardinality):

- ``rafiki_tpu_serving_tenant_requests_total{tenant}`` — requests per
  hashed client key, accounted per request SERVED (a throttled or
  malformed hammer cannot inflate a tenant's count or churn the LRU);
- ``rafiki_tpu_serving_tenant_device_seconds_total{tenant}`` — device
  time prorated over the tenant mix a burst's frames carried (the
  ``_tenant`` bus-envelope carry, injected next to ``_trace``);
- ``rafiki_tpu_serving_tenant_request_seconds{tenant}`` — per-request
  serving latency histogram at the frontend (SERVED requests only) —
  the tenant-scoped latency source the SLO plane's per-tenant p99
  objectives read (observe/slo.py).

The ``tenant`` label is ``blake2b(client_key)[:12]`` — bounded length,
no raw client identifiers in the exposition — and the live tenant set
is an LRU capped at :data:`TENANT_CAP`: evicting a tenant removes its
series, so a rotating-key client cannot grow the registry without
bound.

Gating (the r11 disabled-means-free discipline):
``RAFIKI_TPU_SERVING_ATTRIBUTION`` (NodeConfig ``serving_attribution``,
default OFF) resolves ONCE at first use — disabled means every account
call is one function call + one None check, NO family is ever
registered, and a scrape shows zero ``serving_bin_``/``serving_tenant_``
series. Per-instance lifecycle: a frontend's ``service``-labeled series
drop on its ``stop()`` (``close_service``), a worker's ``(job, bin)``
series drop when its serve loop exits (``close_worker``), and the
process-global tenant rollup is cleared when the LAST attributing owner
closes (``open_owner``/``close_owner`` refcount) — deploy/stop churn
can never grow the scrape payload.
"""

from __future__ import annotations

import collections
import hashlib
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import metrics as _metrics

ATTRIBUTION_ENV = "RAFIKI_TPU_SERVING_ATTRIBUTION"

#: Live-tenant cardinality cap (LRU): the 65th distinct client key
#: evicts (and removes the series of) the least recently seen one.
TENANT_CAP = 64

#: Bus-envelope key for the tenant carry (next to trace's ``_trace``).
#: Old frames lack it, old consumers ignore it — skew degrades to
#: "unattributed", never a failed query.
ENVELOPE_KEY = "_tenant"

#: A super-batch mixes many clients; the envelope carries at most this
#: many ``[tenant, count]`` pairs (largest first — the rest of the
#: burst's device time goes unattributed rather than unbounded).
MAX_ENVELOPE_TENANTS = 8

_lock = threading.Lock()
_state: Optional[Tuple] = None  # dict-of-metrics | (None,) sentinel
_owners = 0
_tenants: "collections.OrderedDict[str, None]" = collections.OrderedDict()


def active() -> bool:
    """Whether the ledger is resolved ON in this process (families
    registered). Cheap enough for per-batch checks; resolves the env
    on first call like every account site."""
    return _families() is not None


def enabled(raw: Optional[str] = None) -> bool:
    """Whether serving attribution is requested (construction-time
    read; the resolved metric families are cached separately)."""
    if raw is None:
        raw = os.environ.get(ATTRIBUTION_ENV, "0")
    return raw.strip().lower() not in ("", "0", "false", "no", "off")


def tenant_key(raw: Optional[str]) -> Optional[str]:
    """Bounded-cardinality tenant label for one client key: a short
    blake2b digest — raw client identifiers (API keys, emails) must
    never appear in the exposition."""
    if not raw:
        return None
    return hashlib.blake2b(str(raw).encode("utf-8", errors="replace"),
                           digest_size=6).hexdigest()


def _clamp(tenant: Any) -> str:
    """The ONE normalization of a tenant label. Our own keys are
    12-hex ``tenant_key`` digests, but the envelope is produced by
    whatever rides the bus — clamping at every boundary keeps the
    label bounded AND keeps the LRU key identical to the series label
    (an eviction that removes a different spelling than was inc'd
    would leak the series forever)."""
    return str(tenant)[:16]


def _families() -> Optional[Dict[str, Any]]:
    """The ledger's metric families, resolved ONCE: None when
    attribution (or metrics) is off — no family registered, zero
    series, one None check per account call."""
    global _state
    # rta: disable=RTA101 double-checked init: the bare read is the fast path; the write re-checks under _lock
    s = _state
    if s is None:
        with _lock:
            s = _state
            if s is None:
                if enabled() and _metrics.metrics_enabled():
                    r = _metrics.registry()
                    fams = {
                        "bin_queries": r.counter(
                            "rafiki_tpu_serving_bin_queries_total",
                            "Queries scattered toward each serving "
                            "trial bin (frontend side)"),
                        "bin_queue": r.counter(
                            "rafiki_tpu_serving_bin_queue_seconds_total",
                            "Admission-queue wait accrued by work "
                            "bound for each bin, seconds"),
                        "bin_rejected": r.counter(
                            "rafiki_tpu_serving_bin_rejected_total",
                            "429 backpressure per attributing frontend "
                            "(reason=queue_full|client_share; no bin — "
                            "a rejected request never reached a plan)"),
                        "bin_requests": r.counter(
                            "rafiki_tpu_serving_bin_requests_total",
                            "Queries served per (job, bin) — worker "
                            "side"),
                        "bin_compute": r.counter(
                            "rafiki_tpu_serving_bin_compute_seconds_total",
                            "Burst device time per (job, bin), "
                            "seconds"),
                        "bin_device": r.histogram(
                            "rafiki_tpu_serving_bin_device_seconds",
                            "Per-dispatch device time with the serving "
                            "variant breakdown (bucket, dtype, quant, "
                            "mode=stacked|fallback|members|single)"),
                        "tenant_requests": r.counter(
                            "rafiki_tpu_serving_tenant_requests_total",
                            "Requests per hashed client key (LRU-"
                            "capped tenant cardinality)"),
                        "tenant_device": r.counter(
                            "rafiki_tpu_serving_tenant_device_seconds_total",
                            "Device seconds prorated over the tenant "
                            "mix the bursts carried"),
                        "tenant_latency": r.histogram(
                            "rafiki_tpu_serving_tenant_request_seconds",
                            "Per-request serving latency per hashed "
                            "client key + frontend service label (the "
                            "SLO plane's tenant-scoped latency "
                            "source; tenant LRU cap/lifecycle shared "
                            "with the rollup counters, service slice "
                            "dropped on frontend stop)"),
                    }
                    s = (fams,)
                else:
                    s = (None,)
                _state = s
    return s[0]


def reset_for_tests() -> None:
    """Drop the resolved state so a test that flips
    ``RAFIKI_TPU_SERVING_ATTRIBUTION`` sees its env take effect
    (production resolves once, by design)."""
    global _state, _owners
    with _lock:
        _state = None
        _owners = 0
        _tenants.clear()


# --- Owner lifecycle --------------------------------------------------

def open_owner() -> None:
    """An attributing service (frontend or worker) came up."""
    global _owners
    if _families() is None:
        return
    with _lock:
        _owners += 1


def close_owner() -> None:
    """An attributing service went away; the LAST one out clears the
    process-global tenant rollup (per-instance series are the owners'
    own ``close_service``/``close_worker`` duty)."""
    global _owners
    fams = _families()
    if fams is None:
        return
    with _lock:
        _owners = max(0, _owners - 1)
        last = _owners == 0
        if last:
            _tenants.clear()
    if last:
        fams["tenant_requests"].remove()
        fams["tenant_device"].remove()
        fams["tenant_latency"].remove()


def close_service(service: str) -> None:
    """Drop one frontend's ``service``-labeled ledger series (the
    tenant latency histogram's slice included)."""
    fams = _families()
    if fams is None:
        return
    for key in ("bin_queries", "bin_queue", "bin_rejected",
                "tenant_latency"):
        fams[key].remove(service=service)
    close_owner()


def drop_worker_bin(job: str, bin_id: str) -> None:
    """Drop one ``(job, bin)`` label set from the worker-side
    families WITHOUT touching the owner refcount — the promote-path
    restack changes a live worker's bin in place, and the old bin's
    series must not outlive the swap (promotion churn may never grow
    the scrape payload). Replicas share the label set, so a sibling
    that keeps serving simply re-creates it on its next burst (a
    counter reset, which every delta consumer here tolerates)."""
    fams = _families()
    if fams is None:
        return
    # Same truncation as account_burst, or the removal never matches.
    job, bin_id = str(job)[:12], str(bin_id)[:12]
    for key in ("bin_requests", "bin_compute", "bin_device"):
        fams[key].remove(job=job, bin=bin_id)


def close_worker(job: str, bin_id: str) -> None:
    """Drop one worker's ``(job, bin)`` ledger series and release its
    owner slot (serve-loop exit)."""
    if _families() is None:
        return
    drop_worker_bin(job, bin_id)
    close_owner()


# --- Tenant LRU -------------------------------------------------------

def _touch_tenant(fams: Dict[str, Any], tenant: str) -> None:
    """LRU-admit one tenant label; caller is about to inc its series.
    Evicting removes the evictee's series from BOTH tenant families."""
    evicted = None
    with _lock:
        if tenant in _tenants:
            _tenants.move_to_end(tenant)
        else:
            _tenants[tenant] = None
            if len(_tenants) > TENANT_CAP:
                evicted, _ = _tenants.popitem(last=False)
    if evicted is not None:
        fams["tenant_requests"].remove(tenant=evicted)
        fams["tenant_device"].remove(tenant=evicted)
        fams["tenant_latency"].remove(tenant=evicted)


# --- Frontend accounting ----------------------------------------------

def account_admitted(tenant: Optional[str], n_requests: int = 1) -> None:
    fams = _families()
    if fams is None or not tenant:
        return
    tenant = _clamp(tenant)
    _touch_tenant(fams, tenant)
    fams["tenant_requests"].inc(n_requests, tenant=tenant)


def account_tenant_latency(tenant: Optional[str], seconds: float,
                           service: str = "") -> None:
    """One SERVED request's end-to-end latency under its tenant label
    (frontend side — the r17 carry "tenant-labeled p99 SLO tracking"
    closed: a tenant-scoped latency objective reads this histogram's
    bucket deltas). Unlike the process-global tenant rollup counters,
    this histogram ALSO carries the frontend's ``service`` label: two
    jobs' frontends sharing one process registry must not fold each
    other's tenant latency into their own SLO instances (the engine
    filters on it). Same LRU admission as the rollup counters, so a
    rotating-key client cannot grow the registry; ``close_service``
    drops the frontend's slice like every other service-labeled
    family."""
    fams = _families()
    if fams is None or not tenant or seconds < 0:
        return
    tenant = _clamp(tenant)
    _touch_tenant(fams, tenant)
    fams["tenant_latency"].observe(seconds, tenant=tenant,
                                   service=service)


def account_rejected(service: str, reason: str) -> None:
    fams = _families()
    if fams is None:
        return
    # reason is the fixed queue_full|client_share vocabulary on the
    # same service-labeled series close_service removes.
    fams["bin_rejected"].inc(service=service, reason=reason)


def account_scatter(service: str, bin_queries: Dict[str, int],
                    queue_wait_s: float = 0.0) -> None:
    """One plan's per-bin query counts (+ the super-batch's admission
    wait, charged to every bin it scatters to)."""
    fams = _families()
    if fams is None:
        return
    for bin_id, n in bin_queries.items():
        if n <= 0:
            continue
        fams["bin_queries"].inc(n, service=service, bin=str(bin_id)[:12])
        if queue_wait_s > 0:
            fams["bin_queue"].inc(queue_wait_s, service=service,
                                  bin=str(bin_id)[:12])


# --- Worker accounting ------------------------------------------------

def account_burst(job: str, bin_id: str, n_queries: int,
                  device_s: float, bucket: Optional[int] = None,
                  dtype: Optional[str] = None, quant: str = "",
                  mode: str = "single") -> None:
    """One served burst's device time, attributed to the worker's
    (job, bin) with the dispatch-variant breakdown."""
    fams = _families()
    if fams is None or n_queries <= 0:
        return
    job, bin_id = str(job)[:12], str(bin_id)[:12]
    fams["bin_requests"].inc(n_queries, job=job, bin=bin_id)
    fams["bin_compute"].inc(max(0.0, device_s), job=job, bin=bin_id)
    fams["bin_device"].observe(
        max(0.0, device_s), job=job, bin=bin_id,
        bucket=str(bucket) if bucket is not None else "-",
        dtype=str(dtype) if dtype else "-",
        quant=quant or "-", mode=mode or "single")


def account_tenant_device(tenants: Iterable[Tuple[str, int]],
                          device_s: float, n_queries: int) -> None:
    """Prorate one burst's device time over the tenant mix its frames
    carried (under-attributes when frames carried no tenant info —
    never fabricates)."""
    fams = _families()
    if fams is None or n_queries <= 0 or device_s <= 0:
        return
    for tenant, count in tenants:
        if not tenant or count <= 0:
            continue
        tenant = _clamp(tenant)
        _touch_tenant(fams, tenant)
        fams["tenant_device"].inc(
            device_s * min(count, n_queries) / n_queries,
            tenant=tenant)


# --- Bus-envelope carry ----------------------------------------------

def inject_tenants(tenants: Optional[List[Tuple[str, int]]],
                   ) -> Optional[List[List[Any]]]:
    """Envelope field for a query frame carrying these requests'
    tenant mix, or None when nothing is attributed (the frame then
    looks exactly like a pre-attribution frame)."""
    if not tenants:
        return None
    merged: Dict[str, int] = {}
    for tenant, count in tenants:
        if tenant and count > 0:
            merged[_clamp(tenant)] = (merged.get(_clamp(tenant), 0)
                                      + int(count))
    if not merged:
        return None
    top = sorted(merged.items(), key=lambda kv: (-kv[1], kv[0]))
    return [[t, n] for t, n in top[:MAX_ENVELOPE_TENANTS]]


def extract_tenants(frame: Any) -> List[Tuple[str, int]]:
    """Pop the tenant envelope off a bus frame dict; old frames and
    malformed envelopes yield ``[]`` — attribution must never fail a
    query."""
    if not isinstance(frame, dict):
        return []
    env = frame.pop(ENVELOPE_KEY, None)
    if not isinstance(env, list):
        return []
    out: List[Tuple[str, int]] = []
    try:
        for tenant, count in env:
            out.append((_clamp(tenant), int(count)))
    except (TypeError, ValueError):
        return []
    return out


def extract_frames_tenants(frames: Iterable[Any],
                           ) -> List[Tuple[str, int]]:
    """Extract + merge tenant counts across a popped burst."""
    merged: Dict[str, int] = {}
    for frame in frames:
        for tenant, count in extract_tenants(frame):
            merged[tenant] = merged.get(tenant, 0) + count
    return sorted(merged.items(), key=lambda kv: (-kv[1], kv[0]))
