"""Deterministic discrete-event replay: a recorded workload against a
modeled fleet, under the REAL control plane.

The capacity engine's middle layer (docs/capacity.md). The recorder
(observe/workload.py) captures what arrived; this module answers the
question operators actually have: *would this policy have survived it?*
A :func:`simulate` run replays a trace's arrivals against a modeled
fleet and executes, on simulated sweeps, the very code production runs:

- ``AutoscalePolicy.decide`` (admin/autoscaler.py) — the same decision
  table, cooldowns, hysteresis band and step bounds, fed synthetic
  :class:`~rafiki_tpu.admin.autoscaler.JobSignals` built from simulated
  queue depth / 429 deltas / completed-request latencies;
- the SLO vocabulary (observe/slo.py) — ``Objective`` / ``Instance`` /
  ``AlertMachine``, so a candidate rules file is judged by the same
  burn-rate state machine the live engine runs.

What is MODELED (the fidelity caveats, honestly): service time. Each
serving bin draws per-batch device time from a :class:`BinModel` —
either an empirical inverse-CDF sample over the live ledger's
``rafiki_tpu_serving_bin_device_seconds`` cumulative buckets, or a
synthetic ``base + per_query * n`` curve with bounded jitter. The
simulator does not model compilation stalls, cache hits, paging or
stragglers; per-bin arrival attribution is uniform (every admitted
request scatters to every bin — the recorder sees the frontend, not the
scatter plan), so ``JobSignals.bins`` stays None and the policy runs
its per-job fallback ordering. Treat absolute numbers as calibrated
estimates (``bench.py --config replay`` measures the gap against a
live stack); treat POLICY COMPARISONS — the regression gate — as the
load-bearing output.

Determinism: one ``random.Random(seed)`` drives every sample, the event
heap breaks time ties by insertion sequence, and nothing reads the wall
clock — the same (trace, fleet, knobs, seed) always yields the same
report, byte for byte. That is what makes a simulation diff reviewable
in CI.
"""

from __future__ import annotations

import heapq
import math
import random
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..admin.autoscaler import (AutoscalePolicy, Decision, JobSignals,
                                JobState, PolicyKnobs)
from . import metrics as _metrics
from . import slo as _slo

#: Ledger family the empirical fleet model is fit from (the r17
#: worker-side per-bin device-time histogram).
FLEET_SOURCE_SERIES = "rafiki_tpu_serving_bin_device_seconds"


# --- Fleet model -------------------------------------------------------

@dataclass(frozen=True)
class BinModel:
    """One serving bin's service-time model.

    ``buckets`` (empirical): cumulative ``[(le_seconds, count), ...]``
    from the live ledger histogram; per-batch service time is an
    inverse-CDF draw with uniform interpolation inside the landing
    bucket. A draw landing in the ``+Inf`` bucket reports 1.5x the last
    finite bound — a known floor, never a fabricated tail.

    ``base_s``/``per_query_s`` (synthetic fallback): affine in the
    batch's query count with ±20% uniform jitter, for canned traces and
    fleets that have no ledger history yet.
    """

    name: str
    buckets: Tuple[Tuple[float, float], ...] = ()
    base_s: float = 0.005
    per_query_s: float = 0.04

    def service_s(self, n_queries: int, rng: random.Random) -> float:
        if self.buckets and self.buckets[-1][1] > 0:
            total = self.buckets[-1][1]
            rank = rng.random() * total
            prev_bound, prev_cum = 0.0, 0.0
            for bound, cum in self.buckets:
                if cum >= rank:
                    if bound == math.inf:
                        return prev_bound * 1.5
                    if cum <= prev_cum:
                        return bound
                    frac = (rank - prev_cum) / (cum - prev_cum)
                    return prev_bound + (bound - prev_bound) * frac
                prev_bound, prev_cum = bound, cum
            return prev_bound
        jitter = 0.8 + 0.4 * rng.random()
        return (self.base_s + self.per_query_s * max(1, n_queries)) \
            * jitter


@dataclass(frozen=True)
class FleetModel:
    """The modeled fleet: one :class:`BinModel` per serving bin."""

    bins: Tuple[BinModel, ...]

    @classmethod
    def synthetic(cls, n_bins: int = 1, base_s: float = 0.005,
                  per_query_s: float = 0.04) -> "FleetModel":
        """Default synthetic fleet. One bin by default: every admitted
        request scatters to EVERY bin (the uniform-attribution caveat
        above) while a scale-up only feeds one, so multi-bin synthetic
        fleets demand a per-bin scaling cadence the per-job step/
        cooldown knobs were never sized for — multi-bin models earn
        their keep when fit from a real ledger, not fabricated."""
        return cls(bins=tuple(
            BinModel(name=f"bin{i}", base_s=base_s,
                     per_query_s=per_query_s) for i in range(n_bins)))

    @classmethod
    def from_trace(cls, trace: Sequence[Dict[str, Any]],
                   name: str = "trace") -> Optional["FleetModel"]:
        """Empirical service-time model from a recorded workload's own
        ``compute_ms`` column (the edge duration minus admission wait).
        Unlike :meth:`from_exposition` — the device-kernel histogram —
        this includes the scatter/gather and HTTP overhead the edge
        actually pays per dispatch, so it is the fit calibration runs
        compare against a LIVE p99 (``bench.py --config replay``).
        None when the trace carries no served compute samples."""
        comp = sorted(float(r.get("compute_ms") or 0.0) / 1e3
                      for r in trace
                      if r.get("status") == 200 and r.get("compute_ms"))
        if not comp:
            return None
        # Exact empirical inverse-CDF: one cumulative step per sample
        # (service_s interpolates between order statistics).
        buckets = tuple((v, float(i + 1)) for i, v in enumerate(comp))
        return cls(bins=(BinModel(name=name, buckets=buckets),))

    @classmethod
    def from_exposition(cls, text: str) -> Optional["FleetModel"]:
        """Fit per-bin empirical models from a /metrics exposition's
        ``rafiki_tpu_serving_bin_device_seconds`` buckets. None when
        the ledger families are absent or empty (attribution off, or
        no traffic yet) — callers fall back to :meth:`synthetic`."""
        parsed = _metrics.parse_exposition(text)
        by_bin: Dict[str, Dict[float, float]] = {}
        for labels, v in parsed.get(f"{FLEET_SOURCE_SERIES}_bucket", []):
            b = labels.get("bin", "")
            le = labels.get("le", "")
            bound = math.inf if le == "+Inf" else float(le)
            row = by_bin.setdefault(b, {})
            row[bound] = max(row.get(bound, 0.0), float(v))
        models = []
        for b in sorted(by_bin):
            cum = tuple(sorted(by_bin[b].items()))
            if cum and cum[-1][1] > 0:
                models.append(BinModel(name=b, buckets=cum))
        return cls(bins=tuple(models)) if models else None


# --- Simulation knobs --------------------------------------------------

@dataclass(frozen=True)
class SimKnobs:
    """The simulated frontend/fleet constants (not the policy's)."""

    seed: int = 0
    sweep_interval_s: float = 1.0   # supervise cadence under test
    queue_cap: float = 64.0         # admission bound, in queries
    max_batch: int = 8              # batcher's per-burst query budget
    initial_replicas: int = 1       # per bin, at t=0
    provision_delay_s: float = 2.0  # scale-up actuation latency
    max_sim_s: float = 3600.0       # runaway guard past the last arrival


# --- The engine --------------------------------------------------------

class _Sim:
    """One simulation run's mutable state (see :func:`simulate`)."""

    def __init__(self, fleet: FleetModel, sim: SimKnobs,
                 policy: AutoscalePolicy,
                 objectives: Sequence[_slo.Objective],
                 periodicity: Optional[Dict[str, Any]]):
        self.fleet = {m.name: m for m in fleet.bins}
        self.sim = sim
        self.policy = policy
        self.rng = random.Random(sim.seed)
        self.periodicity = periodicity
        # Event heap: (t, seq, kind, payload); seq makes ties stable.
        self.heap: List[Tuple[float, int, str, Any]] = []
        self.seq = 0
        self.req_seq = 0
        # Per-bin replica pools.
        self.active = {b: sim.initial_replicas for b in self.fleet}
        self.busy = {b: 0 for b in self.fleet}
        self.provisioning = {b: 0 for b in self.fleet}
        self.retiring = {b: 0 for b in self.fleet}
        self.queues: Dict[str, List[Tuple[int, int]]] = \
            {b: [] for b in self.fleet}  # [(req_id, n_queries), ...]
        # Requests in flight: req_id -> [t_arrive, pending_bin_slices].
        self.inflight: Dict[int, List[float]] = {}
        self.latencies_ms: List[float] = []
        self.sweep_latencies: List[float] = []  # completed this sweep
        self.rejected = 0
        self.admitted = 0
        self.arrived_queries = 0
        self.sweep_arrivals = 0
        self.sweep_admitted = 0
        self.sweep_rejected = 0
        # Controller state (the REAL JobState the policy reads).
        self.state = JobState()
        self.objectives = [
            _slo.Instance.create(o, {"job": "sim"}) for o in objectives
            if o.scope == "job"]
        self.skipped_objectives = [o.name for o in objectives
                                   if o.scope != "job"]
        self.decisions: List[Dict[str, Any]] = []
        self.timeline: List[Dict[str, Any]] = []
        self.replica_seconds = 0.0
        self._last_change_t = 0.0
        self.firing_s: Dict[str, float] = {}
        self.transitions: Dict[str, List[Dict[str, Any]]] = {}
        self.now = 0.0

    # -- event plumbing -------------------------------------------------

    def push(self, t: float, kind: str, payload: Any = None) -> None:
        self.seq += 1
        heapq.heappush(self.heap, (t, self.seq, kind, payload))

    def total_active(self) -> int:
        return sum(self.active.values())

    def _note_replica_change(self) -> None:
        self.replica_seconds += self.total_active() \
            * (self.now - self._last_change_t)
        self._last_change_t = self.now
        self.timeline.append(
            {"t": round(self.now, 3),
             "replicas": {b: self.active[b]
                          for b in sorted(self.active)}})

    # -- arrivals / service ---------------------------------------------

    def arrive(self, rec: Dict[str, Any]) -> None:
        n = max(1, int(rec.get("n") or 1))
        self.sweep_arrivals += 1
        self.arrived_queries += n
        depth = self.queue_depth()
        if depth + n > self.sim.queue_cap:
            self.rejected += 1
            self.sweep_rejected += 1
            return
        self.admitted += 1
        self.sweep_admitted += 1
        # Own counter: the heap's seq only advances on push(), so two
        # back-to-back arrivals that find every replica busy (no done
        # event pushed between them) would otherwise share an id and
        # alias each other's inflight slot.
        self.req_seq += 1
        req_id = self.req_seq
        self.inflight[req_id] = [self.now, len(self.fleet)]
        for b in self.fleet:
            self.queues[b].append((req_id, n))
            self.dispatch(b)

    def queue_depth(self) -> float:
        """Admission-gauge analogue: queries queued toward the slowest
        bin (the bin that gates the frontend)."""
        if not self.queues:
            return 0.0
        return float(max((sum(n for _, n in q)
                          for q in self.queues.values()), default=0))

    def dispatch(self, b: str) -> None:
        while self.queues[b] and \
                self.busy[b] < self.active[b] - self.retiring[b]:
            batch: List[Tuple[int, int]] = []
            got = 0
            while self.queues[b] and got < self.sim.max_batch:
                item = self.queues[b].pop(0)
                batch.append(item)
                got += item[1]
            self.busy[b] += 1
            svc = self.fleet[b].service_s(got, self.rng)
            self.push(self.now + max(1e-6, svc), "done", (b, batch))

    def complete(self, b: str, batch: List[Tuple[int, int]]) -> None:
        self.busy[b] -= 1
        if self.retiring[b] > 0 and self.active[b] > 1:
            self.retiring[b] -= 1
            self.active[b] -= 1
            self._note_replica_change()
        for req_id, _n in batch:
            slot = self.inflight.get(req_id)
            if slot is None:
                continue
            slot[1] -= 1
            if slot[1] <= 0:
                del self.inflight[req_id]
                ms = (self.now - slot[0]) * 1e3
                self.latencies_ms.append(ms)
                self.sweep_latencies.append(ms)
        self.dispatch(b)

    def provision(self, b: str) -> None:
        self.provisioning[b] -= 1
        self.active[b] += 1
        self._note_replica_change()
        self.dispatch(b)

    # -- the sweep (the real control plane, on simulated signals) -------

    def counts(self) -> Dict[str, int]:
        return {b: self.active[b] + self.provisioning[b]
                - self.retiring[b] for b in self.fleet}

    def sweep(self) -> None:
        dt = self.sim.sweep_interval_s
        sig = JobSignals(queue_depth=self.queue_depth(),
                         queue_cap=self.sim.queue_cap)
        inst_qps = self.sweep_arrivals / dt
        self.state.qps_ewma = (
            inst_qps if self.state.qps_ewma is None else
            0.4 * inst_qps + 0.6 * self.state.qps_ewma)
        sig.qps = self.state.qps_ewma
        sig.backpressure_delta = float(self.sweep_rejected)
        if self.sweep_latencies:
            ordered = sorted(self.sweep_latencies)
            rank = max(0, math.ceil(0.99 * len(ordered)) - 1)
            sig.p99_ms = round(ordered[rank], 3)
        # The predictive plane, exactly as the live sweep feeds it:
        # queue-trend projection plus the learned periodicity lookup
        # (sim time doubles as the phase clock).
        self.policy.note_trend(sig, self.state, self.now)
        if self.periodicity is not None and \
                self.policy.knobs.predict_horizon_s > 0:
            from ..admin.capacity import expected_qps
            sig.expected_qps = expected_qps(
                self.periodicity, self.now,
                self.policy.knobs.predict_horizon_s)
        # SLO instances judge this sweep's completions/admissions.
        firing = None
        for inst in self.objectives:
            obj = inst.objective
            if obj.otype == "latency":
                thr = obj.threshold_ms
                good = float(sum(1 for ms in self.sweep_latencies
                                 if ms <= thr))
                total = float(len(self.sweep_latencies))
            else:
                good = float(self.sweep_admitted)
                total = float(self.sweep_admitted + self.sweep_rejected)
            transition = inst.evaluate(self.now, good, total)
            if transition is not None:
                self.transitions.setdefault(obj.name, []).append(
                    {"t": round(self.now, 3), "state": transition})
            if inst.machine.state == "firing":
                self.firing_s[obj.name] = \
                    self.firing_s.get(obj.name, 0.0) + dt
                if obj.otype == "latency":
                    firing = ""
        sig.slo_firing = firing
        counts = self.counts()
        for d in self.policy.decide(sig, counts, self.state, self.now):
            self.apply(d, counts, sig)
        self.sweep_latencies = []
        self.sweep_arrivals = 0
        self.sweep_admitted = 0
        self.sweep_rejected = 0

    def apply(self, d: Decision, counts: Dict[str, int],
              sig: JobSignals) -> None:
        self.decisions.append(
            {"t": round(self.now, 3), "action": d.action, "bin": d.bin,
             "reason": d.reason, "replicas": counts[d.bin],
             "signals": {"qps": round(sig.qps, 2),
                         "queue_frac": round(sig.queue_frac, 4),
                         "backpressure_delta": sig.backpressure_delta,
                         "p99_ms": sig.p99_ms}})
        if d.action == "scale_up":
            # Same cooldown contract as Autoscaler._apply: the attempt
            # consumes the cooldown.
            self.state.last_up_mono = self.now
            self.provisioning[d.bin] += 1
            self.push(self.now + self.sim.provision_delay_s,
                      "provision", d.bin)
        else:
            self.state.last_down_mono = self.now
            if self.active[d.bin] - self.retiring[d.bin] > 1:
                if self.busy[d.bin] < self.active[d.bin] \
                        - self.retiring[d.bin]:
                    self.active[d.bin] -= 1  # a free replica retires now
                    self._note_replica_change()
                else:
                    self.retiring[d.bin] += 1  # retire on next drain

    # -- run ------------------------------------------------------------

    def run(self, trace: Sequence[Dict[str, Any]]) -> None:
        last_arrival = 0.0
        for rec in trace:
            t = max(0.0, float(rec.get("off_s") or 0.0))
            last_arrival = max(last_arrival, t)
            self.push(t, "arrival", rec)
        deadline = last_arrival + self.sim.max_sim_s
        self.push(self.sim.sweep_interval_s, "sweep", None)
        self._note_replica_change()
        while self.heap:
            t, _seq, kind, payload = heapq.heappop(self.heap)
            if t > deadline:
                break
            self.now = t
            if kind == "arrival":
                self.arrive(payload)
            elif kind == "done":
                self.complete(*payload)
            elif kind == "provision":
                self.provision(payload)
            elif kind == "sweep":
                self.sweep()
                # Sweeps stop once the work is drained — they are the
                # only self-renewing event, so this bounds the run.
                if self.inflight or self.heap:
                    self.push(self.now + self.sim.sweep_interval_s,
                              "sweep", None)
        self.replica_seconds += self.total_active() \
            * (self.now - self._last_change_t)
        self._last_change_t = self.now


def _percentile(ordered: List[float], q: float) -> Optional[float]:
    if not ordered:
        return None
    rank = max(0, math.ceil(q * len(ordered)) - 1)
    return round(ordered[rank], 3)


def simulate(trace: Sequence[Dict[str, Any]],
             fleet: Optional[FleetModel] = None,
             sim: Optional[SimKnobs] = None,
             policy: Optional[PolicyKnobs] = None,
             objectives: Sequence[_slo.Objective] = (),
             periodicity: Optional[Dict[str, Any]] = None,
             ) -> Dict[str, Any]:
    """Replay ``trace`` (workload records; only ``off_s``/``n`` are
    consumed) against ``fleet`` under ``policy`` + ``objectives``.
    Returns the full report: latency quantiles, 429s, the replica
    timeline, every policy decision, and per-objective SLO outcomes
    (``violations`` lists objectives that ever fired — the regression
    gate's verdict)."""
    fleet = fleet or FleetModel.synthetic()
    sim = sim or SimKnobs()
    engine = _Sim(fleet, sim, AutoscalePolicy(policy or PolicyKnobs()),
                  objectives, periodicity)
    engine.run(trace)
    ordered = sorted(engine.latencies_ms)
    actions: Dict[str, int] = {}
    for d in engine.decisions:
        key = f"{d['action']}:{d['reason']}"
        actions[key] = actions.get(key, 0) + 1
    slo_out = {}
    for inst in engine.objectives:
        name = inst.objective.name
        slo_out[name] = {
            "budget_remaining": round(inst.budget_remaining, 4),
            "firing_s": round(engine.firing_s.get(name, 0.0), 3),
            "state": inst.machine.state,
            "transitions": engine.transitions.get(name, []),
        }
    violations = sorted(n for n, s in slo_out.items()
                        if s["firing_s"] > 0 or s["state"] != "ok")
    return {
        "ok": not violations,
        "violations": violations,
        "requests": engine.admitted + engine.rejected,
        "served": len(engine.latencies_ms),
        "rejected": engine.rejected,
        "queries": engine.arrived_queries,
        "duration_s": round(engine.now, 3),
        "latency_ms": {
            "p50": _percentile(ordered, 0.50),
            "p95": _percentile(ordered, 0.95),
            "p99": _percentile(ordered, 0.99),
            "mean": (round(sum(ordered) / len(ordered), 3)
                     if ordered else None),
        },
        "replica_seconds": round(engine.replica_seconds, 3),
        "max_replicas": {b: max((e["replicas"][b]
                                 for e in engine.timeline), default=0)
                         for b in sorted(engine.fleet)},
        "replica_timeline": engine.timeline,
        "decisions": engine.decisions,
        "actions": actions,
        "slo": slo_out,
        "slo_skipped_scopes": sorted(engine.skipped_objectives),
        "seed": sim.seed,
    }
