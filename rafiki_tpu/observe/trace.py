"""End-to-end request tracing: trace ids across HTTP edge, bus, worker.

Dapper-shaped, sized for this system: a trace id is minted (or adopted
from an ``X-Trace-Id`` request header) at the admin/predictor HTTP
edges, carried thread-locally through the handler, captured by the
micro-batcher at admission, injected into the bus message envelope at
scatter (``"_trace"`` key — old frames simply lack it, old consumers
ignore it: both directions of the version skew degrade to "no trace"),
and recovered by the inference worker on the far side of the bus.

Span *events* are flat JSONL lines appended to a **segmented store**
under the log dir (``utils/service_logs`` gives every service the same
directory): the active segment is ``spans.jsonl``, written with
O_APPEND semantics so resident-runner threads and subprocess services
interleave whole lines; at ``RAFIKI_TPU_TRACE_MAX_MB`` it rolls to
``spans.jsonl.1`` (older generations shift to ``.2`` .. ``.N``), with
retention bounded by ``RAFIKI_TPU_TRACE_RETAIN_SEGMENTS`` (generation
count) and ``RAFIKI_TPU_TRACE_RETAIN_MB`` (total rolled bytes). Each
frozen segment gets a **sidecar index** (``<segment>.idx``: trace id →
byte offsets) built once at roll time, so ``Admin.get_trace``
(``GET /trace/<id>``) is an indexed seek-and-read per frozen segment
instead of a full-store scan; the active segment is covered by an
incremental in-process scan cache that only ever reads the appended
tail. "Why was this /predict slow, yesterday" stays one curl on a
busy node.

Sampling, two stages:

- **Head** (``RAFIKI_TPU_TRACE_SAMPLE``, 0..1, default 1.0) samples
  freshly minted traces at the edge; a request that ARRIVES with a
  trace id is always honored (the caller already decided to trace it).
  Sampling out costs nothing downstream — no context means no envelope
  field and no span writes.
- **Tail** (``RAFIKI_TPU_TRACE_TAIL_SAMPLE`` < 1.0 enables): spans of
  freshly minted traces are buffered in memory until the minting edge
  completes its request, then the verdict is made on the OUTCOME —
  error responses and requests slower than
  ``RAFIKI_TPU_TRACE_TAIL_SLOW_MS`` are always retained, fast/ok ones
  are kept at the tail sample rate. The interesting 1% survives a
  sample rate that would have dropped it head-side. Per-process by
  construction: spans recorded by a *different* process (subprocess
  workers) are written eagerly and can't be un-written — the orphan
  spans of a dropped trace are the documented cost of not running a
  central collector.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
import uuid
from typing import Any, Dict, Iterable, List, Optional, Tuple

_log = logging.getLogger(__name__)

TRACE_SAMPLE_ENV = "RAFIKI_TPU_TRACE_SAMPLE"
TRACE_MAX_MB_ENV = "RAFIKI_TPU_TRACE_MAX_MB"
TRACE_RETAIN_SEGMENTS_ENV = "RAFIKI_TPU_TRACE_RETAIN_SEGMENTS"
TRACE_RETAIN_MB_ENV = "RAFIKI_TPU_TRACE_RETAIN_MB"
TRACE_TAIL_SAMPLE_ENV = "RAFIKI_TPU_TRACE_TAIL_SAMPLE"
TRACE_TAIL_SLOW_MS_ENV = "RAFIKI_TPU_TRACE_TAIL_SLOW_MS"
TRACE_HEADER = "X-Trace-Id"

#: Envelope key inside bus message frames. Absent on old frames (the
#: backward-compatible fallback: extract() returns no contexts) and
#: ignored by old consumers (frame readers key on "query"/"queries").
ENVELOPE_KEY = "_trace"

#: A super-batch coalesces many requests; the envelope carries at most
#: this many of their contexts (the worker records one span event per
#: carried trace).
MAX_ENVELOPE_TRACES = 32

SPAN_FILE = "spans.jsonl"
INDEX_SUFFIX = ".idx"
#: Tail-verdict sidecar (shared log dir, O_APPEND like the span file):
#: the minting edge appends one ``{"t": trace_id, "v": kept|dropped}``
#: line per completed tail trace, so OTHER processes (subprocess
#: workers) can honor the verdict instead of writing orphan spans.
VERDICT_FILE = "trace_verdicts.jsonl"

#: Remote tail hold: spans of a tail-pending trace minted in ANOTHER
#: process are buffered this long waiting for its verdict line; no
#: verdict by then = retained (retain-on-doubt, never silently drop).
_REMOTE_HOLD_S = 5.0
_REMOTE_MAX_TRACES = 512
#: Verdict map memory bound (FIFO): verdicts only matter for the hold
#: window, so old entries age out.
_VERDICT_REMEMBER = 8192
_VERDICT_MAX_BYTES = 4 * 1024 * 1024

#: Tail-sampling buffer bounds: a pending trace whose edge never
#: completes (crashed handler, client that holds the socket forever)
#: must not grow memory without bound — overflowing traces/spans are
#: flushed to the store (retain-on-doubt, never silently dropped).
_PENDING_MAX_TRACES = 512
_PENDING_MAX_SPANS = 200
#: Recently-dropped trace ids remembered so a straggler span arriving
#: after the tail verdict (a late worker reply) doesn't resurrect a
#: dropped trace as orphan lines.
_DROPPED_REMEMBER = 1024


def new_trace_id() -> str:
    return uuid.uuid4().hex


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


class TraceContext:
    """One request's position in its trace: the trace id plus the
    CURRENT span id (children parent onto it). ``tail=True`` marks a
    context whose retention verdict is deferred to edge completion
    (set only on the minting edge, under tail sampling)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "tail")

    def __init__(self, trace_id: str, span_id: Optional[str] = None,
                 parent_id: Optional[str] = None, tail: bool = False):
        self.trace_id = trace_id
        self.span_id = span_id or new_span_id()
        self.parent_id = parent_id
        self.tail = tail

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, parent_id=self.span_id,
                            tail=self.tail)

    def header_value(self) -> str:
        return f"{self.trace_id}-{self.span_id}"

    def __repr__(self):  # pragma: no cover - debug aid
        return f"TraceContext({self.trace_id[:8]}…/{self.span_id})"


# --- Thread-local current context ------------------------------------

_local = threading.local()


def current() -> Optional[TraceContext]:
    return getattr(_local, "ctx", None)


class use:
    """``with trace.use(ctx): ...`` — bind/restore the thread's current
    context. ``ctx=None`` clears for the duration."""

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx

    def __enter__(self):
        self._prior = current()
        _local.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _local.ctx = self._prior
        return False


def sample_rate() -> float:
    raw = os.environ.get(TRACE_SAMPLE_ENV, "").strip()
    if not raw:
        return 1.0
    try:
        return min(1.0, max(0.0, float(raw)))
    except ValueError:
        return 1.0


def tail_sample_rate() -> Optional[float]:
    """The tail-sampling keep rate for fast/ok traces, or None when
    tail sampling is off (unset / 1.0 / unparseable — fail toward the
    legacy keep-everything behavior)."""
    raw = os.environ.get(TRACE_TAIL_SAMPLE_ENV, "").strip()
    if not raw:
        return None
    try:
        rate = float(raw)
    except ValueError:
        return None
    if rate >= 1.0:
        return None
    return max(0.0, rate)


def tail_slow_ms() -> float:
    try:
        return max(0.0, float(os.environ.get(TRACE_TAIL_SLOW_MS_ENV,
                                             "250") or 250))
    except ValueError:
        return 250.0


_HEADER_RE = None


def start_trace(header: Optional[str] = None) -> Optional[TraceContext]:
    """Context for one incoming edge request. An ``X-Trace-Id`` header
    is always honored: our own ``<32hex>-<16hex>`` format splits into
    trace + parent span; ANY other non-empty value (a dashed UUID, an
    opaque upstream id) is taken whole as the trace id — splitting at
    a dash would silently truncate standard ``str(uuid4())`` ids.
    Honored traces are never tail-buffered (the caller already decided
    to retain). Otherwise a fresh trace is minted subject to the head
    sample rate (None = sampled out); under tail sampling the fresh
    trace is registered PENDING — its spans buffer until
    :func:`complete` delivers the outcome verdict."""
    global _HEADER_RE
    if header and header.strip():
        import re

        if _HEADER_RE is None:
            _HEADER_RE = re.compile(
                r"^([0-9a-fA-F]{32})-([0-9a-fA-F]{16})$")
        value = header.strip()
        match = _HEADER_RE.match(value)
        if match:
            return TraceContext(match.group(1),
                                parent_id=match.group(2))
        return TraceContext(value)
    rate = sample_rate()
    if rate <= 0.0 or (rate < 1.0 and random.random() >= rate):
        return None
    ctx = TraceContext(new_trace_id())
    # rta: disable=RTA101 benign racy read of the sink pointer (GIL-atomic reference); worst case one sample misses tail registration
    if tail_sample_rate() is not None and _sink_path is not None:
        ctx.tail = True
        _tail_register(ctx.trace_id)
    return ctx


# --- Envelope carry (bus frames) --------------------------------------

def inject(ctxs: Iterable[Optional[TraceContext]]) -> Optional[Dict]:
    """Envelope field for a bus frame carrying these requests' traces,
    or None when nothing is traced (the frame then looks exactly like
    an old frame). Tail-pending contexts are marked by INDEX in a
    separate ``tail`` key — old consumers read only ``ids`` (changing
    the id pair shape would break their unpack and degrade every
    trace), new ones buffer those traces' spans until the edge's
    verdict arrives (see the module docstring)."""
    ids = []
    tail = []
    for c in ctxs:
        if c is None:
            continue
        if len(ids) >= MAX_ENVELOPE_TRACES:
            break
        if c.tail:
            tail.append(len(ids))
        ids.append([c.trace_id, c.span_id])
    if not ids:
        return None
    env: Dict[str, Any] = {"ids": ids}
    if tail:
        env["tail"] = tail
    return env


def extract(frame: Any) -> List[TraceContext]:
    """Pop the trace envelope off a bus frame dict. Old frames (no
    ``_trace`` key) and malformed envelopes return ``[]`` — tracing
    must never fail a query.

    The returned contexts CONTINUE the propagated spans (same span id),
    so a consumer's ``record_event(child=True)`` parents its span onto
    the span that sent the frame."""
    if not isinstance(frame, dict):
        return []
    env = frame.pop(ENVELOPE_KEY, None)
    if not isinstance(env, dict):
        return []
    out = []
    try:
        for tid, sid in env.get("ids", []):
            out.append(TraceContext(str(tid), span_id=str(sid)))
        for i in env.get("tail") or ():
            # The tail marks survive the bus hop so a consumer in
            # ANOTHER process can hold these traces' spans for the
            # edge's retain/drop verdict instead of writing orphans.
            if isinstance(i, int) and 0 <= i < len(out):
                out[i].tail = True
    except (TypeError, ValueError):
        return []
    return out


def extract_frames(frames: Iterable[Any]) -> List[TraceContext]:
    """Extract across a popped burst, deduplicated by trace id (a
    worker burst may drain several frames of one super-batch)."""
    seen = set()
    out: List[TraceContext] = []
    for frame in frames:
        for ctx in extract(frame):
            if ctx.trace_id not in seen:
                seen.add(ctx.trace_id)
                out.append(ctx)
    return out


# --- Span sink (segmented JSONL store through the service log dir) ----

_sink_lock = threading.Lock()
_sink_path: Optional[str] = None
_sink_file = None

# Tail-sampling state: pending (buffered) trace ids -> span lines, an
# insertion-ordered dict so overflow flushes the OLDEST pending trace;
# recently dropped ids suppress straggler spans.
_tail_lock = threading.Lock()
_tail_pending: "Dict[str, List[str]]" = {}
_tail_dropped: "Dict[str, None]" = {}
_tail_rng = random.Random()

# Cross-process tail verdicts: spans of tail-pending traces minted in
# ANOTHER process (the bus envelope's tail marks) hold here —
# ``tid -> [deadline, [lines]]``, insertion-ordered — until the
# minting edge's verdict line lands in the verdict sidecar, the hold
# expires (retain-on-doubt), or the buffer overflows (flush, never
# drop). The verdict map is the sidecar's incremental read, bounded
# FIFO.
_remote_pending: "Dict[str, List[Any]]" = {}
_verdict_sink = None              # this process's verdict appender
_verdict_reader: List[Any] = [0, None]   # [bytes read, file identity]
_verdicts: "Dict[str, str]" = {}

# Incremental scan cache for the ACTIVE segment: path -> [bytes
# scanned, {trace_id: [line offsets]}]. Lookups only ever read the
# tail appended since the previous lookup.
_active_lock = threading.Lock()
_active_cache: Dict[str, List[Any]] = {}


def span_log_path(log_dir: str) -> str:
    return os.path.join(log_dir, SPAN_FILE)


def configure(log_dir: Optional[str]) -> None:
    """Point this process's span sink at ``<log_dir>/spans.jsonl``
    (append; created on first span). ``None``/"" disables recording.
    Resident-runner mode configures once per platform; subprocess
    services configure from their ``RAFIKI_TPU_LOG_DIR`` env. Any
    tail-pending buffers are flushed to the OLD sink first (retained:
    reconfiguring must not silently eat buffered spans)."""
    global _sink_path, _sink_file, _verdict_sink
    _tail_flush_all()
    flush_remote_tail()
    with _sink_lock:
        if _sink_file is not None:
            try:
                _sink_file.close()
            except OSError:
                pass
            _sink_file = None
        if _verdict_sink is not None:
            try:
                _verdict_sink.close()
            except OSError:
                pass
            _verdict_sink = None
        _sink_path = span_log_path(log_dir) if log_dir else None
    with _tail_lock:
        _verdict_reader[:] = [0, None]
        _verdicts.clear()


def configured() -> bool:
    # rta: disable=RTA101 lock-free liveness probe; a reference read is GIL-atomic
    return _sink_path is not None


def _max_span_bytes() -> int:
    try:
        return int(float(os.environ.get(TRACE_MAX_MB_ENV, "64"))
                   * 1024 * 1024)
    except ValueError:
        return 64 * 1024 * 1024


def retain_segments() -> int:
    """Rolled generations kept (``.1`` .. ``.N``). Default 4; the
    pre-r17 single-``.1`` behavior is ``=1``."""
    try:
        return max(1, int(os.environ.get(TRACE_RETAIN_SEGMENTS_ENV,
                                         "4") or 4))
    except ValueError:
        return 4


def _retain_total_bytes() -> int:
    try:
        return int(float(os.environ.get(TRACE_RETAIN_MB_ENV, "256")
                         or 256) * 1024 * 1024)
    except ValueError:
        return 256 * 1024 * 1024


def _store_counter():
    from . import metrics

    return metrics.registry().counter(
        "rafiki_tpu_trace_store_total",
        "Trace span-store events (event=roll|index_build|index_read|"
        "tail_scan|compact)")


def _write_lines(lines: List[str]) -> None:
    global _sink_file
    wrote = 0
    rolled: Optional[str] = None
    with _sink_lock:
        if _sink_path is None:
            return
        try:
            if _sink_file is None or _sink_file.closed:
                os.makedirs(os.path.dirname(_sink_path) or ".",
                            exist_ok=True)
                # rta: disable=RTA105 the sink lock guards the handle itself; the lazy open IS the bind it serializes (once per roll)
                _sink_file = open(_sink_path, "a", encoding="utf-8")
            _sink_file.write("".join(lines))
            _sink_file.flush()
            wrote = len(lines)
            # Size cap (RAFIKI_TPU_TRACE_MAX_MB, default 64): roll the
            # active segment into the retained generation chain so a
            # busy node (or a client that always sends X-Trace-Id,
            # bypassing sampling) cannot fill the disk while multi-day
            # lookback stays possible. Append mode means tell() is the
            # file size; a concurrent multi-process rotation race is
            # benign — the atomic replaces at worst drop some spans of
            # one generation.
            if _sink_file.tell() > _max_span_bytes():
                _sink_file.close()
                _sink_file = None
                rolled = _roll_segments(_sink_path)
        except OSError:  # sink dir vanished (test teardown); drop spans
            _sink_file = None
    if rolled is not None:
        # The sidecar index scans the whole frozen segment — done
        # OUTSIDE the sink lock, or every in-flight handler's span
        # write (and tail flush) would stall behind a multi-MB read at
        # each roll. The segment is frozen, so nothing races the scan;
        # a reader arriving before the .idx lands just rebuilds it
        # lazily (the _load_index fallback).
        try:
            _build_index(rolled)
        except OSError:
            pass
        if tail_sample_rate() is not None:
            # Idle-time compaction (rolls are rare): rewrite ONE older
            # frozen segment to only-retained traces — orphan spans of
            # tail-dropped traces (eager pre-verdict writers, overflow
            # flushes) stop surviving on disk. The two NEWEST
            # generations are skipped: .1 because its traces' verdicts
            # may still be pending, and .2 because a co-writing
            # PROCESS whose append handle chased the renames may still
            # be flushing its last burst into it — compaction swaps
            # the inode (os.replace of a rewrite), and replacing a
            # segment a laggard writer still holds open would turn the
            # documented drop-a-few-spans rotation race into losing
            # every span that writer appends until its own next roll.
            # By the time a generation shifts to .3 every writer has
            # re-rolled (frozen segments sit above the size cap, so a
            # stale handle's very next write triggers its reopen).
            try:
                base = rolled[:-2]  # "<dir>/spans.jsonl.1" -> base
                compact_segments(os.path.dirname(rolled), limit=1,
                                 exclude={rolled, base + ".2"})
            except OSError:
                pass
    if wrote:
        # Counted at WRITE time (outside the sink lock), so a tail-
        # buffered span only counts once its trace's verdict actually
        # lands it in the store — the bench's overhead delta reads
        # spans that exist, not spans that were considered.
        from . import metrics

        metrics.registry().counter(
            "rafiki_tpu_trace_spans_total",
            "Span events written to the span log").inc(wrote)


def _roll_segments(path: str) -> Optional[str]:
    """Shift the generation chain (``.k`` -> ``.k+1``, oldest beyond
    the retention bounds deleted) and freeze the active file as
    ``.1``; returns the frozen segment's path so the CALLER can build
    its sidecar index outside the sink lock (None when the freeze
    itself failed). Caller holds ``_sink_lock``."""
    n = retain_segments()
    # Drop the generation that would shift past the count bound.
    for stale in (f"{path}.{n}", f"{path}.{n}{INDEX_SUFFIX}"):
        try:
            os.remove(stale)
        except OSError:
            pass
    for k in range(n - 1, 0, -1):
        for suffix in (INDEX_SUFFIX, ""):
            src = f"{path}.{k}{suffix}"
            if os.path.exists(src):
                try:
                    os.replace(src, f"{path}.{k + 1}{suffix}")
                except OSError:
                    pass
    try:
        os.replace(path, f"{path}.1")
    except OSError:
        return None
    with _active_lock:
        _active_cache.pop(path, None)  # the active file restarted
    # Total-bytes retention: delete oldest generations until the rolled
    # chain fits the byte budget (the newest generation always stays —
    # a budget below one segment must not erase the roll entirely).
    budget = _retain_total_bytes()
    sizes = []
    for k in range(1, n + 1):
        try:
            sizes.append((k, os.path.getsize(f"{path}.{k}")))
        except OSError:
            continue
    total = sum(s for _, s in sizes)
    for k, size in sorted(sizes, reverse=True):
        if total <= budget or k == 1:
            break
        for stale in (f"{path}.{k}", f"{path}.{k}{INDEX_SUFFIX}"):
            try:
                os.remove(stale)
            except OSError:
                pass
        total -= size
    try:
        _store_counter().inc(event="roll")
    except Exception:  # metrics must never fail the span sink
        pass
    return f"{path}.1"


def _trace_id_of_line(line: str) -> Optional[str]:
    """Cheap trace-id extraction without a full JSON parse. Tolerates
    whitespace after the key separator (lines written by other tools /
    older versions with default ``json.dumps`` spacing); trace ids are
    hex, so the value can never contain escapes."""
    marker = '"trace_id":'
    i = line.find(marker)
    if i < 0:
        return None
    j = i + len(marker)
    while j < len(line) and line[j] in " \t":
        j += 1
    if j >= len(line) or line[j] != '"':
        return None
    k = line.find('"', j + 1)
    if k < 0:
        return None
    return line[j + 1:k]


def _scan_offsets(path: str, start: int = 0,
                  ) -> Tuple[Dict[str, List[int]], int]:
    """``{trace_id: [byte offsets]}`` for every span line from byte
    ``start`` to EOF, plus the byte position scanned to."""
    offsets: Dict[str, List[int]] = {}
    with open(path, "rb") as f:
        f.seek(start)
        pos = start
        for raw in f:
            if raw.endswith(b"\n"):
                tid = _trace_id_of_line(
                    raw.decode("utf-8", errors="replace"))
                if tid:
                    offsets.setdefault(tid, []).append(pos)
                pos += len(raw)
            else:
                break  # torn tail write; re-scan it next lookup
    return offsets, pos


def index_path(segment_path: str) -> str:
    return segment_path + INDEX_SUFFIX


def _write_index(segment_path: str, offsets: Dict[str, List[int]],
                 compacted: bool) -> None:
    """Persist one sidecar index atomically (tmp + replace) so a
    concurrent reader never loads a torn index. The segment's byte
    size is recorded so a reader can detect a STALE index: compaction
    replaces segment then index as two separate atomic steps, and
    offsets loaded against the wrong generation must read as
    missing-index (rebuild), never seek misaligned."""
    tmp = index_path(segment_path) + ".tmp"
    try:
        size = os.path.getsize(segment_path)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"v": 1, "compacted": compacted, "size": size,
                       "traces": offsets}, f, separators=(",", ":"))
        os.replace(tmp, index_path(segment_path))
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass


def _build_index(segment_path: str,
                 compacted: bool = False) -> Dict[str, List[int]]:
    """Scan one FROZEN segment once and persist its sidecar index
    (``{trace_id: [offsets]}`` + the ``compacted`` marker)."""
    offsets, _pos = _scan_offsets(segment_path)
    _write_index(segment_path, offsets, compacted)
    try:
        _store_counter().inc(event="index_build")
    except Exception:
        pass
    return offsets


def _load_index_data(segment_path: str) -> Optional[Dict[str, Any]]:
    """The sidecar index as written (traces + compacted marker), or
    None when missing/torn — or STALE: an index whose recorded size
    disagrees with the segment on disk belongs to another generation
    of the file (a compaction replaced the segment but not yet the
    index, or vice versa); its offsets must not be seeked."""
    try:
        with open(index_path(segment_path), encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(data, dict) or \
            not isinstance(data.get("traces"), dict):
        return None
    size = data.get("size")
    if size is not None:
        try:
            if os.path.getsize(segment_path) != size:
                return None
        except OSError:
            return None
    return data


def segment_compacted(segment_path: str) -> bool:
    data = _load_index_data(segment_path)
    return bool(data and data.get("compacted"))


def _dropped_verdict_ids() -> set:
    """Every trace id the verdict sidecar (active + rolled generation)
    records as dropped — what compaction removes. A later 'kept' line
    for the same id wins (a re-used header id must never be erased)."""
    path = _verdict_path()
    out: Dict[str, str] = {}
    if path is None:
        return set()
    for p in (path + ".1", path):
        try:
            with open(p, "rb") as f:
                for raw in f:
                    if not raw.endswith(b"\n"):
                        break
                    try:
                        rec = json.loads(raw)
                    except json.JSONDecodeError:
                        continue
                    tid, v = rec.get("t"), rec.get("v")
                    if isinstance(tid, str) and v in ("kept",
                                                      "dropped"):
                        out[tid] = v
        except OSError:
            continue
    return {tid for tid, v in out.items() if v == "dropped"}


def compact_segment(segment_path: str,
                    dropped: Optional[set] = None) -> Dict[str, Any]:
    """Rewrite ONE frozen segment to only-retained traces: lines whose
    trace id carries a ``dropped`` tail verdict (orphan spans written
    eagerly by other processes, or flushed on buffer overflow before
    the verdict landed) are removed, everything else — including
    verdict-less lines — survives. The sidecar index is rebuilt from
    the new content and replaced atomically WITH its ``compacted``
    marker; the segment replace itself is atomic too (tmp + replace),
    so a concurrent reader sees either the old segment or the new one,
    never a torn file. The segment+index PAIR is not atomic — two
    replaces — but each index records its segment's byte size, so a
    reader that catches the window loads a size-mismatched index,
    treats it as missing, and rebuilds from the file it actually has
    instead of seeking stale offsets."""
    if dropped is None:
        dropped = _dropped_verdict_ids()
    kept_lines: List[bytes] = []
    offsets: Dict[str, List[int]] = {}
    removed = 0
    pos = 0
    with open(segment_path, "rb") as f:
        for raw in f:
            if not raw.endswith(b"\n"):
                break  # torn tail (shouldn't exist on a frozen file)
            tid = _trace_id_of_line(
                raw.decode("utf-8", errors="replace"))
            if tid and tid in dropped:
                removed += 1
                continue
            if tid:
                offsets.setdefault(tid, []).append(pos)
            kept_lines.append(raw)
            pos += len(raw)
    tmp = segment_path + ".compact.tmp"
    with open(tmp, "wb") as f:
        f.write(b"".join(kept_lines))
    os.replace(tmp, segment_path)
    _write_index(segment_path, offsets, compacted=True)
    try:
        _store_counter().inc(event="compact")
    except Exception:
        pass
    return {"segment": os.path.basename(segment_path),
            "removed": removed, "kept": len(kept_lines)}


def compact_segments(log_dir: str, limit: Optional[int] = None,
                     exclude: Optional[set] = None,
                     ) -> List[Dict[str, Any]]:
    """The idle-time compaction pass: rewrite frozen segments (oldest
    first, never the active file) not yet marked compacted, up to
    ``limit``. Called with ``limit=1`` from the roll path — rolls are
    rare and already off the hot lock — and directly by tests/ops."""
    path = span_log_path(log_dir)
    out: List[Dict[str, Any]] = []
    dropped: Optional[set] = None
    for p in segment_paths(log_dir):
        if p == path or (exclude and p in exclude):
            continue
        if limit is not None and len(out) >= limit:
            break
        if segment_compacted(p):
            continue
        if dropped is None:
            dropped = _dropped_verdict_ids()
        try:
            out.append(compact_segment(p, dropped))
        except OSError:
            continue
    return out


def _read_lines_at(path: str, offsets: List[int],
                   ) -> Tuple[List[str], int]:
    """Seek-and-read one line per offset; returns the lines and the
    bytes actually read (the indexed-read evidence)."""
    out: List[str] = []
    n_bytes = 0
    try:
        with open(path, "rb") as f:
            for off in offsets:
                f.seek(off)
                raw = f.readline()
                n_bytes += len(raw)
                out.append(raw.decode("utf-8", errors="replace"))
    except OSError:
        return out, n_bytes
    return out, n_bytes


# --- Tail-sampling buffer ---------------------------------------------

def _tail_register(trace_id: str) -> None:
    flush: List[List[str]] = []
    with _tail_lock:
        if trace_id in _tail_pending:
            return
        while len(_tail_pending) >= _PENDING_MAX_TRACES:
            # Oldest pending first: its edge presumably died; retain.
            _oldest, lines = next(iter(_tail_pending.items()))
            del _tail_pending[_oldest]
            if lines:
                flush.append(lines)
        _tail_pending[trace_id] = []
    for lines in flush:
        _write_lines(lines)


def _tail_route(lines_by_ctx: List[Tuple[Optional[TraceContext],
                                         str]]) -> None:
    """Write span lines, detouring those of tail-pending traces into
    their buffer, suppressing those of recently dropped traces, and —
    for tail-marked traces MINTED IN ANOTHER PROCESS (the envelope's
    tail carry; their ids are unknown to this process's pending
    buffer) — holding them for the minting edge's verdict line in the
    verdict sidecar instead of writing orphans."""
    direct: List[str] = []
    overflow: List[str] = []
    now = time.monotonic()
    with _tail_lock:
        for ctx, line in lines_by_ctx:
            tid = ctx.trace_id if ctx is not None else None
            buf = _tail_pending.get(tid) if tid else None
            if buf is not None:
                if len(buf) >= _PENDING_MAX_SPANS:
                    # A runaway trace stops buffering: flush what it
                    # has, retain everything after (never drop spans
                    # we can no longer hold the verdict open for).
                    del _tail_pending[tid]
                    overflow.extend(buf)
                    overflow.append(line)
                else:
                    buf.append(line)
            elif tid and tid in _tail_dropped:
                continue
            elif ctx is not None and ctx.tail and \
                    _verdicts.get(tid) == "dropped":
                continue  # verdict already known: suppressed orphan
            elif ctx is not None and ctx.tail and \
                    _verdicts.get(tid) != "kept":
                # Remote-minted, verdict unknown: hold briefly.
                entry = _remote_pending.get(tid)
                if entry is None:
                    while len(_remote_pending) >= _REMOTE_MAX_TRACES:
                        _oldest, old = next(iter(
                            _remote_pending.items()))
                        del _remote_pending[_oldest]
                        overflow.extend(old[1])  # retain-on-doubt
                    entry = _remote_pending[tid] = \
                        [now + _REMOTE_HOLD_S, []]
                if len(entry[1]) >= _PENDING_MAX_SPANS:
                    # A runaway remote trace stops holding: flush and
                    # retain, mirroring the local pending buffer's
                    # overflow contract (bounded per trace, not just
                    # per trace COUNT).
                    del _remote_pending[tid]
                    overflow.extend(entry[1])
                    overflow.append(line)
                else:
                    entry[1].append(line)
            else:
                direct.append(line)
    if overflow:
        _write_lines(overflow)
    if direct:
        _write_lines(direct)
    _remote_sweep(now)


# --- Cross-process tail verdicts (the verdict sidecar) ----------------

def _verdict_path() -> Optional[str]:
    with _sink_lock:
        path = _sink_path
    if path is None:
        return None
    return os.path.join(os.path.dirname(path), VERDICT_FILE)


def _write_verdict(trace_id: str, verdict: str) -> None:
    """Append one retain/drop verdict line (minting edge only) so
    OTHER processes' held spans can honor it. Bounded: the file rolls
    once to ``.1`` at the size cap — verdicts only matter for the hold
    window, so losing old ones degrades to retain-on-doubt."""
    global _verdict_sink
    path = _verdict_path()
    if path is None:
        return
    line = json.dumps({"t": trace_id, "v": verdict},
                      separators=(",", ":")) + "\n"
    with _sink_lock:
        try:
            f = _verdict_sink
            if f is None or f.closed or f.name != path:
                os.makedirs(os.path.dirname(path) or ".",
                            exist_ok=True)
                # rta: disable=RTA105 same sink-bind idiom as _write_lines: the lock guards the handle, the lazy open is the bind
                _verdict_sink = f = open(path, "a", encoding="utf-8")
            f.write(line)
            f.flush()
            if f.tell() > _VERDICT_MAX_BYTES:
                f.close()
                _verdict_sink = None
                os.replace(path, path + ".1")
        except OSError:
            _verdict_sink = None


def _refresh_verdicts() -> None:
    """Incrementally fold new verdict-sidecar lines into the bounded
    verdict map (inode-aware: a roll by the writing process resets the
    read position)."""
    path = _verdict_path()
    if path is None:
        return
    try:
        st = os.stat(path)
    except OSError:
        return
    ident = (st.st_ino, st.st_dev)
    with _tail_lock:
        pos, prev_ident = _verdict_reader
        if prev_ident != ident or pos > st.st_size:
            pos = 0
        if st.st_size <= pos:
            _verdict_reader[:] = [pos, ident]
            return
    updates: Dict[str, str] = {}
    try:
        with open(path, "rb") as f:
            f.seek(pos)
            for raw in f:
                if not raw.endswith(b"\n"):
                    break  # torn tail write; re-read next refresh
                pos += len(raw)
                try:
                    rec = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                tid, v = rec.get("t"), rec.get("v")
                if isinstance(tid, str) and v in ("kept", "dropped"):
                    updates[tid] = v
    except OSError:
        return
    with _tail_lock:
        _verdict_reader[:] = [pos, ident]
        _verdicts.update(updates)
        while len(_verdicts) > _VERDICT_REMEMBER:
            _verdicts.pop(next(iter(_verdicts)))


def _remote_sweep(now: Optional[float] = None,
                  force: bool = False) -> None:
    """Resolve held remote-tail spans: a ``dropped`` verdict suppresses
    them (the orphan-rate win), ``kept`` — or hold expiry / ``force``
    with no verdict — writes them (retain-on-doubt)."""
    with _tail_lock:
        any_pending = bool(_remote_pending)
    if not any_pending:
        return
    _refresh_verdicts()
    if now is None:
        now = time.monotonic()
    write: List[str] = []
    kept = dropped = 0
    with _tail_lock:
        for tid in list(_remote_pending):
            deadline, lines = _remote_pending[tid]
            v = _verdicts.get(tid)
            if v == "dropped":
                del _remote_pending[tid]
                dropped += 1
            elif v == "kept" or force or now >= deadline:
                del _remote_pending[tid]
                write.extend(lines)
                kept += 1
    if write:
        _write_lines(write)
    for verdict, n in (("remote_kept", kept),
                       ("remote_dropped", dropped)):
        if n:
            try:
                _tail_counter().inc(n, verdict=verdict)
            except Exception:
                pass


def flush_remote_tail() -> None:
    """Resolve every held remote-tail span NOW (verdicts honored when
    already known, everything else retained) — shutdown/reconfigure
    hygiene and the test seam."""
    _remote_sweep(force=True)


def flush_remote_expired() -> None:
    """Resolve remote-held spans whose verdict arrived or whose hold
    deadline passed. The routine sweep rides every span write, but an
    IDLE worker writes none — long-poll loops (the inference worker's
    serve loop) call this per iteration so a quiet worker's held spans
    still honor the edge's verdict within ~one poll interval instead
    of waiting for its next burst. One lock check when nothing is
    pending."""
    _remote_sweep()


def complete(ctx: Optional[TraceContext], dur_s: float,
             error: bool = False) -> None:
    """The tail-sampling verdict, called by the minting edge when its
    request finishes: error and slow-over-threshold traces always
    flush to the store; fast/ok ones keep with the tail sample rate.
    No-op for non-tail contexts (honored headers, head-sampled legacy
    mode)."""
    if ctx is None or not ctx.tail:
        return
    rate = tail_sample_rate()
    with _tail_lock:
        lines = _tail_pending.pop(ctx.trace_id, None)
        if lines is None:
            verdict = None  # already flushed (overflow) — retained
        elif error:
            verdict = "kept_error"
        elif dur_s * 1e3 >= tail_slow_ms():
            verdict = "kept_slow"
        elif rate is None or _tail_rng.random() < rate:
            verdict = "kept_sampled"
        else:
            verdict = "dropped"
            _tail_dropped[ctx.trace_id] = None
            while len(_tail_dropped) > _DROPPED_REMEMBER:
                _tail_dropped.pop(next(iter(_tail_dropped)))
    # The verdict rides the sidecar EITHER WAY (overflow counts as
    # kept): a subprocess worker holding this trace's spans needs the
    # retain signal as much as the drop.
    _write_verdict(ctx.trace_id,
                   "dropped" if verdict == "dropped" else "kept")
    if verdict is None:
        return
    if verdict != "dropped" and lines:
        _write_lines(lines)
    try:
        # rta: disable=RTA301 verdict is the fixed vocabulary in _tail_counter's help; process-global family, deliberately immortal
        _tail_counter().inc(verdict=verdict)
    except Exception:
        pass


def _tail_counter():
    from . import metrics

    return metrics.registry().counter(
        "rafiki_tpu_trace_tail_total",
        "Tail-sampling verdicts (verdict=kept_error|kept_slow|"
        "kept_sampled|dropped at the minting edge; remote_kept|"
        "remote_dropped for held spans of edge-minted traces resolved "
        "in this process)")


def _tail_flush_all() -> None:
    with _tail_lock:
        pending = list(_tail_pending.values())
        _tail_pending.clear()
    for lines in pending:
        if lines:
            _write_lines(lines)


def exemplar_ok(ctx: TraceContext) -> bool:
    """Whether a metric exemplar may reference this trace: a
    tail-PENDING trace's verdict could still drop its spans, and a
    dropped trace's exemplar would link to an empty timeline. Non-tail
    contexts (honored headers, tail-off mode) and tail traces whose
    verdict KEPT them qualify; pending/dropped ones don't — the
    exemplar under-captures rather than dangles."""
    if not ctx.tail:
        return True
    with _tail_lock:
        return ctx.trace_id not in _tail_pending and \
            ctx.trace_id not in _tail_dropped


def seed_tail(seed: int) -> None:
    """Deterministic tail-sampling decisions (tests / seeded bench)."""
    global _tail_rng
    with _tail_lock:
        _tail_rng = random.Random(seed)


def reset_tail_for_tests() -> None:
    with _tail_lock:
        _tail_pending.clear()
        _tail_dropped.clear()
        _remote_pending.clear()
        _verdicts.clear()
        _verdict_reader[:] = [0, None]


def record_event(name: str, service: str,
                 ctxs: Iterable[Optional[TraceContext]],
                 start_wall: float, dur_s: float,
                 attrs: Optional[Dict[str, Any]] = None,
                 child: bool = True) -> None:
    """Append one span event per traced context. ``child=True`` (the
    common case) records a NEW span parented on each context's span;
    ``child=False`` records the context's own span (the HTTP edge,
    which minted it)."""
    # rta: disable=RTA101 hot-path early-out on the sink pointer (GIL-atomic reference read); the append path re-reads under _sink_lock
    if _sink_path is None:
        return
    lines: List[Tuple[Optional[TraceContext], str]] = []
    for ctx in ctxs:
        if ctx is None:
            continue
        span = {
            "trace_id": ctx.trace_id,
            "span_id": new_span_id() if child else ctx.span_id,
            "parent_id": ctx.span_id if child else ctx.parent_id,
            "name": name,
            "service": service,
            "start_s": round(start_wall, 6),
            "dur_ms": round(dur_s * 1e3, 3),
        }
        if attrs:
            span["attrs"] = attrs
        lines.append((ctx,
                      json.dumps(span, separators=(",", ":")) + "\n"))
    if lines:
        _tail_route(lines)


class span:
    """``with trace.span("worker.predict", service=sid, ctxs=...)`` —
    times the block (monotonic) and records the event(s) at exit.
    No-ops entirely when nothing is traced or no sink is configured."""

    def __init__(self, name: str, service: str = "",
                 ctxs: Optional[Iterable[Optional[TraceContext]]] = None,
                 attrs: Optional[Dict[str, Any]] = None,
                 child: bool = True):
        self.name = name
        self.service = service
        self.attrs = attrs
        self.child = child
        self._ctxs = list(ctxs) if ctxs is not None else None

    def __enter__(self):
        if self._ctxs is None:
            cur = current()
            self._ctxs = [cur] if cur is not None else []
        self._wall = time.time()
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        if self._ctxs and _sink_path is not None:
            record_event(self.name, self.service, self._ctxs, self._wall,
                         time.monotonic() - self._t0, attrs=self.attrs,
                         child=self.child)
        return False


# --- Stitching (admin's GET /trace/<id>) ------------------------------

def segment_paths(log_dir: str) -> List[str]:
    """Store segments oldest-first: rolled generations ``.N`` .. ``.1``
    then the active file (only the ones that exist)."""
    path = span_log_path(log_dir)
    out = [f"{path}.{k}"
           for k in range(retain_segments(), 0, -1)
           if os.path.exists(f"{path}.{k}")]
    if os.path.exists(path):
        out.append(path)
    return out


def _active_offsets(path: str, trace_id: str) -> Tuple[List[int], int]:
    """The active segment's offsets for one trace via the incremental
    scan cache; second value is the bytes scanned by THIS lookup (the
    appended tail only, 0 on a warm repeat). The cache entry carries
    the file's inode: a roll performed by ANOTHER process replaces the
    active file (``os.replace`` + fresh create), and a size check
    alone would miss it whenever the new file has already grown past
    the cached scan position — stale offsets against new content would
    silently truncate timelines."""
    try:
        st = os.stat(path)
        size, ident = st.st_size, (st.st_ino, st.st_dev)
    except OSError:
        return [], 0
    with _active_lock:
        entry = _active_cache.get(path)
        if entry is None or entry[0] > size or entry[2] != ident:
            entry = [0, {}, ident]  # rolled/truncated/replaced: reset
            _active_cache[path] = entry
        scanned_from = entry[0]
        if size > entry[0]:
            # rta: disable=RTA105 the scan must fold into the cache entry atomically — two threads scanning the same tail concurrently would double-append offsets
            fresh, pos = _scan_offsets(path, start=entry[0])
            for tid, offs in fresh.items():
                entry[1].setdefault(tid, []).extend(offs)
            entry[0] = pos
        offsets = list(entry[1].get(trace_id, ()))
    return offsets, max(0, size - scanned_from)


def collect_trace(log_dir: str, trace_id: str,
                  max_spans: int = 1000) -> Dict[str, Any]:
    """Stitch every span of one trace across the segmented store into
    an ordered timeline. Frozen segments are INDEXED reads (sidecar
    ``.idx`` built at roll time, rebuilt lazily if missing): a seek
    and one readline per matching span, never a full-segment scan.
    The active segment rides the incremental scan cache — only bytes
    appended since the previous lookup are read. The per-segment
    ``segments`` diagnostics (mode + bytes_read) are what the indexed-
    read regression test pins. A corrupt line is skipped, never
    fatal."""
    path = span_log_path(log_dir)
    spans: List[Dict[str, Any]] = []
    diags: List[Dict[str, Any]] = []
    for p in segment_paths(log_dir):
        if len(spans) >= max_spans:
            break
        compacted = None
        if p == path:
            offsets, scanned = _active_offsets(p, trace_id)
            mode, overhead = "scan_tail", scanned
            try:
                _store_counter().inc(event="tail_scan")
            except Exception:
                pass
        else:
            data = _load_index_data(p)
            if data is None:
                try:
                    index = _build_index(p)
                    mode, compacted = "index_rebuilt", False
                except OSError:
                    continue
            else:
                index = data["traces"]
                mode = "index"
                compacted = bool(data.get("compacted"))
            try:
                _store_counter().inc(event="index_read")
            except Exception:
                pass
            offsets, overhead = list(index.get(trace_id, ())), 0
        lines, n_bytes = _read_lines_at(p, offsets[:max_spans
                                                   - len(spans)])
        for line in lines:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("trace_id") == trace_id:
                spans.append(rec)
        diag = {"segment": os.path.basename(p), "mode": mode,
                "n_spans": len(lines),
                "bytes_read": n_bytes + overhead}
        if compacted is not None:
            # Frozen segments report whether the idle-time compaction
            # pass already rewrote them to only-retained traces.
            diag["compacted"] = compacted
        diags.append(diag)
    spans.sort(key=lambda s: (s.get("start_s", 0.0), s.get("name", "")))
    t0 = spans[0].get("start_s", 0.0) if spans else 0.0
    for s in spans:
        s["offset_ms"] = round((s.get("start_s", t0) - t0) * 1e3, 3)
    return {"trace_id": trace_id, "n_spans": len(spans),
            "spans": spans, "segments": diags}
