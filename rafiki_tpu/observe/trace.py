"""End-to-end request tracing: trace ids across HTTP edge, bus, worker.

Dapper-shaped, sized for this system: a trace id is minted (or adopted
from an ``X-Trace-Id`` request header) at the admin/predictor HTTP
edges, carried thread-locally through the handler, captured by the
micro-batcher at admission, injected into the bus message envelope at
scatter (``"_trace"`` key — old frames simply lack it, old consumers
ignore it: both directions of the version skew degrade to "no trace"),
and recovered by the inference worker on the far side of the bus.

Span *events* are flat JSONL lines appended to one shared file per log
dir (``<log_dir>/spans.jsonl`` — the same directory
``utils/service_logs`` gives every service), written with O_APPEND
semantics so resident-runner threads and subprocess services
interleave whole lines. ``Admin.get_trace`` (``GET /trace/<id>``)
stitches the file's lines for one trace id into an ordered timeline —
"why was this /predict slow" is one curl.

Knobs: ``RAFIKI_TPU_TRACE_SAMPLE`` (0..1, default 1.0) samples freshly
minted traces at the edge; a request that ARRIVES with a trace id is
always honored (the caller already decided to trace it). Sampling out
costs nothing downstream — no context means no envelope field and no
span writes.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
import uuid
from typing import Any, Dict, Iterable, List, Optional

_log = logging.getLogger(__name__)

TRACE_SAMPLE_ENV = "RAFIKI_TPU_TRACE_SAMPLE"
TRACE_MAX_MB_ENV = "RAFIKI_TPU_TRACE_MAX_MB"
TRACE_HEADER = "X-Trace-Id"

#: Envelope key inside bus message frames. Absent on old frames (the
#: backward-compatible fallback: extract() returns no contexts) and
#: ignored by old consumers (frame readers key on "query"/"queries").
ENVELOPE_KEY = "_trace"

#: A super-batch coalesces many requests; the envelope carries at most
#: this many of their contexts (the worker records one span event per
#: carried trace).
MAX_ENVELOPE_TRACES = 32

SPAN_FILE = "spans.jsonl"


def new_trace_id() -> str:
    return uuid.uuid4().hex


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


class TraceContext:
    """One request's position in its trace: the trace id plus the
    CURRENT span id (children parent onto it)."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: Optional[str] = None,
                 parent_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id or new_span_id()
        self.parent_id = parent_id

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, parent_id=self.span_id)

    def header_value(self) -> str:
        return f"{self.trace_id}-{self.span_id}"

    def __repr__(self):  # pragma: no cover - debug aid
        return f"TraceContext({self.trace_id[:8]}…/{self.span_id})"


# --- Thread-local current context ------------------------------------

_local = threading.local()


def current() -> Optional[TraceContext]:
    return getattr(_local, "ctx", None)


class use:
    """``with trace.use(ctx): ...`` — bind/restore the thread's current
    context. ``ctx=None`` clears for the duration."""

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx

    def __enter__(self):
        self._prior = current()
        _local.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _local.ctx = self._prior
        return False


def sample_rate() -> float:
    raw = os.environ.get(TRACE_SAMPLE_ENV, "").strip()
    if not raw:
        return 1.0
    try:
        return min(1.0, max(0.0, float(raw)))
    except ValueError:
        return 1.0


_HEADER_RE = None


def start_trace(header: Optional[str] = None) -> Optional[TraceContext]:
    """Context for one incoming edge request. An ``X-Trace-Id`` header
    is always honored: our own ``<32hex>-<16hex>`` format splits into
    trace + parent span; ANY other non-empty value (a dashed UUID, an
    opaque upstream id) is taken whole as the trace id — splitting at
    a dash would silently truncate standard ``str(uuid4())`` ids.
    Otherwise a fresh trace is minted subject to the sample rate
    (None = sampled out)."""
    global _HEADER_RE
    if header and header.strip():
        import re

        if _HEADER_RE is None:
            _HEADER_RE = re.compile(
                r"^([0-9a-fA-F]{32})-([0-9a-fA-F]{16})$")
        value = header.strip()
        match = _HEADER_RE.match(value)
        if match:
            return TraceContext(match.group(1),
                                parent_id=match.group(2))
        return TraceContext(value)
    rate = sample_rate()
    if rate <= 0.0 or (rate < 1.0 and random.random() >= rate):
        return None
    return TraceContext(new_trace_id())


# --- Envelope carry (bus frames) --------------------------------------

def inject(ctxs: Iterable[Optional[TraceContext]]) -> Optional[Dict]:
    """Envelope field for a bus frame carrying these requests' traces,
    or None when nothing is traced (the frame then looks exactly like
    an old frame)."""
    ids = [[c.trace_id, c.span_id] for c in ctxs
           if c is not None][:MAX_ENVELOPE_TRACES]
    if not ids:
        return None
    return {"ids": ids}


def extract(frame: Any) -> List[TraceContext]:
    """Pop the trace envelope off a bus frame dict. Old frames (no
    ``_trace`` key) and malformed envelopes return ``[]`` — tracing
    must never fail a query.

    The returned contexts CONTINUE the propagated spans (same span id),
    so a consumer's ``record_event(child=True)`` parents its span onto
    the span that sent the frame."""
    if not isinstance(frame, dict):
        return []
    env = frame.pop(ENVELOPE_KEY, None)
    if not isinstance(env, dict):
        return []
    out = []
    try:
        for tid, sid in env.get("ids", []):
            out.append(TraceContext(str(tid), span_id=str(sid)))
    except (TypeError, ValueError):
        return []
    return out


def extract_frames(frames: Iterable[Any]) -> List[TraceContext]:
    """Extract across a popped burst, deduplicated by trace id (a
    worker burst may drain several frames of one super-batch)."""
    seen = set()
    out: List[TraceContext] = []
    for frame in frames:
        for ctx in extract(frame):
            if ctx.trace_id not in seen:
                seen.add(ctx.trace_id)
                out.append(ctx)
    return out


# --- Span sink (JSONL through the service log dir) --------------------

_sink_lock = threading.Lock()
_sink_path: Optional[str] = None
_sink_file = None


def span_log_path(log_dir: str) -> str:
    return os.path.join(log_dir, SPAN_FILE)


def configure(log_dir: Optional[str]) -> None:
    """Point this process's span sink at ``<log_dir>/spans.jsonl``
    (append; created on first span). ``None``/"" disables recording.
    Resident-runner mode configures once per platform; subprocess
    services configure from their ``RAFIKI_TPU_LOG_DIR`` env."""
    global _sink_path, _sink_file
    with _sink_lock:
        if _sink_file is not None:
            try:
                _sink_file.close()
            except OSError:
                pass
            _sink_file = None
        _sink_path = span_log_path(log_dir) if log_dir else None


def configured() -> bool:
    return _sink_path is not None


def _max_span_bytes() -> int:
    try:
        return int(float(os.environ.get(TRACE_MAX_MB_ENV, "64"))
                   * 1024 * 1024)
    except ValueError:
        return 64 * 1024 * 1024


def _write_lines(lines: List[str]) -> None:
    global _sink_file
    with _sink_lock:
        if _sink_path is None:
            return
        try:
            if _sink_file is None or _sink_file.closed:
                os.makedirs(os.path.dirname(_sink_path) or ".",
                            exist_ok=True)
                _sink_file = open(_sink_path, "a", encoding="utf-8")
            _sink_file.write("".join(lines))
            _sink_file.flush()
            # Size cap (RAFIKI_TPU_TRACE_MAX_MB, default 64): roll to
            # ONE .1 generation so a busy node (or a client that always
            # sends X-Trace-Id, bypassing sampling) cannot fill the
            # disk. Append mode means tell() is the file size; a
            # concurrent multi-process rotation race is benign — the
            # atomic replace at worst drops some spans of one
            # generation.
            if _sink_file.tell() > _max_span_bytes():
                _sink_file.close()
                _sink_file = None
                os.replace(_sink_path, _sink_path + ".1")
        except OSError:  # sink dir vanished (test teardown); drop spans
            _sink_file = None


def record_event(name: str, service: str,
                 ctxs: Iterable[Optional[TraceContext]],
                 start_wall: float, dur_s: float,
                 attrs: Optional[Dict[str, Any]] = None,
                 child: bool = True) -> None:
    """Append one span event per traced context. ``child=True`` (the
    common case) records a NEW span parented on each context's span;
    ``child=False`` records the context's own span (the HTTP edge,
    which minted it)."""
    if _sink_path is None:
        return
    lines = []
    for ctx in ctxs:
        if ctx is None:
            continue
        span = {
            "trace_id": ctx.trace_id,
            "span_id": new_span_id() if child else ctx.span_id,
            "parent_id": ctx.span_id if child else ctx.parent_id,
            "name": name,
            "service": service,
            "start_s": round(start_wall, 6),
            "dur_ms": round(dur_s * 1e3, 3),
        }
        if attrs:
            span["attrs"] = attrs
        lines.append(json.dumps(span, separators=(",", ":")) + "\n")
    if lines:
        _write_lines(lines)
        from . import metrics

        metrics.registry().counter(
            "rafiki_tpu_trace_spans_total",
            "Span events recorded to the span log").inc(len(lines))


class span:
    """``with trace.span("worker.predict", service=sid, ctxs=...)`` —
    times the block (monotonic) and records the event(s) at exit.
    No-ops entirely when nothing is traced or no sink is configured."""

    def __init__(self, name: str, service: str = "",
                 ctxs: Optional[Iterable[Optional[TraceContext]]] = None,
                 attrs: Optional[Dict[str, Any]] = None,
                 child: bool = True):
        self.name = name
        self.service = service
        self.attrs = attrs
        self.child = child
        self._ctxs = list(ctxs) if ctxs is not None else None

    def __enter__(self):
        if self._ctxs is None:
            cur = current()
            self._ctxs = [cur] if cur is not None else []
        self._wall = time.time()
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        if self._ctxs and _sink_path is not None:
            record_event(self.name, self.service, self._ctxs, self._wall,
                         time.monotonic() - self._t0, attrs=self.attrs,
                         child=self.child)
        return False


# --- Stitching (admin's GET /trace/<id>) ------------------------------

def collect_trace(log_dir: str, trace_id: str,
                  max_spans: int = 1000) -> Dict[str, Any]:
    """Read ``<log_dir>/spans.jsonl`` (plus its rolled ``.1``
    generation) and stitch every span of one trace into an ordered
    timeline. The scan is substring-first (cheap reject) then
    JSON-parse; a corrupt line is skipped, never fatal."""
    path = span_log_path(log_dir)
    spans: List[Dict[str, Any]] = []
    for p in (path + ".1", path):
        if len(spans) >= max_spans:
            break
        try:
            with open(p, "r", encoding="utf-8", errors="replace") as f:
                for line in f:
                    if trace_id not in line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if rec.get("trace_id") == trace_id:
                        spans.append(rec)
                        if len(spans) >= max_spans:
                            break
        except OSError:
            continue
    spans.sort(key=lambda s: (s.get("start_s", 0.0), s.get("name", "")))
    t0 = spans[0].get("start_s", 0.0) if spans else 0.0
    for s in spans:
        s["offset_ms"] = round((s.get("start_s", t0) - t0) * 1e3, 3)
    return {"trace_id": trace_id, "n_spans": len(spans), "spans": spans}
