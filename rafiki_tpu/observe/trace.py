"""End-to-end request tracing: trace ids across HTTP edge, bus, worker.

Dapper-shaped, sized for this system: a trace id is minted (or adopted
from an ``X-Trace-Id`` request header) at the admin/predictor HTTP
edges, carried thread-locally through the handler, captured by the
micro-batcher at admission, injected into the bus message envelope at
scatter (``"_trace"`` key — old frames simply lack it, old consumers
ignore it: both directions of the version skew degrade to "no trace"),
and recovered by the inference worker on the far side of the bus.

Span *events* are flat JSONL lines appended to a **segmented store**
under the log dir (``utils/service_logs`` gives every service the same
directory): the active segment is ``spans.jsonl``, written with
O_APPEND semantics so resident-runner threads and subprocess services
interleave whole lines; at ``RAFIKI_TPU_TRACE_MAX_MB`` it rolls to
``spans.jsonl.1`` (older generations shift to ``.2`` .. ``.N``), with
retention bounded by ``RAFIKI_TPU_TRACE_RETAIN_SEGMENTS`` (generation
count) and ``RAFIKI_TPU_TRACE_RETAIN_MB`` (total rolled bytes). Each
frozen segment gets a **sidecar index** (``<segment>.idx``: trace id →
byte offsets) built once at roll time, so ``Admin.get_trace``
(``GET /trace/<id>``) is an indexed seek-and-read per frozen segment
instead of a full-store scan; the active segment is covered by an
incremental in-process scan cache that only ever reads the appended
tail. "Why was this /predict slow, yesterday" stays one curl on a
busy node.

Sampling, two stages:

- **Head** (``RAFIKI_TPU_TRACE_SAMPLE``, 0..1, default 1.0) samples
  freshly minted traces at the edge; a request that ARRIVES with a
  trace id is always honored (the caller already decided to trace it).
  Sampling out costs nothing downstream — no context means no envelope
  field and no span writes.
- **Tail** (``RAFIKI_TPU_TRACE_TAIL_SAMPLE`` < 1.0 enables): spans of
  freshly minted traces are buffered in memory until the minting edge
  completes its request, then the verdict is made on the OUTCOME —
  error responses and requests slower than
  ``RAFIKI_TPU_TRACE_TAIL_SLOW_MS`` are always retained, fast/ok ones
  are kept at the tail sample rate. The interesting 1% survives a
  sample rate that would have dropped it head-side. Per-process by
  construction: spans recorded by a *different* process (subprocess
  workers) are written eagerly and can't be un-written — the orphan
  spans of a dropped trace are the documented cost of not running a
  central collector.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
import uuid
from typing import Any, Dict, Iterable, List, Optional, Tuple

_log = logging.getLogger(__name__)

TRACE_SAMPLE_ENV = "RAFIKI_TPU_TRACE_SAMPLE"
TRACE_MAX_MB_ENV = "RAFIKI_TPU_TRACE_MAX_MB"
TRACE_RETAIN_SEGMENTS_ENV = "RAFIKI_TPU_TRACE_RETAIN_SEGMENTS"
TRACE_RETAIN_MB_ENV = "RAFIKI_TPU_TRACE_RETAIN_MB"
TRACE_TAIL_SAMPLE_ENV = "RAFIKI_TPU_TRACE_TAIL_SAMPLE"
TRACE_TAIL_SLOW_MS_ENV = "RAFIKI_TPU_TRACE_TAIL_SLOW_MS"
TRACE_HEADER = "X-Trace-Id"

#: Envelope key inside bus message frames. Absent on old frames (the
#: backward-compatible fallback: extract() returns no contexts) and
#: ignored by old consumers (frame readers key on "query"/"queries").
ENVELOPE_KEY = "_trace"

#: A super-batch coalesces many requests; the envelope carries at most
#: this many of their contexts (the worker records one span event per
#: carried trace).
MAX_ENVELOPE_TRACES = 32

SPAN_FILE = "spans.jsonl"
INDEX_SUFFIX = ".idx"

#: Tail-sampling buffer bounds: a pending trace whose edge never
#: completes (crashed handler, client that holds the socket forever)
#: must not grow memory without bound — overflowing traces/spans are
#: flushed to the store (retain-on-doubt, never silently dropped).
_PENDING_MAX_TRACES = 512
_PENDING_MAX_SPANS = 200
#: Recently-dropped trace ids remembered so a straggler span arriving
#: after the tail verdict (a late worker reply) doesn't resurrect a
#: dropped trace as orphan lines.
_DROPPED_REMEMBER = 1024


def new_trace_id() -> str:
    return uuid.uuid4().hex


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


class TraceContext:
    """One request's position in its trace: the trace id plus the
    CURRENT span id (children parent onto it). ``tail=True`` marks a
    context whose retention verdict is deferred to edge completion
    (set only on the minting edge, under tail sampling)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "tail")

    def __init__(self, trace_id: str, span_id: Optional[str] = None,
                 parent_id: Optional[str] = None, tail: bool = False):
        self.trace_id = trace_id
        self.span_id = span_id or new_span_id()
        self.parent_id = parent_id
        self.tail = tail

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, parent_id=self.span_id,
                            tail=self.tail)

    def header_value(self) -> str:
        return f"{self.trace_id}-{self.span_id}"

    def __repr__(self):  # pragma: no cover - debug aid
        return f"TraceContext({self.trace_id[:8]}…/{self.span_id})"


# --- Thread-local current context ------------------------------------

_local = threading.local()


def current() -> Optional[TraceContext]:
    return getattr(_local, "ctx", None)


class use:
    """``with trace.use(ctx): ...`` — bind/restore the thread's current
    context. ``ctx=None`` clears for the duration."""

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx

    def __enter__(self):
        self._prior = current()
        _local.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _local.ctx = self._prior
        return False


def sample_rate() -> float:
    raw = os.environ.get(TRACE_SAMPLE_ENV, "").strip()
    if not raw:
        return 1.0
    try:
        return min(1.0, max(0.0, float(raw)))
    except ValueError:
        return 1.0


def tail_sample_rate() -> Optional[float]:
    """The tail-sampling keep rate for fast/ok traces, or None when
    tail sampling is off (unset / 1.0 / unparseable — fail toward the
    legacy keep-everything behavior)."""
    raw = os.environ.get(TRACE_TAIL_SAMPLE_ENV, "").strip()
    if not raw:
        return None
    try:
        rate = float(raw)
    except ValueError:
        return None
    if rate >= 1.0:
        return None
    return max(0.0, rate)


def tail_slow_ms() -> float:
    try:
        return max(0.0, float(os.environ.get(TRACE_TAIL_SLOW_MS_ENV,
                                             "250") or 250))
    except ValueError:
        return 250.0


_HEADER_RE = None


def start_trace(header: Optional[str] = None) -> Optional[TraceContext]:
    """Context for one incoming edge request. An ``X-Trace-Id`` header
    is always honored: our own ``<32hex>-<16hex>`` format splits into
    trace + parent span; ANY other non-empty value (a dashed UUID, an
    opaque upstream id) is taken whole as the trace id — splitting at
    a dash would silently truncate standard ``str(uuid4())`` ids.
    Honored traces are never tail-buffered (the caller already decided
    to retain). Otherwise a fresh trace is minted subject to the head
    sample rate (None = sampled out); under tail sampling the fresh
    trace is registered PENDING — its spans buffer until
    :func:`complete` delivers the outcome verdict."""
    global _HEADER_RE
    if header and header.strip():
        import re

        if _HEADER_RE is None:
            _HEADER_RE = re.compile(
                r"^([0-9a-fA-F]{32})-([0-9a-fA-F]{16})$")
        value = header.strip()
        match = _HEADER_RE.match(value)
        if match:
            return TraceContext(match.group(1),
                                parent_id=match.group(2))
        return TraceContext(value)
    rate = sample_rate()
    if rate <= 0.0 or (rate < 1.0 and random.random() >= rate):
        return None
    ctx = TraceContext(new_trace_id())
    if tail_sample_rate() is not None and _sink_path is not None:
        ctx.tail = True
        _tail_register(ctx.trace_id)
    return ctx


# --- Envelope carry (bus frames) --------------------------------------

def inject(ctxs: Iterable[Optional[TraceContext]]) -> Optional[Dict]:
    """Envelope field for a bus frame carrying these requests' traces,
    or None when nothing is traced (the frame then looks exactly like
    an old frame)."""
    ids = [[c.trace_id, c.span_id] for c in ctxs
           if c is not None][:MAX_ENVELOPE_TRACES]
    if not ids:
        return None
    return {"ids": ids}


def extract(frame: Any) -> List[TraceContext]:
    """Pop the trace envelope off a bus frame dict. Old frames (no
    ``_trace`` key) and malformed envelopes return ``[]`` — tracing
    must never fail a query.

    The returned contexts CONTINUE the propagated spans (same span id),
    so a consumer's ``record_event(child=True)`` parents its span onto
    the span that sent the frame."""
    if not isinstance(frame, dict):
        return []
    env = frame.pop(ENVELOPE_KEY, None)
    if not isinstance(env, dict):
        return []
    out = []
    try:
        for tid, sid in env.get("ids", []):
            out.append(TraceContext(str(tid), span_id=str(sid)))
    except (TypeError, ValueError):
        return []
    return out


def extract_frames(frames: Iterable[Any]) -> List[TraceContext]:
    """Extract across a popped burst, deduplicated by trace id (a
    worker burst may drain several frames of one super-batch)."""
    seen = set()
    out: List[TraceContext] = []
    for frame in frames:
        for ctx in extract(frame):
            if ctx.trace_id not in seen:
                seen.add(ctx.trace_id)
                out.append(ctx)
    return out


# --- Span sink (segmented JSONL store through the service log dir) ----

_sink_lock = threading.Lock()
_sink_path: Optional[str] = None
_sink_file = None

# Tail-sampling state: pending (buffered) trace ids -> span lines, an
# insertion-ordered dict so overflow flushes the OLDEST pending trace;
# recently dropped ids suppress straggler spans.
_tail_lock = threading.Lock()
_tail_pending: "Dict[str, List[str]]" = {}
_tail_dropped: "Dict[str, None]" = {}
_tail_rng = random.Random()

# Incremental scan cache for the ACTIVE segment: path -> [bytes
# scanned, {trace_id: [line offsets]}]. Lookups only ever read the
# tail appended since the previous lookup.
_active_lock = threading.Lock()
_active_cache: Dict[str, List[Any]] = {}


def span_log_path(log_dir: str) -> str:
    return os.path.join(log_dir, SPAN_FILE)


def configure(log_dir: Optional[str]) -> None:
    """Point this process's span sink at ``<log_dir>/spans.jsonl``
    (append; created on first span). ``None``/"" disables recording.
    Resident-runner mode configures once per platform; subprocess
    services configure from their ``RAFIKI_TPU_LOG_DIR`` env. Any
    tail-pending buffers are flushed to the OLD sink first (retained:
    reconfiguring must not silently eat buffered spans)."""
    global _sink_path, _sink_file
    _tail_flush_all()
    with _sink_lock:
        if _sink_file is not None:
            try:
                _sink_file.close()
            except OSError:
                pass
            _sink_file = None
        _sink_path = span_log_path(log_dir) if log_dir else None


def configured() -> bool:
    return _sink_path is not None


def _max_span_bytes() -> int:
    try:
        return int(float(os.environ.get(TRACE_MAX_MB_ENV, "64"))
                   * 1024 * 1024)
    except ValueError:
        return 64 * 1024 * 1024


def retain_segments() -> int:
    """Rolled generations kept (``.1`` .. ``.N``). Default 4; the
    pre-r17 single-``.1`` behavior is ``=1``."""
    try:
        return max(1, int(os.environ.get(TRACE_RETAIN_SEGMENTS_ENV,
                                         "4") or 4))
    except ValueError:
        return 4


def _retain_total_bytes() -> int:
    try:
        return int(float(os.environ.get(TRACE_RETAIN_MB_ENV, "256")
                         or 256) * 1024 * 1024)
    except ValueError:
        return 256 * 1024 * 1024


def _store_counter():
    from . import metrics

    return metrics.registry().counter(
        "rafiki_tpu_trace_store_total",
        "Trace span-store events (event=roll|index_build|index_read|"
        "tail_scan)")


def _write_lines(lines: List[str]) -> None:
    global _sink_file
    wrote = 0
    rolled: Optional[str] = None
    with _sink_lock:
        if _sink_path is None:
            return
        try:
            if _sink_file is None or _sink_file.closed:
                os.makedirs(os.path.dirname(_sink_path) or ".",
                            exist_ok=True)
                _sink_file = open(_sink_path, "a", encoding="utf-8")
            _sink_file.write("".join(lines))
            _sink_file.flush()
            wrote = len(lines)
            # Size cap (RAFIKI_TPU_TRACE_MAX_MB, default 64): roll the
            # active segment into the retained generation chain so a
            # busy node (or a client that always sends X-Trace-Id,
            # bypassing sampling) cannot fill the disk while multi-day
            # lookback stays possible. Append mode means tell() is the
            # file size; a concurrent multi-process rotation race is
            # benign — the atomic replaces at worst drop some spans of
            # one generation.
            if _sink_file.tell() > _max_span_bytes():
                _sink_file.close()
                _sink_file = None
                rolled = _roll_segments(_sink_path)
        except OSError:  # sink dir vanished (test teardown); drop spans
            _sink_file = None
    if rolled is not None:
        # The sidecar index scans the whole frozen segment — done
        # OUTSIDE the sink lock, or every in-flight handler's span
        # write (and tail flush) would stall behind a multi-MB read at
        # each roll. The segment is frozen, so nothing races the scan;
        # a reader arriving before the .idx lands just rebuilds it
        # lazily (the _load_index fallback).
        try:
            _build_index(rolled)
        except OSError:
            pass
    if wrote:
        # Counted at WRITE time (outside the sink lock), so a tail-
        # buffered span only counts once its trace's verdict actually
        # lands it in the store — the bench's overhead delta reads
        # spans that exist, not spans that were considered.
        from . import metrics

        metrics.registry().counter(
            "rafiki_tpu_trace_spans_total",
            "Span events written to the span log").inc(wrote)


def _roll_segments(path: str) -> Optional[str]:
    """Shift the generation chain (``.k`` -> ``.k+1``, oldest beyond
    the retention bounds deleted) and freeze the active file as
    ``.1``; returns the frozen segment's path so the CALLER can build
    its sidecar index outside the sink lock (None when the freeze
    itself failed). Caller holds ``_sink_lock``."""
    n = retain_segments()
    # Drop the generation that would shift past the count bound.
    for stale in (f"{path}.{n}", f"{path}.{n}{INDEX_SUFFIX}"):
        try:
            os.remove(stale)
        except OSError:
            pass
    for k in range(n - 1, 0, -1):
        for suffix in (INDEX_SUFFIX, ""):
            src = f"{path}.{k}{suffix}"
            if os.path.exists(src):
                try:
                    os.replace(src, f"{path}.{k + 1}{suffix}")
                except OSError:
                    pass
    try:
        os.replace(path, f"{path}.1")
    except OSError:
        return None
    with _active_lock:
        _active_cache.pop(path, None)  # the active file restarted
    # Total-bytes retention: delete oldest generations until the rolled
    # chain fits the byte budget (the newest generation always stays —
    # a budget below one segment must not erase the roll entirely).
    budget = _retain_total_bytes()
    sizes = []
    for k in range(1, n + 1):
        try:
            sizes.append((k, os.path.getsize(f"{path}.{k}")))
        except OSError:
            continue
    total = sum(s for _, s in sizes)
    for k, size in sorted(sizes, reverse=True):
        if total <= budget or k == 1:
            break
        for stale in (f"{path}.{k}", f"{path}.{k}{INDEX_SUFFIX}"):
            try:
                os.remove(stale)
            except OSError:
                pass
        total -= size
    try:
        _store_counter().inc(event="roll")
    except Exception:  # metrics must never fail the span sink
        pass
    return f"{path}.1"


def _trace_id_of_line(line: str) -> Optional[str]:
    """Cheap trace-id extraction without a full JSON parse. Tolerates
    whitespace after the key separator (lines written by other tools /
    older versions with default ``json.dumps`` spacing); trace ids are
    hex, so the value can never contain escapes."""
    marker = '"trace_id":'
    i = line.find(marker)
    if i < 0:
        return None
    j = i + len(marker)
    while j < len(line) and line[j] in " \t":
        j += 1
    if j >= len(line) or line[j] != '"':
        return None
    k = line.find('"', j + 1)
    if k < 0:
        return None
    return line[j + 1:k]


def _scan_offsets(path: str, start: int = 0,
                  ) -> Tuple[Dict[str, List[int]], int]:
    """``{trace_id: [byte offsets]}`` for every span line from byte
    ``start`` to EOF, plus the byte position scanned to."""
    offsets: Dict[str, List[int]] = {}
    with open(path, "rb") as f:
        f.seek(start)
        pos = start
        for raw in f:
            if raw.endswith(b"\n"):
                tid = _trace_id_of_line(
                    raw.decode("utf-8", errors="replace"))
                if tid:
                    offsets.setdefault(tid, []).append(pos)
                pos += len(raw)
            else:
                break  # torn tail write; re-scan it next lookup
    return offsets, pos


def index_path(segment_path: str) -> str:
    return segment_path + INDEX_SUFFIX


def _build_index(segment_path: str) -> Dict[str, List[int]]:
    """Scan one FROZEN segment once and persist its sidecar index
    (``{trace_id: [offsets]}``). The write is atomic (tmp + replace)
    so a concurrent reader never loads a torn index."""
    offsets, _pos = _scan_offsets(segment_path)
    tmp = index_path(segment_path) + ".tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"v": 1, "traces": offsets}, f,
                      separators=(",", ":"))
        os.replace(tmp, index_path(segment_path))
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass
    try:
        _store_counter().inc(event="index_build")
    except Exception:
        pass
    return offsets


def _load_index(segment_path: str) -> Optional[Dict[str, List[int]]]:
    try:
        with open(index_path(segment_path), encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    traces = data.get("traces") if isinstance(data, dict) else None
    return traces if isinstance(traces, dict) else None


def _read_lines_at(path: str, offsets: List[int],
                   ) -> Tuple[List[str], int]:
    """Seek-and-read one line per offset; returns the lines and the
    bytes actually read (the indexed-read evidence)."""
    out: List[str] = []
    n_bytes = 0
    try:
        with open(path, "rb") as f:
            for off in offsets:
                f.seek(off)
                raw = f.readline()
                n_bytes += len(raw)
                out.append(raw.decode("utf-8", errors="replace"))
    except OSError:
        return out, n_bytes
    return out, n_bytes


# --- Tail-sampling buffer ---------------------------------------------

def _tail_register(trace_id: str) -> None:
    flush: List[List[str]] = []
    with _tail_lock:
        if trace_id in _tail_pending:
            return
        while len(_tail_pending) >= _PENDING_MAX_TRACES:
            # Oldest pending first: its edge presumably died; retain.
            _oldest, lines = next(iter(_tail_pending.items()))
            del _tail_pending[_oldest]
            if lines:
                flush.append(lines)
        _tail_pending[trace_id] = []
    for lines in flush:
        _write_lines(lines)


def _tail_route(lines_by_tid: List[Tuple[Optional[str], str]]) -> None:
    """Write span lines, detouring those of tail-pending traces into
    their buffer and suppressing those of recently dropped traces."""
    direct: List[str] = []
    overflow: List[str] = []
    with _tail_lock:
        for tid, line in lines_by_tid:
            buf = _tail_pending.get(tid) if tid else None
            if buf is not None:
                if len(buf) >= _PENDING_MAX_SPANS:
                    # A runaway trace stops buffering: flush what it
                    # has, retain everything after (never drop spans
                    # we can no longer hold the verdict open for).
                    del _tail_pending[tid]
                    overflow.extend(buf)
                    overflow.append(line)
                else:
                    buf.append(line)
            elif tid and tid in _tail_dropped:
                continue
            else:
                direct.append(line)
    if overflow:
        _write_lines(overflow)
    if direct:
        _write_lines(direct)


def complete(ctx: Optional[TraceContext], dur_s: float,
             error: bool = False) -> None:
    """The tail-sampling verdict, called by the minting edge when its
    request finishes: error and slow-over-threshold traces always
    flush to the store; fast/ok ones keep with the tail sample rate.
    No-op for non-tail contexts (honored headers, head-sampled legacy
    mode)."""
    if ctx is None or not ctx.tail:
        return
    rate = tail_sample_rate()
    with _tail_lock:
        lines = _tail_pending.pop(ctx.trace_id, None)
        if lines is None:
            return  # already flushed (overflow) — retained
        if error:
            verdict = "kept_error"
        elif dur_s * 1e3 >= tail_slow_ms():
            verdict = "kept_slow"
        elif rate is None or _tail_rng.random() < rate:
            verdict = "kept_sampled"
        else:
            verdict = "dropped"
            _tail_dropped[ctx.trace_id] = None
            while len(_tail_dropped) > _DROPPED_REMEMBER:
                _tail_dropped.pop(next(iter(_tail_dropped)))
    if verdict != "dropped" and lines:
        _write_lines(lines)
    try:
        from . import metrics

        c = metrics.registry().counter(
            "rafiki_tpu_trace_tail_total",
            "Tail-sampling verdicts at trace completion (verdict="
            "kept_error|kept_slow|kept_sampled|dropped)")
        # rta: disable=RTA301 verdict is the fixed 4-value vocabulary above; process-global family, deliberately immortal
        c.inc(verdict=verdict)
    except Exception:
        pass


def _tail_flush_all() -> None:
    with _tail_lock:
        pending = list(_tail_pending.values())
        _tail_pending.clear()
    for lines in pending:
        if lines:
            _write_lines(lines)


def exemplar_ok(ctx: TraceContext) -> bool:
    """Whether a metric exemplar may reference this trace: a
    tail-PENDING trace's verdict could still drop its spans, and a
    dropped trace's exemplar would link to an empty timeline. Non-tail
    contexts (honored headers, tail-off mode) and tail traces whose
    verdict KEPT them qualify; pending/dropped ones don't — the
    exemplar under-captures rather than dangles."""
    if not ctx.tail:
        return True
    with _tail_lock:
        return ctx.trace_id not in _tail_pending and \
            ctx.trace_id not in _tail_dropped


def seed_tail(seed: int) -> None:
    """Deterministic tail-sampling decisions (tests / seeded bench)."""
    global _tail_rng
    _tail_rng = random.Random(seed)


def reset_tail_for_tests() -> None:
    with _tail_lock:
        _tail_pending.clear()
        _tail_dropped.clear()


def record_event(name: str, service: str,
                 ctxs: Iterable[Optional[TraceContext]],
                 start_wall: float, dur_s: float,
                 attrs: Optional[Dict[str, Any]] = None,
                 child: bool = True) -> None:
    """Append one span event per traced context. ``child=True`` (the
    common case) records a NEW span parented on each context's span;
    ``child=False`` records the context's own span (the HTTP edge,
    which minted it)."""
    if _sink_path is None:
        return
    lines: List[Tuple[Optional[str], str]] = []
    for ctx in ctxs:
        if ctx is None:
            continue
        span = {
            "trace_id": ctx.trace_id,
            "span_id": new_span_id() if child else ctx.span_id,
            "parent_id": ctx.span_id if child else ctx.parent_id,
            "name": name,
            "service": service,
            "start_s": round(start_wall, 6),
            "dur_ms": round(dur_s * 1e3, 3),
        }
        if attrs:
            span["attrs"] = attrs
        lines.append((ctx.trace_id,
                      json.dumps(span, separators=(",", ":")) + "\n"))
    if lines:
        _tail_route(lines)


class span:
    """``with trace.span("worker.predict", service=sid, ctxs=...)`` —
    times the block (monotonic) and records the event(s) at exit.
    No-ops entirely when nothing is traced or no sink is configured."""

    def __init__(self, name: str, service: str = "",
                 ctxs: Optional[Iterable[Optional[TraceContext]]] = None,
                 attrs: Optional[Dict[str, Any]] = None,
                 child: bool = True):
        self.name = name
        self.service = service
        self.attrs = attrs
        self.child = child
        self._ctxs = list(ctxs) if ctxs is not None else None

    def __enter__(self):
        if self._ctxs is None:
            cur = current()
            self._ctxs = [cur] if cur is not None else []
        self._wall = time.time()
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        if self._ctxs and _sink_path is not None:
            record_event(self.name, self.service, self._ctxs, self._wall,
                         time.monotonic() - self._t0, attrs=self.attrs,
                         child=self.child)
        return False


# --- Stitching (admin's GET /trace/<id>) ------------------------------

def segment_paths(log_dir: str) -> List[str]:
    """Store segments oldest-first: rolled generations ``.N`` .. ``.1``
    then the active file (only the ones that exist)."""
    path = span_log_path(log_dir)
    out = [f"{path}.{k}"
           for k in range(retain_segments(), 0, -1)
           if os.path.exists(f"{path}.{k}")]
    if os.path.exists(path):
        out.append(path)
    return out


def _active_offsets(path: str, trace_id: str) -> Tuple[List[int], int]:
    """The active segment's offsets for one trace via the incremental
    scan cache; second value is the bytes scanned by THIS lookup (the
    appended tail only, 0 on a warm repeat). The cache entry carries
    the file's inode: a roll performed by ANOTHER process replaces the
    active file (``os.replace`` + fresh create), and a size check
    alone would miss it whenever the new file has already grown past
    the cached scan position — stale offsets against new content would
    silently truncate timelines."""
    try:
        st = os.stat(path)
        size, ident = st.st_size, (st.st_ino, st.st_dev)
    except OSError:
        return [], 0
    with _active_lock:
        entry = _active_cache.get(path)
        if entry is None or entry[0] > size or entry[2] != ident:
            entry = [0, {}, ident]  # rolled/truncated/replaced: reset
            _active_cache[path] = entry
        scanned_from = entry[0]
        if size > entry[0]:
            fresh, pos = _scan_offsets(path, start=entry[0])
            for tid, offs in fresh.items():
                entry[1].setdefault(tid, []).extend(offs)
            entry[0] = pos
        offsets = list(entry[1].get(trace_id, ()))
    return offsets, max(0, size - scanned_from)


def collect_trace(log_dir: str, trace_id: str,
                  max_spans: int = 1000) -> Dict[str, Any]:
    """Stitch every span of one trace across the segmented store into
    an ordered timeline. Frozen segments are INDEXED reads (sidecar
    ``.idx`` built at roll time, rebuilt lazily if missing): a seek
    and one readline per matching span, never a full-segment scan.
    The active segment rides the incremental scan cache — only bytes
    appended since the previous lookup are read. The per-segment
    ``segments`` diagnostics (mode + bytes_read) are what the indexed-
    read regression test pins. A corrupt line is skipped, never
    fatal."""
    path = span_log_path(log_dir)
    spans: List[Dict[str, Any]] = []
    diags: List[Dict[str, Any]] = []
    for p in segment_paths(log_dir):
        if len(spans) >= max_spans:
            break
        if p == path:
            offsets, scanned = _active_offsets(p, trace_id)
            mode, overhead = "scan_tail", scanned
            try:
                _store_counter().inc(event="tail_scan")
            except Exception:
                pass
        else:
            index = _load_index(p)
            if index is None:
                try:
                    index = _build_index(p)
                    mode = "index_rebuilt"
                except OSError:
                    continue
            else:
                mode = "index"
            try:
                _store_counter().inc(event="index_read")
            except Exception:
                pass
            offsets, overhead = list(index.get(trace_id, ())), 0
        lines, n_bytes = _read_lines_at(p, offsets[:max_spans
                                                   - len(spans)])
        for line in lines:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("trace_id") == trace_id:
                spans.append(rec)
        diags.append({"segment": os.path.basename(p), "mode": mode,
                      "n_spans": len(lines),
                      "bytes_read": n_bytes + overhead})
    spans.sort(key=lambda s: (s.get("start_s", 0.0), s.get("name", "")))
    t0 = spans[0].get("start_s", 0.0) if spans else 0.0
    for s in spans:
        s["offset_ms"] = round((s.get("start_s", t0) - t0) * 1e3, 3)
    return {"trace_id": trace_id, "n_spans": len(spans),
            "spans": spans, "segments": diags}
