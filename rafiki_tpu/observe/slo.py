"""SLO plane: declarative objectives, error budgets, burn-rate alerts.

Rafiki is a multi-tenant MLaaS, and since r17 the repo MEASURES
everything the serving path does — per-job latency histograms, per-bin
and per-tenant attribution counters — but nothing JUDGES any of it: no
series says "this job is violating its latency objective", so the
autoscaler scales to the queue and a pager has nothing to attach to.
This module is the judgment layer's vocabulary; the evaluator that
rides the supervise cadence lives in ``admin/slo_engine.py``.

An **objective** declares a good-event fraction target over a rolling
budget window:

- ``latency``: "at least ``target`` of requests complete within
  ``threshold_ms``" — evaluated from histogram BUCKET DELTAS via the
  same cumulative-bucket interpolation ``bucket_percentile`` uses, so
  the SLO plane judges exactly what the bench and the autoscaler
  already trust. Scoped ``job`` (the predictor's ``/predict`` http
  histogram), ``bin`` (the r17 worker-side per-bin device-time
  histogram) or ``tenant`` (the tenant-labeled request-latency
  histogram the attribution ledger records at the frontend).
- ``ratio``: "at least ``target`` of requests are admitted" —
  availability from the serving requests/rejected counter deltas
  (``job`` scope only; nothing else carries an error counter).

**Error budget**: over the budget window ``window_s`` the objective
allows ``(1 - target)`` of events to be bad.
``budget_remaining = 1 - bad_fraction/(1 - target)`` (floored at 0 for
the gauge). **Burn rate** over a window is
``bad_fraction / (1 - target)`` — 1.0 burns the budget exactly at the
window's length, N burns it N× faster.

**Multi-window multi-burn-rate alerting** (the SRE-workbook shape,
sized for this system's sweep cadence): an alert goes *pending* when
the burn rate exceeds ``burn`` over BOTH the fast and the slow window
— the fast window reacts in seconds, the slow window is the flap
guard: a one-sweep blip cannot lift a 60 s average over threshold —
*firing* after ``for_s`` of continuous breach, and *resolved* once the
FAST window has stayed under threshold for ``resolve_s`` (the fast
window clears quickly after the fault does; the slow window would hold
the alert long past recovery). The state machine is pure and
unit-tested like ``AutoscalePolicy``'s decision table.

Rules ride ``RAFIKI_TPU_SLO_RULES`` (NodeConfig ``slo_rules``): a path
to a JSON/TOML rules file (the value ends in ``.json``/``.toml``), or
the compact inline grammar::

    predict-p99:p99<50ms,window=300,fast=60,slow=300,burn=2,for=10,resolve=30
    avail:ratio>=0.995,window=600

``;``-separated rules, each ``name:spec[,key=value...]``. Unknown keys
and malformed specs are rejected LOUDLY at NodeConfig validation (the
fault-plan discipline: a typo'd objective must fail the node's
construction, not silently judge nothing).
"""

from __future__ import annotations

import json
import math
import os
import re
from collections import deque
from dataclasses import dataclass, field, fields
from typing import Any, Dict, List, Optional, Tuple

SLO_RULES_ENV = "RAFIKI_TPU_SLO_RULES"

#: Series the evaluator READS (never registers) per (type, scope) —
#: the RTA506 drift gate cross-checks every name here (and every
#: ``metric`` override in a rules file) against the registered-series
#: vocabulary, so a renamed source series breaks the build instead of
#: silently blanking every objective that reads it.
CONSUMED_SERIES: Dict[Tuple[str, str], str] = {
    ("latency", "job"): "rafiki_tpu_http_request_seconds",
    ("latency", "bin"): "rafiki_tpu_serving_bin_device_seconds",
    ("latency", "tenant"): "rafiki_tpu_serving_tenant_request_seconds",
    ("ratio", "good"): "rafiki_tpu_serving_requests_total",
    ("ratio", "bad"): "rafiki_tpu_serving_rejected_total",
}

_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,39}$")
_LATENCY_SPEC_RE = re.compile(
    r"^p([0-9]+(?:\.[0-9]+)?)<([0-9]+(?:\.[0-9]+)?)ms$")
_RATIO_SPEC_RE = re.compile(r"^ratio>=(0?\.[0-9]+|1(?:\.0+)?)$")

_INLINE_KEYS = frozenset({"scope", "window", "fast", "slow", "burn",
                          "for", "resolve", "route", "job", "metric"})
_SCOPES = ("job", "bin", "tenant")


@dataclass(frozen=True)
class Objective:
    """One declarative objective (see the module docstring)."""

    name: str
    otype: str                 # "latency" | "ratio"
    target: float              # required good-event fraction, (0, 1)
    threshold_ms: float = 0.0  # latency objectives only
    scope: str = "job"         # "job" | "bin" | "tenant"
    window_s: float = 300.0    # error-budget window
    fast_s: float = 60.0       # fast burn window (reaction)
    slow_s: float = 300.0      # slow burn window (flap guard)
    burn: float = 2.0          # burn-rate alert threshold, both windows
    for_s: float = 0.0         # continuous breach before firing
    resolve_s: float = 0.0     # fast-window-quiet before resolving
    route: str = "/predict"    # http route (latency/job scope)
    job: str = ""              # inference-job id prefix filter ("": all)
    metric: str = ""           # source-series override ("": the default)

    def source_metric(self) -> str:
        if self.metric:
            return self.metric
        return CONSUMED_SERIES[(self.otype, self.scope
                                if self.otype == "latency" else "good")]

    @property
    def budget(self) -> float:
        return 1.0 - self.target

    def validate(self) -> "Objective":
        if not _NAME_RE.match(self.name):
            raise ValueError(f"SLO objective name {self.name!r} must "
                             f"match {_NAME_RE.pattern}")
        if self.otype not in ("latency", "ratio"):
            raise ValueError(f"SLO objective {self.name}: type "
                             f"{self.otype!r} is not latency/ratio")
        if not (0.0 < self.target < 1.0):
            raise ValueError(f"SLO objective {self.name}: target "
                             f"{self.target} must be within (0, 1)")
        if self.otype == "latency" and self.threshold_ms <= 0:
            raise ValueError(f"SLO objective {self.name}: latency "
                             f"objectives need threshold_ms > 0")
        if self.scope not in _SCOPES:
            raise ValueError(f"SLO objective {self.name}: scope "
                             f"{self.scope!r} is not one of {_SCOPES}")
        if self.otype == "ratio" and self.scope != "job":
            raise ValueError(
                f"SLO objective {self.name}: ratio objectives are "
                f"job-scoped only (no per-bin/per-tenant error "
                f"counter exists to read)")
        if self.otype == "ratio" and self.metric:
            raise ValueError(
                f"SLO objective {self.name}: ratio objectives read a "
                f"counter PAIR (requests + rejected) — a single "
                f"metric override cannot express that, and silently "
                f"ignoring it would judge the wrong series")
        if not (0 < self.fast_s <= self.slow_s):
            raise ValueError(f"SLO objective {self.name}: need "
                             f"0 < fast_s <= slow_s")
        if self.window_s < self.slow_s:
            raise ValueError(f"SLO objective {self.name}: the budget "
                             f"window must be >= the slow burn window")
        if self.burn <= 0:
            raise ValueError(f"SLO objective {self.name}: burn "
                             f"threshold must be positive")
        if self.for_s < 0 or self.resolve_s < 0:
            raise ValueError(f"SLO objective {self.name}: for_s and "
                             f"resolve_s must be >= 0")
        return self


def _from_mapping(name: str, raw: Dict[str, Any]) -> Objective:
    """Build one objective from a rules-file table. Unknown keys are
    rejected loudly — a typo'd field must not silently fall back to a
    default."""
    keymap = {
        "type": "otype", "target": "target",
        "threshold_ms": "threshold_ms", "scope": "scope",
        "window_s": "window_s", "fast_window_s": "fast_s",
        "slow_window_s": "slow_s", "burn_threshold": "burn",
        "for_s": "for_s", "resolve_for_s": "resolve_s",
        "route": "route", "job": "job", "metric": "metric",
    }
    unknown = set(raw) - set(keymap) - {"name"}
    if unknown:
        raise ValueError(
            f"SLO objective {name}: unknown field(s) "
            f"{sorted(unknown)} (valid: {sorted(keymap)})")
    kwargs: Dict[str, Any] = {"name": name}
    ftypes = {f.name: f.type for f in fields(Objective)}
    for src, dst in keymap.items():
        if src not in raw:
            continue
        value = raw[src]
        if ftypes[dst] == "float":
            value = float(value)
        elif ftypes[dst] == "str":
            value = str(value)
        kwargs[dst] = value
    if "otype" not in kwargs:
        raise ValueError(f"SLO objective {name}: missing 'type'")
    if "target" not in kwargs:
        raise ValueError(f"SLO objective {name}: missing 'target'")
    _window_defaults(kwargs)
    return Objective(**kwargs).validate()


def _window_defaults(kwargs: Dict[str, Any]) -> None:
    """Fill dependent window defaults in place: slow defaults to the
    budget window, fast to window/5 capped at 60 s, resolve to one
    fast window of quiet (shared by the file and inline parsers so the
    two sources cannot drift)."""
    window = kwargs.get("window_s", 300.0)
    kwargs.setdefault("slow_s", window)
    kwargs.setdefault("fast_s", min(60.0, window / 5.0))
    kwargs.setdefault("resolve_s", kwargs["fast_s"])


def _parse_inline_rule(rule: str) -> Objective:
    name, sep, rest = rule.partition(":")
    if not sep or not rest.strip():
        raise ValueError(f"SLO rule {rule!r} is not name:spec[,k=v...]")
    name = name.strip()
    parts = [p.strip() for p in rest.split(",") if p.strip()]
    spec, kvs = parts[0], parts[1:]
    kwargs: Dict[str, Any] = {"name": name}
    m = _LATENCY_SPEC_RE.match(spec)
    if m:
        kwargs["otype"] = "latency"
        kwargs["target"] = float(m.group(1)) / 100.0
        kwargs["threshold_ms"] = float(m.group(2))
    else:
        m = _RATIO_SPEC_RE.match(spec)
        if m:
            kwargs["otype"] = "ratio"
            kwargs["target"] = float(m.group(1))
        else:
            raise ValueError(
                f"SLO rule {name}: spec {spec!r} is neither "
                f"p<q><<ms>ms (e.g. p99<50ms) nor ratio>=<frac>")
    # Window keys resolve AFTER all kvs are read (fast/slow default
    # from window); collect first.
    seen: Dict[str, str] = {}
    for kv in kvs:
        key, sep, value = kv.partition("=")
        key, value = key.strip(), value.strip()
        if not sep or not value:
            raise ValueError(f"SLO rule {name}: {kv!r} is not k=v")
        if key not in _INLINE_KEYS:
            raise ValueError(f"SLO rule {name}: unknown key {key!r} "
                             f"(valid: {sorted(_INLINE_KEYS)})")
        if key in seen:
            raise ValueError(f"SLO rule {name}: duplicate key {key!r}")
        seen[key] = value
    for key, value in seen.items():
        if key in ("window", "fast", "slow", "burn", "for", "resolve"):
            try:
                num = float(value)
            except ValueError:
                raise ValueError(f"SLO rule {name}: {key}={value!r} is "
                                 f"not a number") from None
            kwargs[{"window": "window_s", "fast": "fast_s",
                    "slow": "slow_s", "burn": "burn", "for": "for_s",
                    "resolve": "resolve_s"}[key]] = num
        else:
            kwargs[key] = value
    _window_defaults(kwargs)
    return Objective(**kwargs).validate()


def _parse_rules_data(data: Any, source: str) -> List[Objective]:
    if not isinstance(data, dict) or \
            not isinstance(data.get("objectives"), list):
        raise ValueError(f"SLO rules {source}: expected an object with "
                         f"an 'objectives' array")
    out: List[Objective] = []
    for i, raw in enumerate(data["objectives"]):
        if not isinstance(raw, dict):
            raise ValueError(f"SLO rules {source}: objectives[{i}] is "
                             f"not an object")
        name = str(raw.get("name") or "")
        if not name:
            raise ValueError(f"SLO rules {source}: objectives[{i}] "
                             f"has no name")
        out.append(_from_mapping(name, raw))
    return out


def parse_rules(text: str) -> List[Objective]:
    """Parse a rules source: '' → no objectives; a value ending in
    ``.json``/``.toml`` → that rules file (which must exist and parse —
    failing the node loudly beats silently judging nothing); anything
    else → the compact inline grammar. Duplicate objective names are
    rejected (the name keys every gauge/alert label)."""
    text = (text or "").strip()
    if not text:
        return []
    if text.endswith(".json") or text.endswith(".toml"):
        try:
            with open(text, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise ValueError(f"SLO rules file {text!r}: {e}") from None
        if text.endswith(".json"):
            try:
                data = json.loads(raw.decode("utf-8"))
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                raise ValueError(
                    f"SLO rules file {text!r}: {e}") from None
        else:
            try:
                import tomllib
            except ImportError:  # pragma: no cover - py<3.11
                raise ValueError(
                    f"SLO rules file {text!r}: TOML rules need "
                    f"Python 3.11+ (tomllib); use JSON") from None
            try:
                data = tomllib.loads(raw.decode("utf-8"))
            except (UnicodeDecodeError, tomllib.TOMLDecodeError) as e:
                raise ValueError(
                    f"SLO rules file {text!r}: {e}") from None
        objectives = _parse_rules_data(data, text)
    else:
        objectives = [_parse_inline_rule(rule)
                      for rule in text.split(";") if rule.strip()]
    names = [o.name for o in objectives]
    dupes = sorted({n for n in names if names.count(n) > 1})
    if dupes:
        raise ValueError(f"SLO rules: duplicate objective name(s) "
                         f"{dupes}")
    return objectives


def rules_from_env() -> List[Objective]:
    return parse_rules(os.environ.get(SLO_RULES_ENV, ""))


# --- Event accounting -------------------------------------------------

def good_total_from_deltas(cum_deltas: List[Tuple[float, int]],
                           threshold_s: float) -> Tuple[float, float]:
    """``(good, total)`` events from one sweep's cumulative bucket
    DELTAS (``[(le_seconds, cumulative_delta), ...]`` sorted, ending at
    ``(inf, total)``): good = the interpolated count at the latency
    threshold — the same linear-within-bucket estimate
    ``bucket_percentile`` makes, so the SLO's good fraction and the
    dashboard's quantile agree by construction. Events beyond the last
    finite bound count bad."""
    if not cum_deltas:
        return 0.0, 0.0
    total = float(cum_deltas[-1][1])
    if total <= 0:
        return 0.0, 0.0
    prev_bound, prev_cum = 0.0, 0.0
    for bound, cum in cum_deltas:
        if bound >= threshold_s:
            if bound == math.inf:
                return float(prev_cum), total
            if bound == prev_bound:
                return float(cum), total
            frac = (threshold_s - prev_bound) / (bound - prev_bound)
            return prev_cum + (cum - prev_cum) * frac, total
        prev_bound, prev_cum = bound, float(cum)
    return total, total


class WindowRing:
    """Ring of per-sweep ``(t, good, total)`` event deltas, bounded by
    the horizon (the longest window that ever reads it). Sums are exact
    over whatever landed inside the window — no decay math, no
    bucketing drift; the supervise cadence bounds the entry count."""

    __slots__ = ("horizon_s", "_ring")

    def __init__(self, horizon_s: float, maxlen: int = 4096):
        self.horizon_s = horizon_s
        self._ring: "deque[Tuple[float, float, float]]" = \
            deque(maxlen=maxlen)

    def add(self, t: float, good: float, total: float) -> None:
        if total > 0:
            self._ring.append((t, max(0.0, good), total))
        while self._ring and t - self._ring[0][0] > self.horizon_s:
            self._ring.popleft()

    def sums(self, t: float, window_s: float) -> Tuple[float, float]:
        good = total = 0.0
        for ts, g, n in reversed(self._ring):
            if t - ts > window_s:
                break
            good += g
            total += n
        return good, total

    def bad_fraction(self, t: float, window_s: float) -> float:
        good, total = self.sums(t, window_s)
        if total <= 0:
            return 0.0
        return min(1.0, max(0.0, 1.0 - good / total))

    def burn_rate(self, t: float, window_s: float,
                  budget: float) -> float:
        """``bad_fraction / budget``: 1.0 = spending the error budget
        exactly at the window's pace; N = N× faster."""
        if budget <= 0:
            return 0.0
        return self.bad_fraction(t, window_s) / budget

    def budget_remaining(self, t: float, window_s: float,
                         budget: float) -> float:
        """Fraction of the window's error budget left, floored at 0
        (a gauge reading -3 helps nobody; the burn gauge carries the
        overshoot)."""
        if budget <= 0:
            return 0.0
        return max(0.0, min(1.0,
                            1.0 - self.bad_fraction(t, window_s)
                            / budget))


# --- Alert state machine ----------------------------------------------

#: Fixed transition vocabulary (the ``state`` label of
#: ``rafiki_tpu_slo_alerts_total`` — never free text).
TRANSITIONS = ("pending", "firing", "resolved", "cleared")


class AlertMachine:
    """Pure multi-window burn-rate alert state per objective instance.

    ``ok -> pending`` when BOTH windows breach; ``pending -> firing``
    after ``for_s`` of continuous breach (``for_s == 0`` fires
    immediately); ``pending -> ok`` ("cleared") the moment either
    window recovers; ``firing -> ok`` ("resolved") once the FAST
    window has stayed under threshold for ``resolve_s``. Flap-proof by
    construction: entering takes both windows + the for-duration,
    leaving takes sustained quiet — oscillation around the threshold
    inside one fast window changes nothing (unit-tested like
    ``AutoscalePolicy``'s decision table).
    """

    __slots__ = ("state", "_t_breach", "_t_quiet")

    def __init__(self):
        self.state = "ok"
        self._t_breach: Optional[float] = None
        self._t_quiet: Optional[float] = None

    def update(self, now: float, burn_fast: float, burn_slow: float,
               obj: Objective) -> Optional[str]:
        """Advance one evaluation tick; returns the transition taken
        (one of :data:`TRANSITIONS`) or None."""
        breach = burn_fast >= obj.burn and burn_slow >= obj.burn
        if self.state == "ok":
            if breach:
                self._t_breach = now
                if obj.for_s <= 0:
                    self.state = "firing"
                    self._t_quiet = None
                    return "firing"
                self.state = "pending"
                return "pending"
            return None
        if self.state == "pending":
            if not breach:
                self.state = "ok"
                self._t_breach = None
                return "cleared"
            t_breach = self._t_breach if self._t_breach is not None \
                else now
            if now - t_breach >= obj.for_s:
                self.state = "firing"
                self._t_quiet = None
                return "firing"
            return None
        # firing: resolve on sustained FAST-window quiet.
        if burn_fast < obj.burn:
            if self._t_quiet is None:
                self._t_quiet = now
            if now - self._t_quiet >= obj.resolve_s:
                self.state = "ok"
                self._t_breach = None
                self._t_quiet = None
                return "resolved"
        else:
            self._t_quiet = None
        return None


@dataclass
class Instance:
    """One evaluated (objective, scope-labels) series: its event ring,
    alert machine, previous-scrape basis, and last evaluation."""

    objective: Objective
    labels: Dict[str, str]
    ring: WindowRing
    machine: AlertMachine = field(default_factory=AlertMachine)
    prev: Optional[Any] = None      # previous cumulative snapshot
    last_seen: float = 0.0
    burn_fast: float = 0.0
    burn_slow: float = 0.0
    budget_remaining: float = 1.0
    good: float = 0.0               # window sums at the last eval
    total: float = 0.0

    @classmethod
    def create(cls, obj: Objective,
               labels: Dict[str, str]) -> "Instance":
        return cls(objective=obj, labels=dict(labels),
                   ring=WindowRing(max(obj.window_s, obj.slow_s)))

    def evaluate(self, now: float, good: float,
                 total: float) -> Optional[str]:
        """Fold one sweep's event deltas and advance the alert machine;
        returns the transition taken, if any."""
        obj = self.objective
        self.ring.add(now, good, total)
        self.last_seen = now
        self.burn_fast = self.ring.burn_rate(now, obj.fast_s,
                                             obj.budget)
        self.burn_slow = self.ring.burn_rate(now, obj.slow_s,
                                             obj.budget)
        self.budget_remaining = self.ring.budget_remaining(
            now, obj.window_s, obj.budget)
        self.good, self.total = self.ring.sums(now, obj.window_s)
        return self.machine.update(now, self.burn_fast,
                                   self.burn_slow, obj)
