"""Process-wide metrics registry with Prometheus text exposition.

Parity+: the reference has no metrics plane at all; each reproduction
subsystem grew its own ad-hoc counters (``ServingStats``' dict, the
``MfuMeter``'s properties, per-trial logs). This module is the one
place counters, gauges, and fixed-bucket latency histograms live, so
every service exposes the SAME numbers over ``GET /metrics`` (wired
into ``utils.service.JsonHttpServer``) that the bench and the admin
dashboard read.

Design constraints, in order:

- **Stdlib only, no jax import.** The bus backends instrument their hot
  path through this module; importing it must not drag the accelerator
  runtime into a broker process.
- **Cheap enough to always be on.** A counter inc is one lock + one
  float add; a histogram observe adds a bucket scan over ~14 bounds.
  ``RAFIKI_TPU_METRICS=0`` additionally disables the ``/metrics`` route
  and the call-site wiring (checked at construction time, not per op).
- **Bounded label cardinality.** Queue names carry uuids, so the bus
  records a queue *kind* (``query``/``reply``/``other``), never the
  queue name; per-service serving metrics label by the short service
  id, which is bounded by the number of frontends in a process.

Naming convention (enforced by ``scripts/check_metrics_names.py``):
``rafiki_tpu_<subsystem>_<name>_<unit>`` — subsystem one of the known
set (bus, serving, http, train, trace, node), unit last
(``_total`` for counters, ``_seconds``/``_ratio``/``_bytes``/... for
the rest).
"""

from __future__ import annotations

import json
import math
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

METRICS_ENV = "RAFIKI_TPU_METRICS"
EXEMPLARS_ENV = "RAFIKI_TPU_METRICS_EXEMPLARS"

#: Default latency buckets (seconds): 0.5 ms .. 10 s, roughly
#: logarithmic — wide enough for a bus push (~us, lands in the first
#: bucket) and a cold predictor gather (~seconds) alike.
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def metrics_enabled() -> bool:
    """``RAFIKI_TPU_METRICS=0`` disables exposition + instrumentation
    wiring. Read where wiring happens (server/bus construction), not
    per operation."""
    return os.environ.get(METRICS_ENV, "1").strip().lower() not in (
        "0", "false", "no", "off")


#: Exemplar wiring, resolved ONCE at first histogram observe (the r11
#: disabled-means-free discipline: off = one None check per observe).
_exemplars_flag: Optional[bool] = None
_exemplars_lock = threading.Lock()


def exemplars_enabled() -> bool:
    """Whether histograms attach a last-trace-id exemplar per bucket
    (``RAFIKI_TPU_METRICS_EXEMPLARS``, default off), rendered
    OpenMetrics-style in the exposition. Resolved once per process."""
    global _exemplars_flag
    # rta: disable=RTA101 double-checked init: the bare read is the fast path; the write re-checks under _exemplars_lock
    flag = _exemplars_flag
    if flag is None:
        with _exemplars_lock:
            flag = _exemplars_flag
            if flag is None:
                raw = os.environ.get(EXEMPLARS_ENV, "0")
                flag = raw.strip().lower() not in (
                    "0", "false", "no", "off", "")
                _exemplars_flag = flag
    return flag


def reset_exemplars_for_tests() -> None:
    """Drop the cached exemplar flag so a test that flips
    ``RAFIKI_TPU_METRICS_EXEMPLARS`` sees its env take effect."""
    global _exemplars_flag
    with _exemplars_lock:
        _exemplars_flag = None


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _render_labels(key: Tuple[Tuple[str, str], ...],
                   extra: Optional[Dict[str, str]] = None) -> str:
    items = list(key) + sorted((extra or {}).items())
    if not items:
        return ""
    # json.dumps gives the exact escaping the exposition format wants
    # for label values (backslash, quote, newline).
    body = ",".join(f"{k}={json.dumps(str(v))}" for k, v in items)
    return "{" + body + "}"


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Counter:
    """Monotonically increasing float, one series per label set."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: Dict[Tuple, float] = {}

    def inc(self, n: float = 1.0, **labels: str) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def samples(self) -> List[Tuple[Dict[str, str], float]]:
        with self._lock:
            return [(dict(k), v) for k, v in self._values.items()]

    def remove(self, **labels: str) -> None:
        """Drop every series whose labels INCLUDE this subset. Series
        are otherwise immortal; owners of per-instance labels (a
        stopped predictor frontend, a finished trial) must call this or
        the registry and every scrape grow monotonically with churn."""
        match = set(_label_key(labels))
        with self._lock:
            for key in [k for k in self._values if match <= set(k)]:
                del self._values[key]

    def expose(self) -> List[str]:
        with self._lock:
            return [f"{self.name}{_render_labels(k)} {_fmt(v)}"
                    for k, v in sorted(self._values.items())]


class Gauge(Counter):
    """Point-in-time value; ``set`` replaces, ``inc`` may go down."""

    kind = "gauge"

    def set(self, v: float, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(v)

    def inc(self, n: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def dec(self, n: float = 1.0, **labels: str) -> None:
        # rta: disable=RTA301 registry plumbing: labels pass through; series lifecycle belongs to callers
        self.inc(-n, **labels)


class Histogram:
    """Fixed-bucket histogram (cumulative ``le`` buckets + sum/count),
    one series set per label set."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError("buckets must be a sorted non-empty sequence")
        self.name = name
        self.help = help
        self.buckets = tuple(float(b) for b in buckets)
        self._lock = threading.Lock()
        # label key -> [per-bucket counts..., +Inf count, sum]
        self._series: Dict[Tuple, List[float]] = {}
        # label key -> {bucket index (len(buckets) = +Inf): (trace_id,
        # observed value, wall ts)} — the LAST traced observation per
        # bucket, attached OpenMetrics-style in the exposition so a p99
        # bucket links to an actual stitched timeline. Populated only
        # when RAFIKI_TPU_METRICS_EXEMPLARS is on AND the observing
        # thread carries a trace context.
        self._exemplars: Dict[Tuple, Dict[int, Tuple[str, float,
                                                     float]]] = {}

    def _row(self, key: Tuple) -> List[float]:
        row = self._series.get(key)
        if row is None:
            row = [0.0] * (len(self.buckets) + 2)
            self._series[key] = row
        return row

    def observe(self, v: float, **labels: str) -> None:
        exemplar = None
        if exemplars_enabled():
            from . import trace as _trace

            ctx = _trace.current()
            # exemplar_ok: a tail-sampled trace whose verdict is still
            # pending (or dropped) must not be referenced — the link
            # would resolve to an empty timeline.
            if ctx is not None and _trace.exemplar_ok(ctx):
                import time as _time

                exemplar = (ctx.trace_id, float(v), _time.time())
        key = _label_key(labels)
        with self._lock:
            row = self._row(key)
            for i, bound in enumerate(self.buckets):
                if v <= bound:
                    row[i] += 1
                    break
            else:
                i = len(self.buckets)
                row[i] += 1  # +Inf only
            row[-1] += v
            if exemplar is not None:
                self._exemplars.setdefault(key, {})[i] = exemplar

    # --- Reads ---

    def count(self, **labels: str) -> int:
        with self._lock:
            row = self._series.get(_label_key(labels))
            return int(sum(row[:-1])) if row else 0

    def sum(self, **labels: str) -> float:
        with self._lock:
            row = self._series.get(_label_key(labels))
            return row[-1] if row else 0.0

    def cumulative_buckets(self, **labels: str) -> List[Tuple[float, int]]:
        """``[(le, cumulative_count), ...]`` ending at ``(+Inf, count)``
        — the exposition shape, also what percentile math wants."""
        with self._lock:
            row = self._series.get(_label_key(labels))
            if row is None:
                return []
            out, cum = [], 0
            for bound, n in zip(self.buckets, row):
                cum += int(n)
                out.append((bound, cum))
            out.append((math.inf, cum + int(row[len(self.buckets)])))
            return out

    def percentile(self, q: float, **labels: str) -> Optional[float]:
        return bucket_percentile(self.cumulative_buckets(**labels), q)

    def exemplars(self, **labels: str) -> Dict[str, Dict[str, Any]]:
        """``{le: {"trace_id", "value", "ts"}}`` for one label set —
        what the dashboard's stats panel links from (empty unless
        exemplars are enabled and traced observations landed)."""
        with self._lock:
            ex = self._exemplars.get(_label_key(labels))
            if not ex:
                return {}
            out = {}
            for i, (tid, v, ts) in ex.items():
                le = (_fmt(self.buckets[i]) if i < len(self.buckets)
                      else "+Inf")
                out[le] = {"trace_id": tid, "value": v,
                           "ts": round(ts, 3)}
            return out

    def remove(self, **labels: str) -> None:
        """Drop every series whose labels include this subset (see
        :meth:`Counter.remove`)."""
        match = set(_label_key(labels))
        with self._lock:
            for key in [k for k in self._series if match <= set(k)]:
                del self._series[key]
                self._exemplars.pop(key, None)

    @staticmethod
    def _exemplar_suffix(ex: Optional[Tuple[str, float, float]]) -> str:
        """OpenMetrics exemplar annotation for one bucket line
        (`` # {trace_id="…"} <value> <ts>``), empty when absent."""
        if ex is None:
            return ""
        tid, v, ts = ex
        return (f' # {{trace_id="{tid}"}} {_fmt(v)} '
                f"{round(ts, 3)}")

    def expose(self, exemplars: bool = False) -> List[str]:
        lines = []
        with self._lock:
            series = sorted(self._series.items())
            exemplars_by_key = ({k: dict(v)
                                 for k, v in self._exemplars.items()}
                                if exemplars else {})
        for key, row in series:
            ex = exemplars_by_key.get(key, {})
            cum = 0
            for i, (bound, n) in enumerate(zip(self.buckets, row)):
                cum += int(n)
                lines.append(
                    f"{self.name}_bucket"
                    f"{_render_labels(key, {'le': _fmt(bound)})} {cum}"
                    f"{self._exemplar_suffix(ex.get(i))}")
            total = cum + int(row[len(self.buckets)])
            lines.append(
                f"{self.name}_bucket"
                f"{_render_labels(key, {'le': '+Inf'})} {total}"
                f"{self._exemplar_suffix(ex.get(len(self.buckets)))}")
            lines.append(f"{self.name}_sum{_render_labels(key)} "
                         f"{_fmt(row[-1])}")
            lines.append(f"{self.name}_count{_render_labels(key)} {total}")
        return lines


def bucket_percentile(cum_buckets: List[Tuple[float, int]],
                      q: float) -> Optional[float]:
    """Approximate the q-quantile (0..1) from cumulative ``le`` buckets
    by linear interpolation inside the containing bucket — the same
    estimate Prometheus's ``histogram_quantile`` computes, so bench and
    production dashboards agree by construction. None when empty; a
    quantile landing in the +Inf bucket reports the last finite bound
    (a known floor, not a fabricated value)."""
    if not cum_buckets:
        return None
    total = cum_buckets[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_bound, prev_cum = 0.0, 0
    for bound, cum in cum_buckets:
        if cum >= rank:
            if bound == math.inf:
                return prev_bound
            if cum == prev_cum:
                return bound
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_bound + (bound - prev_bound) * frac
        prev_bound, prev_cum = bound, cum
    return prev_bound


class MetricsRegistry:
    """Get-or-create metric registry; ``registry()`` is the process
    singleton every subsystem shares."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Any] = {}

    def _get(self, cls, name: str, help: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def find(self, name: str) -> Optional[Any]:
        with self._lock:
            return self._metrics.get(name)

    def expose(self, exemplars: bool = False) -> str:
        """Prometheus text exposition format 0.0.4. ``exemplars=True``
        (the explicit ``?exemplars=1`` debug view — see
        ``metrics_route``) additionally annotates histogram buckets
        with their last traced observation, OpenMetrics-style; the
        default exposition never carries them — annotation syntax is
        not part of 0.0.4, and a scrape config must never receive it
        by accident."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines: List[str] = []
        for name, m in metrics:
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            if exemplars and isinstance(m, Histogram):
                lines.extend(m.expose(exemplars=True))
            else:
                lines.extend(m.expose())
        return "\n".join(lines) + "\n"


_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _registry


# --- Thread-local label context -------------------------------------
#
# The train loop (model/jax_model.py) publishes per-trial gauges but has
# no idea which trial it runs for — the TrialRunner does. The runner
# binds ``trial=<id>`` around ``model.train`` and the loop picks it up.

_labels_local = threading.local()


class label_context:
    """``with metrics.label_context(trial=tid): ...`` — labels every
    ``bound_labels()`` read on this thread for the duration."""

    def __init__(self, **labels: str):
        self._labels = {k: str(v) for k, v in labels.items()}

    def __enter__(self):
        prior = getattr(_labels_local, "labels", {})
        self._prior = prior
        _labels_local.labels = {**prior, **self._labels}
        return self

    def __exit__(self, *exc):
        _labels_local.labels = self._prior
        return False


def bound_labels() -> Dict[str, str]:
    return dict(getattr(_labels_local, "labels", {}))


# --- Exposition parsing (bench / tests read what production exposes) --

def _is_escaped(s: str, i: int) -> bool:
    """Whether ``s[i]`` is escaped: preceded by an ODD number of
    backslashes (a value ending in ``\\\\`` must not hide its closing
    quote — the bug a single-backslash look-behind has)."""
    n = 0
    j = i - 1
    while j >= 0 and s[j] == "\\":
        n += 1
        j -= 1
    return n % 2 == 1


def strip_exemplar(line: str) -> str:
    """Drop an OpenMetrics exemplar annotation (`` # {...} value
    [ts]``) from a sample line, respecting quotes — a ``#`` inside a
    quoted label value is data, not an annotation. Scrapers of the
    exposition (bench, the autoscaler, tests) route through
    :func:`parse_exposition`, so exemplars can never break them."""
    if "#" not in line:  # the overwhelming default: no scan at all
        return line
    in_quote = False
    for i, ch in enumerate(line):
        if ch == '"' and not _is_escaped(line, i):
            in_quote = not in_quote
        elif ch == "#" and not in_quote and i >= 1 \
                and line[i - 1] in " \t":
            return line[:i - 1].rstrip()
    return line


def parse_exposition(text: str) -> Dict[str, List[Tuple[Dict[str, str],
                                                        float]]]:
    """Parse Prometheus text into ``{name: [(labels, value), ...]}``.
    Minimal by design: handles what ``MetricsRegistry.expose`` emits —
    including OpenMetrics-style exemplar annotations on histogram
    bucket lines (tolerated and stripped) and json-escaped label
    values (``\\"``, ``\\n``, ``\\\\`` round-trip exactly). It is how
    the bench and the autoscaler read ``/metrics`` instead of
    re-deriving numbers client-side, so it must never regress on what
    the exposition grows."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        line = strip_exemplar(line)
        name_part, _, value_part = line.rpartition(" ")
        labels: Dict[str, str] = {}
        if "{" in name_part:
            name, _, label_body = name_part.partition("{")
            label_body = label_body.rstrip("}")
            # Label values are json-escaped strings; wrap the body into
            # a json object to parse them exactly.
            body = "{" + ",".join(
                f'"{kv.split("=", 1)[0]}":{kv.split("=", 1)[1]}'
                for kv in _split_labels(label_body)) + "}"
            labels = {k: str(v) for k, v in json.loads(body).items()}
        else:
            name = name_part
        value = math.inf if value_part == "+Inf" else float(value_part)
        out.setdefault(name, []).append((labels, value))
    return out


def _split_labels(body: str) -> Iterable[str]:
    """Split ``k1="v1",k2="v2"`` on commas outside quoted values
    (escape-aware: ``\\"`` stays inside a value, ``\\\\"`` closes it)."""
    depth_quote = False
    start = 0
    i = 0
    while i < len(body):
        ch = body[i]
        if ch == '"' and not _is_escaped(body, i):
            depth_quote = not depth_quote
        elif ch == "," and not depth_quote:
            yield body[start:i]
            start = i + 1
        i += 1
    if start < len(body):
        yield body[start:]


def histogram_percentiles_ms(samples: List[Tuple[Dict[str, str], float]],
                             qs: Sequence[float] = (0.5, 0.95, 0.99),
                             **match: str) -> Optional[List[float]]:
    """Percentiles (milliseconds) of one exposed histogram: feed the
    ``<name>_bucket`` samples from :func:`parse_exposition`, filtered
    to the label subset ``match``. None when no matching observations."""
    cum: Dict[float, int] = {}
    for labels, value in samples:
        if any(labels.get(k) != str(v) for k, v in match.items()):
            continue
        le = labels.get("le")
        if le is None:
            continue
        bound = math.inf if le == "+Inf" else float(le)
        cum[bound] = cum.get(bound, 0) + int(value)
    if not cum:
        return None
    buckets = sorted(cum.items(), key=lambda kv: kv[0])
    if buckets[-1][1] <= 0:
        return None
    out = []
    for q in qs:
        v = bucket_percentile(buckets, q)
        out.append(round(v * 1e3, 3) if v is not None else None)
    return out


# --- Standalone metrics server (worker runners have no HTTP surface) --

def serve_metrics(host: str = "0.0.0.0", port: int = 0,
                  name: str = "metrics"):
    """A minimal ``JsonHttpServer`` whose only job is the auto-wired
    ``GET /metrics`` (plus a health ``GET /``). Train/inference worker
    runners in subprocess/docker mode start one when
    ``RAFIKI_TPU_METRICS_PORT`` is set (container/services.py); in
    resident-runner mode the admin frontend's server already exposes
    the shared process registry."""
    from ..utils.service import JsonHttpServer

    server = JsonHttpServer(
        # rta: disable=RTA702 exporter liveness stub for scrapers; /metrics is the real surface
        [("GET", "/", lambda params, body, ctx: (200, {"status": "ok"}))],
        host=host, port=port, name=name)
    return server.start()


METRICS_PORT_ENV = "RAFIKI_TPU_METRICS_PORT"
