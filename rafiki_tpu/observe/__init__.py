"""Observability: metrics plane, request tracing, profiling, MFU.

Parity+: SURVEY.md §5 "Tracing / profiling" — the reference has no
first-party observability; the TPU-native rebuild makes it first-class:

- ``observe.metrics`` — process-wide counter/gauge/histogram registry
  with Prometheus text exposition; every ``JsonHttpServer`` service
  exposes it on ``GET /metrics`` for free.
- ``observe.trace`` — Dapper-style trace ids minted at the HTTP edges,
  carried in bus envelopes, recorded as JSONL span events and stitched
  by the admin's ``GET /trace/<id>``.
- ``observe.profiling`` — per-trial ``jax.profiler`` trace sessions and
  the MFU (model-FLOPs-utilization) meter feeding the north-star
  "≥90% chip utilization" metric (BASELINE.md).
- ``observe.serving`` — the serving frontend's counters, folded into
  the metrics registry (``/stats`` and ``/metrics`` read one source).
- ``observe.phases`` — trial-lifecycle phase timings and the
  dataset/staging residency-cache counters (``docs/training.md``).
- ``observe.attribution`` — the serving attribution ledger: per-bin
  and per-tenant request/queue/device-time accounting
  (``docs/observability.md``; default off, zero series when disabled).

``metrics``/``trace``/``serving``/``phases``/``attribution`` are
stdlib-only; the profiling symbols load lazily so a bus broker or
metrics scrape never imports jax.
"""

from . import attribution, metrics, phases, trace
from .serving import ServingStats

_PROFILING = ("MfuMeter", "DeviceProfileSession", "device_peak_flops",
              "flops_of_compiled", "flops_of_lowered",
              "start_device_profile", "trace_session",
              "trial_trace_dir")

__all__ = ["attribution", "metrics", "phases", "trace", "ServingStats",
           *_PROFILING]


def __getattr__(name):
    if name in _PROFILING:
        from . import profiling

        return getattr(profiling, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
