"""Observability: tracing, profiling, and chip-utilization metering.

Parity+: SURVEY.md §5 "Tracing / profiling" — the reference has no
first-party tracer (models used TF/Torch profilers ad hoc); the TPU-native
rebuild makes tracing and utilization first-class: `jax.profiler` trace
sessions per trial and an MFU (model FLOPs utilization) meter feeding the
north-star "≥90% chip utilization" metric (BASELINE.md).
"""

from .profiling import (MfuMeter, device_peak_flops, flops_of_compiled,
                        flops_of_lowered, trace_session, trial_trace_dir)
from .serving import ServingStats

__all__ = ["trace_session", "trial_trace_dir", "device_peak_flops",
           "flops_of_lowered", "flops_of_compiled", "MfuMeter",
           "ServingStats"]
