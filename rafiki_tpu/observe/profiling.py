"""Tracing and utilization metering (SURVEY.md §5, BASELINE.md north star).

Two independent facilities:

- **Trace sessions** — `jax.profiler.start_trace` wrapped in a context
  manager, opt-in via the ``RAFIKI_TPU_TRACE_DIR`` env var. The TrialRunner
  traces each trial into ``$RAFIKI_TPU_TRACE_DIR/<trial_id>/`` (viewable in
  TensorBoard's profile plugin), so "why is this trial slow" is answerable
  without code changes — per-trial toggles were the plan SURVEY.md §5 set
  out for the rebuild.

- **MFU metering** — model-FLOPs-utilization: achieved FLOP/s as a
  fraction of the device's peak. FLOPs per step come from XLA's own cost
  analysis of the *lowered* (pre-backend-compile) computation, so the
  meter adds tracing cost only, never a second XLA compile. Peak FLOP/s
  is looked up by device kind (bf16 peak — matmuls on the MXU run bf16);
  unknown device kinds (e.g. the CPU test mesh) can be calibrated via
  ``RAFIKI_TPU_PEAK_FLOPS``.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from typing import Any, Iterator, List, Optional

import jax

_log = logging.getLogger(__name__)

TRACE_DIR_ENV = "RAFIKI_TPU_TRACE_DIR"
PEAK_FLOPS_ENV = "RAFIKI_TPU_PEAK_FLOPS"

# Peak dense-matmul FLOP/s per chip by device-kind substring (bf16, the
# MXU's native training precision). Sources: public TPU spec sheets.
_PEAK_FLOPS_BY_KIND = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6": 918e12,        # Trillium
}


def trial_trace_dir(trial_id: str) -> Optional[str]:
    """Directory to trace this trial into, or None when tracing is off."""
    root = os.environ.get(TRACE_DIR_ENV, "").strip()
    if not root:
        return None
    return os.path.join(root, trial_id)


# jax.profiler supports ONE active trace per process; concurrent trials
# (resident-runner threads) must not turn an observability toggle into
# trial failures, so a busy profiler means "skip this trial's trace".
_trace_lock = threading.Lock()


@contextlib.contextmanager
def trace_session(trace_dir: Optional[str]) -> Iterator[None]:
    """Profile the enclosed block into ``trace_dir`` (no-op when None or
    when another trial is already being traced)."""
    if not trace_dir:
        yield
        return
    if not _trace_lock.acquire(blocking=False):
        _log.info("profiler busy; skipping trace for %s", trace_dir)
        yield
        return
    try:
        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
            _log.info("trace written to %s", trace_dir)
    finally:
        _trace_lock.release()


#: Hard ceiling on an on-demand profile session: the profiler holds
#: buffers and a process-wide lock, so a forgotten/abusive request must
#: self-bound.
PROFILE_MAX_S = 60.0


def _profile_counter():
    from . import metrics

    return metrics.registry().counter(
        "rafiki_tpu_profile_sessions_total",
        "On-demand device profile sessions (event=start|busy|stop)")


class DeviceProfileSession:
    """One bounded on-demand ``jax.profiler`` session on a LIVE
    serving worker (``POST /inference_jobs/<id>/profile``): started
    between bursts, stopped by the worker's serve loop once the
    deadline passes (or on loop exit), so serving itself is never
    paused — the session only observes the bursts that happen to run
    inside its window.

    Shares the process-wide profiler lock with the per-trial
    ``trace_session``: jax supports ONE active trace per process, and
    a busy profiler means "no session" (the admin surfaces that),
    never a failed worker."""

    def __init__(self, out_dir: str, deadline_mono: float):
        self.out_dir = out_dir
        self.deadline_mono = deadline_mono
        self._stopped = False

    def expired(self, now: float) -> bool:
        return now >= self.deadline_mono

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        try:
            jax.profiler.stop_trace()
            _log.info("on-demand profile written to %s", self.out_dir)
        except Exception:
            _log.exception("on-demand profile stop failed")
        finally:
            _trace_lock.release()
            try:
                _profile_counter().inc(event="stop")
            except Exception:
                pass


def start_device_profile(out_dir: str, duration_s: float,
                         ) -> Optional[DeviceProfileSession]:
    """Begin a bounded on-demand profile into ``out_dir``; None when
    the profiler is busy (a trial trace or another session holds it)
    or cannot start — the caller keeps serving either way."""
    duration_s = min(max(0.5, float(duration_s)), PROFILE_MAX_S)
    if not _trace_lock.acquire(blocking=False):
        _log.info("profiler busy; on-demand profile request skipped")
        try:
            _profile_counter().inc(event="busy")
        except Exception:
            pass
        return None
    try:
        os.makedirs(out_dir, exist_ok=True)
        jax.profiler.start_trace(out_dir)
    except Exception:
        _trace_lock.release()
        _log.exception("on-demand profile start failed")
        return None
    try:
        _profile_counter().inc(event="start")
    except Exception:
        pass
    return DeviceProfileSession(out_dir,
                                time.monotonic() + duration_s)


def device_peak_flops(device: Optional[Any] = None) -> Optional[float]:
    """Peak FLOP/s of one device, or None when unknown.

    TPU kinds come from the spec table above. For the CPU backend there
    is no spec sheet, so the first call times a dense f32 matmul and
    uses the achieved rate as a *calibrated roofline estimate* — an
    upper-ish bound good enough to keep the chip_util plumbing
    producing numbers everywhere (a CPU MFU is labeled as an estimate
    by callers, never compared against the TPU north star).
    """
    override = os.environ.get(PEAK_FLOPS_ENV, "").strip()
    if override:
        return float(override)
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    for sub, peak in _PEAK_FLOPS_BY_KIND.items():
        if sub in kind:
            return peak
    if getattr(device, "platform", "") == "cpu":
        return _cpu_peak_flops_estimate()
    return None


_cpu_peak_cache: List[float] = []
_cpu_peak_lock = threading.Lock()


def _cpu_peak_flops_estimate() -> float:
    """Best-of-3 achieved FLOP/s of a jitted 512^3 f32 matmul, cached
    per process. ~100 ms once; runs on whatever cores this process has
    (the same budget a training step would get)."""
    with _cpu_peak_lock:
        if _cpu_peak_cache:
            return _cpu_peak_cache[0]
        import numpy as _np

        n = 512
        x = jax.device_put(_np.ones((n, n), _np.float32))
        mm = jax.jit(lambda a: a @ a)
        _np.asarray(mm(x))  # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            _np.asarray(mm(x))  # np.asarray forces a real sync
            best = min(best, time.perf_counter() - t0)
        peak = 2 * n ** 3 / max(best, 1e-9)
        _cpu_peak_cache.append(peak)
        return peak


def _flops_of_cost(cost: Any) -> Optional[float]:
    if isinstance(cost, list):  # some backends return one dict per module
        cost = cost[0] if cost else {}
    flops = (cost or {}).get("flops")
    if flops is None or flops <= 0:
        return None
    return float(flops)


def flops_of_lowered(lowered: Any) -> Optional[float]:
    """FLOPs of one execution of a ``jax.stages.Lowered`` computation.

    Uses the pre-compile cost analysis (tracing-cost only); the CPU
    backend provides it, TPU does not (use ``flops_of_compiled`` there).
    Returns None when the backend has no estimate.
    """
    try:
        return _flops_of_cost(lowered.cost_analysis())
    except Exception:
        return None


def flops_of_compiled(compiled: Any) -> Optional[float]:
    """FLOPs of one execution of a ``jax.stages.Compiled`` executable —
    XLA's post-compile cost model (available on TPU)."""
    try:
        return _flops_of_cost(compiled.cost_analysis())
    except Exception:
        return None


class MfuMeter:
    """Accumulates step counts against wall time → achieved FLOP/s and MFU.

    ``flops_per_step`` is the whole-mesh cost of one (already sharded)
    train step; ``n_devices`` scales the peak accordingly, so the reading
    is utilization *of the chip group the trial runs on* — the quantity
    the north star bounds (≥90% during train).
    """

    def __init__(self, flops_per_step: Optional[float],
                 n_devices: int = 1,
                 peak_flops_per_device: Optional[float] = None):
        if peak_flops_per_device is None:
            peak_flops_per_device = device_peak_flops()
        self.flops_per_step = flops_per_step
        self.peak = (peak_flops_per_device * n_devices
                     if peak_flops_per_device else None)
        self.n_steps = 0
        # Monotonic: elapsed-time math must survive a wall-clock step
        # (NTP slew/jump would otherwise produce negative or inflated
        # rates mid-trial).
        self._t0 = time.monotonic()

    def tick(self, n_steps: int = 1) -> None:
        self.n_steps += n_steps

    def reset(self) -> None:
        """Restart the measurement window (e.g. after the first-step
        XLA compile, which is not part of steady-state utilization)."""
        self.n_steps = 0
        self._t0 = time.monotonic()

    @property
    def elapsed(self) -> float:
        return time.monotonic() - self._t0

    @property
    def achieved_flops(self) -> Optional[float]:
        """Achieved FLOP/s so far (None when the step cost is unknown)."""
        if not self.flops_per_step or self.n_steps == 0:
            return None
        return self.flops_per_step * self.n_steps / max(self.elapsed, 1e-9)

    @property
    def mfu(self) -> Optional[float]:
        """Fraction of peak [0, ~1], or None when peak/cost are unknown."""
        achieved = self.achieved_flops
        if achieved is None or not self.peak:
            return None
        return achieved / self.peak
