"""JaxTransformerTagger: Transformer encoder for sequence tagging.

Beyond-parity zoo model: the reference's POS_TAGGING task ships only a
BiLSTM (SURVEY.md §2 "Example models"); this adds a Transformer encoder
built on the framework's own attention ops (``rafiki_tpu.ops``) so long
sequences are first-class:

- single chip / chip group: Pallas ``flash_attention`` on TPU (blockwise
  XLA fallback elsewhere) — O(block) memory, so ``max_len`` can grow far
  past what a materialised T×T score matrix allows;
- ``sequence_parallel`` knob > 1: the sequence dimension shards over the
  ``sp`` mesh axis and attention runs context-parallel over ICI —
  a ``ppermute`` ring (``ring_attention``, the default) or the Ulysses
  all-to-all head re-sharding (``sp_schedule="alltoall"``, needs
  ``n_heads % sequence_parallel == 0``) — scaling context length with
  the chip group.

Same corpus-dataset contract, hashed vocabulary, and per-token
probability output as ``JaxPosTagger``, so the Advisor, TrainWorker, and
Predictor ensemble treat the two interchangeably.
"""

from __future__ import annotations

import functools
import json
import math
from typing import Any, Dict, List, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import traverse_util

from ..model import CategoricalKnob, FixedKnob, FloatKnob, IntegerKnob
from ..model.base import BaseModel, Params
from ..model.dataset import (PAD_ID, hash_token_ids,
                             load_corpus_dataset)
from ..model.jax_model import (_step_cache_get, _step_cache_put,
                               step_cache_key)
from ..model.logger import logger
from ..model.loop_ckpt import LoopCheckpointer, epoch_rng, schedule_epochs
from ..ops import (default_attention, sequence_sharded_attention,
                   switch_moe)
from ..parallel import (DP_AXIS, SP_AXIS, batch_sharding, build_mesh,
                        device_get_tree,
                        replicated, shard_variables)
from ..parallel.chips import ChipGroup

def _sinusoidal(max_len: int, dim: int) -> np.ndarray:
    pos = np.arange(max_len)[:, None]
    div = np.exp(np.arange(0, dim, 2) * (-math.log(10000.0) / dim))
    pe = np.zeros((max_len, dim), np.float32)
    pe[:, 0::2] = np.sin(pos * div)
    pe[:, 1::2] = np.cos(pos * div)
    return pe


class _EncoderBlock(nn.Module):
    """Pre-LN encoder block; attention is injected so the same module
    serves flash (single group) and sequence-parallel execution.

    ``moe_experts > 0`` replaces the dense FFN with a Switch-routed
    expert FFN (``rafiki_tpu.ops.switch_moe``); the expert-stacked
    parameters' names contain ``expert`` so the sharding rules place
    them over the ``ep`` mesh axis. The router's load-balance loss is
    sown into the ``losses`` collection for the train step to collect.
    """
    n_heads: int
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    moe_experts: int = 0
    # Inside the pipeline's shard_map (where GSPMD cannot partition for
    # us) the expert stack arrives pre-sliced: ``moe_local_experts`` is
    # this rank's slice size and ``ep_axis`` the mesh axis to psum the
    # partial expert outputs over. None/default = the GSPMD path
    # (full stack declared; PartitionSpec("ep", ...) does the rest).
    moe_local_experts: Optional[int] = None
    ep_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x, attn_fn, kv_mask, *, deterministic: bool):
        d_model = x.shape[-1]
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        qkv = nn.Dense(3 * d_model, use_bias=False, dtype=self.dtype)(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(a):  # (B, T, D) -> (B, H, T, Dh)
            b, t, _ = a.shape
            return a.reshape(b, t, self.n_heads,
                             d_model // self.n_heads).transpose(0, 2, 1, 3)

        o = attn_fn(heads(q), heads(k), heads(v), kv_mask)
        b, nh, t, dh = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(b, t, nh * dh)
        x = x + nn.Dense(d_model, use_bias=False, dtype=self.dtype)(o)

        h = nn.LayerNorm(dtype=jnp.float32)(x)
        if self.moe_experts > 0:
            e, f = self.moe_experts, 4 * d_model
            e_loc = self.moe_local_experts or e
            init = nn.initializers.lecun_normal()
            gate_w = self.param("moe_gate", init, (d_model, e),
                                jnp.float32)
            w1 = self.param("expert_w1", init, (e_loc, d_model, f),
                            self.dtype)
            b1 = self.param("expert_b1", nn.initializers.zeros, (e_loc, f),
                            self.dtype)
            w2 = self.param("expert_w2", init, (e_loc, f, d_model),
                            self.dtype)
            b2 = self.param("expert_b2", nn.initializers.zeros,
                            (e_loc, d_model), self.dtype)
            tokens = h.astype(self.dtype).reshape(b * t, d_model)
            out, aux = switch_moe(tokens, gate_w, w1, b1, w2, b2,
                                  token_mask=kv_mask.reshape(b * t),
                                  expert_axis=self.ep_axis)
            self.sow("losses", "moe_aux", aux)
            out = nn.Dropout(self.dropout,
                             deterministic=deterministic)(out)
            return x + out.reshape(b, t, d_model)
        h = nn.Dense(4 * d_model, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dropout(self.dropout, deterministic=deterministic)(h)
        return x + nn.Dense(d_model, dtype=self.dtype)(h)


def quantized_encoder_block(qvars, scales, fvars, prefix: str, x,
                            attn_fn, n_heads: int, kv_mask=None):
    """Dequant-free int8 forward of ONE dense-FFN ``_EncoderBlock``
    (deterministic — the serving path never drops out): the four
    Dense matmuls run int8 x int8 -> int32 via ``dynamic_int8_matmul``
    from per-output-channel weight scales, LayerNorms stay f32,
    mirroring ``_EncoderBlock.__call__`` exactly. ``prefix`` is the
    block's flat param path (e.g. ``params/_EncoderBlock_0``). Returns
    None for a MoE block (3-D expert stacks sit outside the
    quantizer's 2-D/4-D kernel eligibility) so callers fall back to
    the generic dequantized path. Shared by the transformer zoo's
    ``quantized_apply`` implementations (models/vit.py); the
    ``bench.py --quant int8`` accuracy gate is the regression net."""
    from ..model.jax_model import dynamic_int8_matmul

    if f"{prefix}/moe_gate" in fvars or f"{prefix}/moe_gate" in qvars:
        return None

    def ln(h, name):
        g = fvars[f"{prefix}/{name}/scale"].astype(jnp.float32)
        b = fvars[f"{prefix}/{name}/bias"].astype(jnp.float32)
        hf = h.astype(jnp.float32)
        m = hf.mean(-1, keepdims=True)
        v = ((hf - m) ** 2).mean(-1, keepdims=True)
        return (hf - m) * jax.lax.rsqrt(v + 1e-6) * g + b

    def dense(h, name):
        k = f"{prefix}/{name}/kernel"
        flat2d = h.reshape(-1, h.shape[-1])
        if k in qvars:
            out = dynamic_int8_matmul(flat2d, qvars[k], scales[k])
        else:  # per-layer f32 fallback
            out = flat2d @ fvars[k].astype(jnp.float32)
        out = out.reshape(*h.shape[:-1], out.shape[-1])
        bkey = f"{prefix}/{name}/bias"
        if bkey in fvars:
            out = out + fvars[bkey].astype(jnp.float32)
        return out

    d_model = x.shape[-1]
    x = x.astype(jnp.float32)
    h = ln(x, "LayerNorm_0")
    qkv = dense(h, "Dense_0")
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(a):  # (B, T, D) -> (B, H, T, Dh)
        b, t, _ = a.shape
        return a.reshape(b, t, n_heads,
                         d_model // n_heads).transpose(0, 2, 1, 3)

    o = attn_fn(heads(q), heads(k), heads(v), kv_mask)
    b, nh, t, dh = o.shape
    o = o.transpose(0, 2, 1, 3).reshape(b, t, nh * dh)
    x = x + dense(o, "Dense_1")
    h = ln(x, "LayerNorm_1")
    h = nn.gelu(dense(h, "Dense_2"))
    return x + dense(h, "Dense_3")


class _TransformerTagger(nn.Module):
    vocab_size: int
    d_model: int
    n_heads: int
    n_layers: int
    n_tags: int
    max_len: int
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    moe_experts: int = 0

    @nn.compact
    def __call__(self, ids, attn_fn, *, train: bool = False):
        kv_mask = ids != PAD_ID  # hashed token ids are >= 1
        x = nn.Embed(self.vocab_size, self.d_model,
                     dtype=self.dtype)(ids)
        pe = jnp.asarray(_sinusoidal(self.max_len, self.d_model))
        x = x + pe[None, :ids.shape[1]].astype(x.dtype)
        for _ in range(self.n_layers):
            x = _EncoderBlock(self.n_heads, dropout=self.dropout,
                              dtype=self.dtype,
                              moe_experts=self.moe_experts)(
                x, attn_fn, kv_mask, deterministic=not train)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        return nn.Dense(self.n_tags, dtype=jnp.float32)(x)


class JaxTransformerTagger(BaseModel):
    """Transformer token tagger; flash attention, optional sp ring."""

    #: Congruence metadata for the stacked-ensemble probe: sequence
    #: taggers serve variable-length token batches through their own
    #: predict path (no JaxModel bucket substrate), so same-family
    #: bins fall back to per-member runners by contract.
    stack_compatible = False

    @staticmethod
    def get_knob_config():
        return {
            "d_model": CategoricalKnob([64, 128, 256]),
            "n_heads": CategoricalKnob([2, 4, 8]),
            "n_layers": IntegerKnob(1, 6),
            "learning_rate": FloatKnob(1e-4, 3e-2, is_exp=True),
            "batch_size": CategoricalKnob([16, 32, 64]),
            "max_epochs": IntegerKnob(3, 30),
            # Context length is searchable: flash/ring attention keep the
            # memory profile linear in max_len, so long contexts are a
            # knob, not a redesign.
            "max_len": CategoricalKnob([32, 64, 128, 256, 512]),
            "dropout": FloatKnob(0.0, 0.3),
            "vocab_size": FixedKnob(16384),
            # > 1 shards the sequence dim over sp chips.
            "sequence_parallel": FixedKnob(1),
            # Context-parallel schedule when sequence_parallel > 1:
            # "ring" (ppermute K/V rotation, T/n working set) or
            # "alltoall" (Ulysses head re-sharding, two collectives;
            # needs n_heads % sequence_parallel == 0).
            "sp_schedule": FixedKnob("ring"),
            # > 0 replaces each block's dense FFN with a Switch-routed
            # mixture of experts (top-1, capacity-dropped); experts
            # shard over the ep mesh axis set by expert_parallel.
            "moe_experts": FixedKnob(0),
            "expert_parallel": FixedKnob(1),
            # > 1 pipelines the encoder blocks over a pp mesh axis
            # (GPipe microbatch schedule; needs n_layers % pp == 0;
            # composes with sequence_parallel, dropout AND moe_experts/
            # expert_parallel — block params and optimizer state are
            # STORED stage-sharded (P("pp", ...)), expert stacks
            # additionally over ep (P("pp", "ep", ...)), ~1/pp per
            # chip).
            "pipeline_parallel": FixedKnob(1),
            # Microbatches per pipeline step; 0 = auto (~4·pp).
            "pp_microbatches": FixedKnob(0),
            # Deployment knob: pins init, dropout streams, and
            # per-epoch data order (and therefore checkpoint-resume
            # step identity) for reproducibility tests and re-runs.
            "seed": FixedKnob(0),
        }

    def __init__(self, **knobs: Any):
        super().__init__(**knobs)
        self._variables = None
        self._module: Optional[_TransformerTagger] = None
        self._meta: Dict[str, Any] = {}
        self._mesh = None
        self._predict_fn = None
        self._vars_dev = None

    # --- plumbing ---

    @property
    def mesh(self):
        if self._mesh is None:
            sp = int(self.knobs.get("sequence_parallel", 1))
            ep = int(self.knobs.get("expert_parallel", 1))
            pp = int(self.knobs.get("pipeline_parallel", 1))
            experts = int(self.knobs.get("moe_experts", 0))
            if ep > 1 and (experts == 0 or experts % ep != 0):
                # Silent fallback would pay the smaller dp axis while
                # the ep axis idles (dense model) or every expert
                # replicates (indivisible stack) — reject loudly.
                raise ValueError(
                    f"expert_parallel ({ep}) needs moe_experts set and "
                    f"divisible by it (got moe_experts={experts})")
            if pp > 1:
                n_layers = int(self.knobs.get("n_layers", 2))
                if n_layers % pp != 0:
                    raise ValueError(f"pipeline_parallel ({pp}) must "
                                     f"divide n_layers ({n_layers})")
            self._mesh = build_mesh(ChipGroup.current().devices(), sp=sp,
                                    ep=ep, pp=pp)
        return self._mesh

    def _attn_fn(self):
        """The attention the encoder blocks run, chosen by mesh shape.

        Bidirectional (non-causal) in all cases; tagging attends the
        whole sentence.
        """
        mesh = self.mesh
        if mesh.shape[SP_AXIS] > 1:
            mode = str(self.knobs.get("sp_schedule", "ring"))
            return lambda q, k, v, kv_mask: sequence_sharded_attention(
                q, k, v, mesh, causal=False, kv_mask=kv_mask, mode=mode)
        return default_attention(causal=False)

    # --- pipeline-parallel layout -------------------------------------
    #
    # With ``pipeline_parallel > 1`` the encoder blocks are STORED
    # stage-stacked: a ``{"outer": ..., "stages": {"stage{j}": ...}}``
    # tree whose stage leaves carry a leading pp axis that
    # ``shard_variables``' path rule places with ``P("pp", ...)`` —
    # each chip persistently holds only its own layer span (params AND
    # optimizer state drop ~1/pp per chip), not just pipelined compute.
    # ``self._variables`` keeps the ordinary flax layout so init /
    # dump_parameters / load_parameters / param sharing are unchanged;
    # the two helpers below convert at the train/predict boundary.

    def _pp_split(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Ordinary flax params → pp layout (host-side, cheap)."""
        pp = int(self.knobs.get("pipeline_parallel", 1))
        span = int(self.knobs.get("n_layers", 2)) // pp
        tmap = jax.tree_util.tree_map
        outer = {k: v for k, v in params.items()
                 if not k.startswith("_EncoderBlock_")}
        stages = {
            f"stage{j}": tmap(
                lambda *a: np.stack([np.asarray(x) for x in a]),
                *[params[f"_EncoderBlock_{s * span + j}"]
                  for s in range(pp)])
            for j in range(span)}
        return {"outer": outer, "stages": stages}

    def _pp_merge(self, pp_params: Dict[str, Any]) -> Dict[str, Any]:
        """pp layout → ordinary flax params (inverse of ``_pp_split``)."""
        pp = int(self.knobs.get("pipeline_parallel", 1))
        span = int(self.knobs.get("n_layers", 2)) // pp
        tmap = jax.tree_util.tree_map
        out = dict(pp_params["outer"])
        for j in range(span):
            for s in range(pp):
                out[f"_EncoderBlock_{s * span + j}"] = tmap(
                    lambda a, _s=s: a[_s], pp_params["stages"][f"stage{j}"])
        return out

    def _pp_logits_fn(self, n_tags: int, train: bool):
        """Assembled forward for ``pipeline_parallel > 1``: embed →
        GPipe-pipelined encoder blocks (``ops.pipeline_apply`` inside
        ``shard_map`` over pp, batch over dp, sequence over sp when
        ``sequence_parallel > 1``, experts over ep when
        ``moe_experts > 0``) → head, reading the pp param layout
        (see ``_pp_split``). Dropout is supported: the key is folded
        per (optimizer step, schedule tick, stage, sp shard), so every
        microbatch position draws an independent mask. MoE is
        supported: stage-stacked expert leaves enter the shard_map
        sharded ``P("pp", "ep", ...)`` so each rank holds its stage's
        slice of the expert stack, ``switch_moe`` runs in its
        collective form (route globally, compute local experts, psum
        partials over ep), and the router load-balance loss rides the
        pipeline in the microbatch carry.

        Returns ``logits_fn(pp_params, ids, step_i) -> (logits, aux)``
        where ``aux`` is the mean MoE load-balance loss (0.0 for dense
        models).
        """
        from jax.sharding import PartitionSpec as P

        from ..jaxcompat import shard_map
        from ..ops import pipeline_apply, ring_attention, ulysses_attention
        from ..parallel import EP_AXIS, PP_AXIS

        mesh = self.mesh
        pp = int(self.knobs.get("pipeline_parallel", 1))
        sp = mesh.shape[SP_AXIS]
        ep = mesh.shape[EP_AXIS]
        experts = int(self.knobs.get("moe_experts", 0))
        n_layers = int(self.knobs.get("n_layers", 2))
        span = n_layers // pp
        d_model = int(self.knobs.get("d_model", 128))
        vocab = int(self.knobs.get("vocab_size", 16384))
        max_len = int(self.knobs.get("max_len", 128))
        micro = int(self.knobs.get("pp_microbatches", 0))
        dropout = float(self.knobs.get("dropout", 0.0)) if train else 0.0
        seed = int(self.knobs.get("seed", 0))
        block = _EncoderBlock(
            int(self.knobs.get("n_heads", 4)), dropout=dropout,
            dtype=jnp.bfloat16, moe_experts=experts,
            moe_local_experts=(experts // ep) if ep > 1 else None,
            ep_axis=EP_AXIS if (ep > 1 and experts > 0) else None)
        if sp > 1:
            # Inside the pp shard_map the sequence dim is already the
            # local sp shard, so the attention must be the *collective*
            # form (ring/Ulysses over the sp axis of the SAME
            # shard_map), not sequence_sharded_attention's own wrapper.
            mode = str(self.knobs.get("sp_schedule", "ring"))
            inner = (ring_attention if mode == "ring"
                     else ulysses_attention)
            attn = (lambda q, k, v, kv_mask: inner(
                q, k, v, causal=False, axis_size=sp, kv_mask=kv_mask))
        else:
            attn = self._attn_fn()

        act_spec = P(DP_AXIS, SP_AXIS) if sp > 1 else P(DP_AXIS)

        def stage_leaf_spec(path, leaf):
            name = "/".join(str(getattr(p, "key", p))
                            for p in path).lower()
            if ep > 1 and experts > 0 and "expert" in name:
                return P(PP_AXIS, EP_AXIS)
            return P(PP_AXIS)

        def make_run_blocks(stages_tree):
            stage_specs = jax.tree_util.tree_map_with_path(
                stage_leaf_spec, stages_tree)

            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=(stage_specs, act_spec, act_spec, P()),
                out_specs=(act_spec, P()), check_vma=False)
            def run_blocks(stages, x, mask, step_i):
                local = jax.tree_util.tree_map(lambda a: a[0], stages)

                def stage_fn(prm, xm, t):
                    xx, mm, aux = xm
                    det = dropout == 0.0
                    rngs = None
                    if not det:
                        key = jax.random.key(seed + 1)
                        for part in (step_i, t,
                                     jax.lax.axis_index(PP_AXIS)):
                            key = jax.random.fold_in(key, part)
                        if sp > 1:
                            key = jax.random.fold_in(
                                key, jax.lax.axis_index(SP_AXIS))
                    for j in range(span):
                        if not det:
                            rngs = {"dropout": jax.random.fold_in(key, j)}
                        # mutable=["losses"] is a no-op for dense blocks
                        # (empty collection, aux += 0), so one call
                        # covers both MoE and dense stages.
                        xx, mods = block.apply(
                            {"params": prm[f"stage{j}"]}, xx, attn,
                            mm, deterministic=det, rngs=rngs,
                            mutable=["losses"])
                        aux = aux + sum(jax.tree_util.tree_leaves(
                            mods.get("losses", {})))
                    return (xx, mm, aux)

                b = x.shape[0]
                if micro > 0:
                    if b % micro:
                        raise ValueError(
                            f"pp_microbatches ({micro}) must divide the "
                            f"per-dp-shard batch ({b})")
                    m = micro
                else:
                    m = min(b, 4 * pp)
                    while b % m:  # auto: largest divisor <= 4·pp
                        m -= 1
                xs = x.reshape(m, b // m, *x.shape[1:])
                ms = mask.reshape(m, b // m, *mask.shape[1:])
                # The aux accumulator rides the pipeline with the
                # activations: each stage adds its blocks' router
                # losses, so the collected last-stage value is the
                # microbatch's total across ALL layers.
                zeros = jnp.zeros((m, 1), jnp.float32)
                out, _, aux = pipeline_apply(
                    stage_fn, local, (xs, ms, zeros), axis_size=pp,
                    stage_takes_tick=True)
                aux = aux.mean()
                # Every rank must return the same replicated scalar
                # for out_specs=P(): average the data-shard axes.
                aux = jax.lax.pmean(aux, DP_AXIS)
                if sp > 1:
                    aux = jax.lax.pmean(aux, SP_AXIS)
                return out.reshape(b, *out.shape[2:]), aux

            return run_blocks

        def logits_fn(pp_params, ids, step_i):
            outer = pp_params["outer"]
            mask = ids != PAD_ID
            x = nn.Embed(vocab, d_model, dtype=jnp.bfloat16).apply(
                {"params": outer["Embed_0"]}, ids)
            pe = jnp.asarray(_sinusoidal(max_len, d_model))
            x = x + pe[None, :ids.shape[1]].astype(x.dtype)
            x, aux = make_run_blocks(pp_params["stages"])(
                pp_params["stages"], x, mask, step_i)
            x = nn.LayerNorm(dtype=jnp.float32).apply(
                {"params": outer["LayerNorm_0"]}, x)
            return nn.Dense(n_tags, dtype=jnp.float32).apply(
                {"params": outer["Dense_0"]}, x), aux

        return logits_fn

    def _ensure_module(self, n_tags: int) -> None:
        if self._module is None:
            self._module = _TransformerTagger(
                vocab_size=int(self.knobs.get("vocab_size", 16384)),
                d_model=int(self.knobs.get("d_model", 128)),
                n_heads=int(self.knobs.get("n_heads", 4)),
                n_layers=int(self.knobs.get("n_layers", 2)),
                n_tags=n_tags,
                max_len=int(self.knobs.get("max_len", 128)),
                dropout=float(self.knobs.get("dropout", 0.0)),
                moe_experts=int(self.knobs.get("moe_experts", 0)))

    def _encode(self, sentences: List[List[str]]):
        max_len = int(self.knobs.get("max_len", 128))
        vocab = int(self.knobs.get("vocab_size", 16384))
        ids = np.stack([hash_token_ids(s, vocab, max_len)
                        for s in sentences])
        lengths = np.asarray([min(len(s), max_len) for s in sentences],
                             np.int32)
        return ids, lengths

    # --- BaseModel ---

    def train(self, dataset_path: str, *,
              shared_params: Optional[Params] = None, **kwargs: Any) -> None:
        ds = load_corpus_dataset(dataset_path)
        n_tags = len(ds.tag_names)
        self._ensure_module(n_tags)
        self._meta = {"tag_names": list(ds.tag_names)}
        mesh = self.mesh
        dp = mesh.shape[DP_AXIS]
        max_len = int(self.knobs.get("max_len", 128))

        ids, lengths = self._encode(ds.sentences)
        tags = np.zeros((ds.size, max_len), np.int32)
        for i, t in enumerate(ds.tags):
            tags[i, :min(len(t), max_len)] = t[:max_len]

        batch_size = min(int(self.knobs.get("batch_size", 32)), ds.size)
        batch_size = max(dp, (batch_size // dp) * dp)
        max_epochs = int(self.knobs.get("max_epochs", 10))
        if self.knobs.get("quick_train", False):
            max_epochs = min(max_epochs,
                             int(self.knobs.get("trial_epochs", 1)))
        steps = max(1, ds.size // batch_size)

        rng = jax.random.key(int(self.knobs.get("seed", 0)))
        attn = self._attn_fn()
        module = self._module
        variables = jax.jit(
            lambda r, ids: module.init(r, ids, attn, train=False))(
            rng, jnp.zeros((dp, max_len), jnp.int32))
        if shared_params is not None:
            flat = traverse_util.flatten_dict(variables, sep="/")
            for kk, vv in shared_params.items():
                if kk in flat and tuple(flat[kk].shape) == tuple(vv.shape):
                    flat[kk] = jnp.asarray(vv)
            variables = traverse_util.unflatten_dict(flat, sep="/")
        # Expert-stacked leaves shard over ep, everything else
        # replicates (shard_variables' rules; with ep == 1 this is the
        # plain replicated placement). Under pp > 1 the blocks are
        # first re-laid stage-stacked so their leaves (and the optimizer
        # state derived from them) STORE sharded over pp — per-chip
        # param bytes drop ~1/pp, the point of pipeline parallelism.
        pp_mode = mesh.shape["pp"] > 1
        if pp_mode:
            params = shard_variables(
                self._pp_split(variables["params"]), mesh)
        else:
            params = shard_variables(variables, mesh)["params"]

        sched_epochs = schedule_epochs(kwargs, max_epochs)
        cache_key = step_cache_key(self, "train", mesh, steps, sched_epochs)
        cached = _step_cache_get(cache_key)
        if cached is not None:
            tx, train_step = cached["tx"], cached["step"]
        else:
            lr = float(self.knobs.get("learning_rate", 1e-3))
            total = max(1, steps * sched_epochs)
            sched = optax.warmup_cosine_decay_schedule(
                init_value=lr * 0.1, peak_value=lr,
                warmup_steps=max(1, total // 10), decay_steps=total,
                end_value=lr * 0.02)
            tx = optax.adamw(sched, weight_decay=1e-3)
            drop_key = jax.random.key(int(self.knobs.get("seed", 0)) + 1)
            pp_logits = (self._pp_logits_fn(n_tags, train=True)
                         if pp_mode else None)

            @jax.jit
            def train_step(params, opt_state, ids, lengths, tags, step_i):
                def loss_fn(p):
                    if pp_logits is not None:
                        # The pipelined forward carries the MoE router
                        # loss in the microbatch stream and returns it
                        # alongside the logits (0.0 for dense models).
                        logits, aux = pp_logits(p, ids, step_i)
                    else:
                        logits, mods = module.apply(
                            {"params": p}, ids, attn, train=True,
                            rngs={"dropout": jax.random.fold_in(
                                drop_key, step_i)},
                            mutable=["losses"])
                        # Router load-balance terms sown by MoE blocks
                        # (empty collection for dense models).
                        aux = sum(jax.tree_util.tree_leaves(
                            mods.get("losses", {})))
                    mask = (jnp.arange(logits.shape[1])[None, :]
                            < lengths[:, None]).astype(jnp.float32)
                    losses = optax.softmax_cross_entropy_with_integer_labels(
                        logits, tags)
                    loss = (losses * mask).sum() / jnp.maximum(mask.sum(),
                                                               1)
                    loss = loss + 0.01 * aux
                    correct = ((logits.argmax(-1) == tags) * mask).sum() \
                        / jnp.maximum(mask.sum(), 1)
                    return loss, correct
                (loss, acc), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                updates, opt_state = tx.update(grads, opt_state, params)
                return (optax.apply_updates(params, updates), opt_state,
                        loss, acc)

            _step_cache_put(cache_key, {"tx": tx, "step": train_step})

        opt_state = tx.init(params)
        logger.define_plot("Training", ["loss", "token_acc"],
                           x_axis="epoch")
        x_shard = batch_sharding(mesh)
        ckpt = LoopCheckpointer(kwargs)
        (params, opt_state), start_epoch = ckpt.restore((params, opt_state))
        seed = int(self.knobs.get("seed", 0))
        last_epoch = None
        # step_i drives the dropout fold_in (and the pp per-tick rng);
        # resuming it at the epoch boundary keeps the resumed run's rng
        # stream identical to an uninterrupted run's.
        step_i = start_epoch * steps
        for epoch in range(start_epoch, max_epochs):
            order = epoch_rng(seed, epoch).permutation(ds.size)
            ep_loss = ep_acc = 0.0
            for s in range(steps):
                sel = order[s * batch_size:(s + 1) * batch_size]
                if len(sel) < batch_size:
                    sel = np.resize(order, batch_size)
                params, opt_state, loss, acc = train_step(
                    params, opt_state,
                    jax.device_put(ids[sel], x_shard),
                    jax.device_put(lengths[sel], x_shard),
                    jax.device_put(tags[sel], x_shard),
                    jnp.int32(step_i))
                step_i += 1
                ep_loss += float(loss)
                ep_acc += float(acc)
            logger.log(epoch=epoch, loss=ep_loss / steps,
                       token_acc=ep_acc / steps)
            last_epoch = epoch
            ckpt.after_epoch(epoch, (params, opt_state), max_epochs)
        ckpt.after_loop(last_epoch, (params, opt_state))

        if pp_mode:
            params = self._pp_merge(params)
        self._variables = {"params": device_get_tree(params)}
        self._invalidate_compiled()

    def evaluate(self, dataset_path: str) -> float:
        assert self._variables is not None
        ds = load_corpus_dataset(dataset_path)
        max_len = int(self.knobs.get("max_len", 128))
        probs = self._predict_probs(ds.sentences)
        n_correct = n_total = 0
        for i, gold in enumerate(ds.tags):
            length = min(len(gold), max_len)
            pred = probs[i, :length].argmax(-1)
            n_correct += int((pred == np.asarray(gold[:length])).sum())
            n_total += length
        return n_correct / max(n_total, 1)

    def predict(self, queries: List[Any]) -> List[Any]:
        """Per-token tag distributions (the Predictor ensemble contract;
        see JaxPosTagger.predict)."""
        assert self._variables is not None
        if not queries:
            return []
        sentences = [list(q) for q in queries]
        probs = self._predict_probs(sentences)
        max_len = int(self.knobs.get("max_len", 128))
        return [probs[i, :min(len(s), max_len)].tolist()
                for i, s in enumerate(sentences)]

    def _predict_probs(self, sentences: List[List[str]]) -> np.ndarray:
        self._ensure_module(len(self._meta["tag_names"]))
        dp = self.mesh.shape[DP_AXIS]
        pp_mode = self.mesh.shape["pp"] > 1
        if self._vars_dev is None:
            # Same placement rules as training: expert stacks shard
            # over ep, stage stacks over pp (replicating either would
            # cost ep×/pp× HBM at inference), everything else
            # replicates.
            if pp_mode:
                self._vars_dev = {"params": shard_variables(
                    self._pp_split(self._variables["params"]),
                    self.mesh)}
            else:
                self._vars_dev = shard_variables(self._variables,
                                                 self.mesh)
        if self._predict_fn is None:
            if pp_mode:
                pp_logits = self._pp_logits_fn(
                    len(self._meta["tag_names"]), train=False)
                self._predict_fn = jax.jit(
                    lambda v, ids: jax.nn.softmax(
                        pp_logits(v["params"], ids, jnp.int32(0))[0], -1))
            else:
                module, attn = self._module, self._attn_fn()
                self._predict_fn = jax.jit(
                    lambda v, ids: jax.nn.softmax(
                        module.apply(v, ids, attn, train=False), -1))
        ids, _ = self._encode(sentences)
        n = len(sentences)
        bucket = dp
        while bucket < n:
            bucket *= 2
        if n < bucket:
            ids = np.concatenate(
                [ids, np.zeros((bucket - n, ids.shape[1]), ids.dtype)])
        out = np.asarray(self._predict_fn(
            self._vars_dev, jax.device_put(ids, batch_sharding(self.mesh))))
        return out[:n]

    def dump_parameters(self) -> Params:
        assert self._variables is not None
        flat = traverse_util.flatten_dict(self._variables, sep="/")
        out: Params = {k: np.asarray(v) for k, v in flat.items()}
        out["_meta/tag_names_json"] = np.frombuffer(
            json.dumps(self._meta["tag_names"]).encode(), np.uint8)
        return out

    def load_parameters(self, params: Params) -> None:
        blob = params.get("_meta/tag_names_json")
        assert blob is not None, "params missing _meta/tag_names_json"
        self._meta = {"tag_names": json.loads(
            np.asarray(blob).tobytes().decode())}
        flat = {k: np.asarray(v) for k, v in params.items()
                if not k.startswith("_meta/")}
        self._variables = traverse_util.unflatten_dict(flat, sep="/")
        self._module = None
        self._invalidate_compiled()
        self._ensure_module(len(self._meta["tag_names"]))

    def _invalidate_compiled(self) -> None:
        self._predict_fn = None
        self._vars_dev = None

    def destroy(self) -> None:
        self._invalidate_compiled()
        self._variables = None
        self._module = None
