"""JaxFeedForward: parity model for the reference's ``TfFeedForward``.

Parity: SURVEY.md §2 "Example models" — a small dense network for
fashion-MNIST-scale image classification, the platform's "CPU-runnable PR1
reference" config (BASELINE.json configs[0]). Knob space mirrors the
reference's (hidden layer count/size, learning rate, batch size, epochs),
expressed with the SDK's typed knobs.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from ..model import CategoricalKnob, FixedKnob, FloatKnob, IntegerKnob
from ..model.jax_model import JaxModel


class _FeedForward(nn.Module):
    hidden_layer_count: int
    hidden_layer_units: int
    n_classes: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for _ in range(self.hidden_layer_count):
            x = nn.Dense(self.hidden_layer_units, dtype=self.dtype)(x)
            x = nn.relu(x)
        return nn.Dense(self.n_classes, dtype=self.dtype)(x)


class JaxFeedForward(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "hidden_layer_count": IntegerKnob(1, 3),
            "hidden_layer_units": IntegerKnob(16, 128),
            "learning_rate": FloatKnob(1e-4, 1e-2, is_exp=True),
            "batch_size": CategoricalKnob([32, 64, 128]),
            "max_epochs": FixedKnob(5),
        }

    def create_module(self, n_classes: int, image_shape: Sequence[int]):
        return _FeedForward(
            hidden_layer_count=int(self.knobs["hidden_layer_count"]),
            hidden_layer_units=int(self.knobs["hidden_layer_units"]),
            n_classes=n_classes,
        )
