"""JaxFeedForward: parity model for the reference's ``TfFeedForward``.

Parity: SURVEY.md §2 "Example models" — a small dense network for
fashion-MNIST-scale image classification, the platform's "CPU-runnable PR1
reference" config (BASELINE.json configs[0]). Knob space mirrors the
reference's (hidden layer count/size, learning rate, batch size, epochs),
expressed with the SDK's typed knobs.

TPU-first redesign — one executable for the whole search space: upstream
rebuilds a TF graph per hyperparameter assignment; on XLA that is a
multi-second recompile per trial, which dominates AutoML trial time. Here
the architecture knobs are *traced masks* over a fixed-size supernet
(``extra_apply_inputs``): every trial computes MAX_LAYERS x MAX_UNITS
dense layers, a width mask zeroes units beyond ``hidden_layer_units``
(masked activations feed zeros forward, so the function — and its
gradients — equal the exact small MLP), and inactive layers pass their
input through. The learning rate is a traced optimizer hyperparameter
(``traced_knobs``). Net effect: trials recompile only per
batch-size bucket, not per knob assignment — the propose->train->evaluate
loop runs at executed-step speed.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from ..model import CategoricalKnob, FixedKnob, FloatKnob, IntegerKnob
from ..model.jax_model import JaxModel, dynamic_int8_matmul

MAX_LAYERS = 3
MAX_UNITS = 128


class _FeedForward(nn.Module):
    """Dense net; static shape from attrs, or masked supernet when the
    ``hidden_layer_count`` / ``hidden_layer_units`` mask inputs are given
    (then the attrs must be MAX_LAYERS / MAX_UNITS)."""
    hidden_layer_count: int
    hidden_layer_units: int
    n_classes: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False, hidden_layer_count=None,
                 hidden_layer_units=None):
        masked = hidden_layer_count is not None
        h = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for i in range(self.hidden_layer_count):
            y = nn.relu(nn.Dense(self.hidden_layer_units,
                                 dtype=self.dtype)(h))
            if not masked:
                h = y
                continue
            y = y * hidden_layer_units.astype(y.dtype)  # width mask
            # Layer 0 always runs (count >= 1); deeper layers blend to a
            # pass-through when masked off.
            h = y if i == 0 else jnp.where(
                hidden_layer_count[i].astype(y.dtype) > 0, y, h)
        return nn.Dense(self.n_classes, dtype=self.dtype)(h)


class JaxFeedForward(JaxModel):
    traced_knobs = frozenset({"learning_rate"})
    traced_knob_defaults = {"learning_rate": 1e-3}

    @staticmethod
    def get_knob_config():
        return {
            "hidden_layer_count": IntegerKnob(1, MAX_LAYERS),
            "hidden_layer_units": IntegerKnob(16, MAX_UNITS),
            "learning_rate": FloatKnob(1e-4, 1e-2, is_exp=True),
            "batch_size": CategoricalKnob([32, 64, 128]),
            "max_epochs": FixedKnob(5),
        }

    def create_module(self, n_classes: int, image_shape: Sequence[int]):
        # Fixed supernet shape: the knobs arrive as traced masks, so the
        # module (and its XLA graph) is identical across trials.
        return _FeedForward(
            hidden_layer_count=MAX_LAYERS,
            hidden_layer_units=MAX_UNITS,
            n_classes=n_classes,
        )

    def create_optimizer(self, steps_per_epoch: int, max_epochs: int):
        return self.traced_hyperparam_optimizer(steps_per_epoch,
                                                max_epochs)

    def extra_apply_inputs(self) -> Dict[str, np.ndarray]:
        count = int(self.knobs.get("hidden_layer_count", MAX_LAYERS))
        units = int(self.knobs.get("hidden_layer_units", MAX_UNITS))
        return {
            "hidden_layer_count":
                (np.arange(MAX_LAYERS) < count).astype(np.float32),
            "hidden_layer_units":
                (np.arange(MAX_UNITS) < units).astype(np.float32),
        }

    def stack_signature(self):
        # Congruence metadata for vmap-stacked serving: every trial
        # shares the fixed supernet, so same-family bins stack no
        # matter which width/depth masks their knobs trace in.
        return (*super().stack_signature(), MAX_LAYERS, MAX_UNITS)

    def quantized_apply(self, qvars, scales, fvars, x, extra):
        """Dequant-free int8 serving path: every Dense matmul runs
        int8 x int8 -> int32 on the MXU (``dynamic_int8_matmul``:
        weights statically quantized per output channel, activations
        dynamically per row — no calibration pass), mirroring
        ``_FeedForward.__call__``'s masked-supernet forward exactly. A
        kernel the quantizer left in f32 (none today, but the contract
        is per-layer) falls back to a plain matmul on that layer. The
        accuracy-delta gate in ``bench.py --quant int8`` is the
        regression net for this hand-mirrored forward."""
        import jax.numpy as jnp

        def dense(h, i):
            k = f"params/Dense_{i}/kernel"
            b = fvars[f"params/Dense_{i}/bias"].astype(jnp.float32)
            if k in qvars:
                return dynamic_int8_matmul(h, qvars[k], scales[k]) + b
            return h @ fvars[k].astype(jnp.float32) + b  # f32 fallback

        count_mask = extra["hidden_layer_count"]
        units_mask = extra["hidden_layer_units"]
        h = x.reshape((x.shape[0], -1))
        for i in range(MAX_LAYERS):
            y = jnp.maximum(dense(h, i), 0.0)  # relu
            y = y * units_mask.astype(y.dtype)
            h = y if i == 0 else jnp.where(
                count_mask[i].astype(y.dtype) > 0, y, h)
        return dense(h, MAX_LAYERS)
