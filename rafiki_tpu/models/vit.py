"""JaxViT: Vision Transformer image classifier on the framework's ops.

Beyond-parity zoo model (SURVEY.md §2 "Example models" lists only
dense/conv/ENAS image classifiers): patches → the same pre-LN encoder
blocks the sequence models use (``rafiki_tpu.ops`` flash attention on
TPU, blockwise fallback elsewhere) → CLS-token head. Connects the
attention-kernel layer to the flagship IMAGE_CLASSIFICATION task, and
inherits the whole ``JaxModel`` substrate: device-resident input
pipeline, scanned multi-step dispatch, traced lr/wd hyperparameters
(one executable per batch-size bucket), AOT bucketed predict, and
chip-utilization metering.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..model import CategoricalKnob, FixedKnob, FloatKnob, IntegerKnob
from ..model.jax_model import JaxModel
from ..ops import default_attention
from .transformer import _EncoderBlock

MAX_DEPTH = 6  # supernet depth; the depth knob masks trailing blocks


class _ViT(nn.Module):
    """Patchify-conv + CLS token + encoder blocks + linear head.

    ``depth`` (traced, a (MAX_DEPTH,) 0/1 mask — named for the knob
    that drives it, the compiled-step cache-key convention) blends each
    block's output with its input: a masked block is the identity, so
    the searched depth rides ONE executable like JaxCnn's width mask.
    """
    n_classes: int
    d_model: int
    n_heads: int
    patch: int
    n_tokens: int  # 1 + (H/patch)·(W/patch), fixed per dataset
    max_depth: int = MAX_DEPTH
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False, depth=None):
        attn = default_attention(causal=False)

        x = nn.Conv(self.d_model, (self.patch, self.patch),
                    strides=(self.patch, self.patch),
                    dtype=self.dtype)(x.astype(self.dtype))
        b = x.shape[0]
        x = x.reshape(b, -1, self.d_model)          # (B, hw, D)
        # Params stay f32 (like every flax kernel; ``dtype`` is the
        # COMPUTE dtype) — bf16 params would leak into the optimizer
        # state and break the scanned train step's carry types.
        cls = self.param("cls", nn.initializers.zeros,
                         (1, 1, self.d_model), jnp.float32)
        x = jnp.concatenate(
            [jnp.tile(cls.astype(self.dtype), (b, 1, 1)), x], axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, self.n_tokens, self.d_model), jnp.float32)
        x = x + pos.astype(self.dtype)
        for i in range(self.max_depth):
            y = _EncoderBlock(self.n_heads, dropout=0.0,
                              dtype=self.dtype)(
                x, attn, None, deterministic=not train)
            if depth is not None:
                gate = depth[i].astype(y.dtype)
                y = x + gate * (y - x)   # masked block == identity
            x = y
        x = nn.LayerNorm(dtype=jnp.float32)(x[:, 0])  # CLS token
        return nn.Dense(self.n_classes, dtype=jnp.float32)(x)


class JaxViT(JaxModel):
    """Vision Transformer; depth searched via a traced block mask."""

    traced_knobs = frozenset({"learning_rate", "weight_decay"})
    traced_knob_defaults = {"learning_rate": 1e-3, "weight_decay": 1e-4}

    @staticmethod
    def get_knob_config():
        return {
            "depth": IntegerKnob(2, MAX_DEPTH),  # traced mask -> one exe
            "d_model": FixedKnob(128),
            "n_heads": FixedKnob(4),
            "patch": FixedKnob(4),
            "learning_rate": FloatKnob(1e-4, 1e-2, is_exp=True),
            "batch_size": CategoricalKnob([64, 128, 256]),
            "weight_decay": FloatKnob(1e-5, 1e-3, is_exp=True),
            "max_epochs": IntegerKnob(3, 40),
            "early_stop_epochs": FixedKnob(5),
        }

    def create_module(self, n_classes: int, image_shape: Sequence[int]):
        patch = int(self.knobs.get("patch", 4))
        h, w = int(image_shape[0]), int(image_shape[1])
        if h % patch or w % patch:
            raise ValueError(f"image {h}x{w} not divisible by "
                             f"patch {patch}")
        return _ViT(n_classes=n_classes,
                    d_model=int(self.knobs.get("d_model", 128)),
                    n_heads=int(self.knobs.get("n_heads", 4)),
                    patch=patch,
                    n_tokens=1 + (h // patch) * (w // patch))

    def create_optimizer(self, steps_per_epoch: int, max_epochs: int):
        return self.traced_hyperparam_optimizer(
            steps_per_epoch, max_epochs, opt="adam", weight_decay=True)

    def extra_apply_inputs(self) -> Dict[str, Any]:
        import numpy as np

        # Keyed by the KNOB name: that's what excludes ``depth`` from
        # the compiled-step cache key (see step_cache_key).
        depth = int(self.knobs.get("depth", MAX_DEPTH))
        return {"depth":
                (np.arange(MAX_DEPTH) < depth).astype(np.float32)}

    def stack_signature(self):
        # Congruence metadata for vmap-stacked serving (module
        # dataclass equality already compares d_model/n_heads/patch/
        # n_tokens; the supernet depth is the family constant).
        return (*super().stack_signature(), MAX_DEPTH)

    def quantized_apply(self, qvars, scales, fvars, x, extra):
        """Dequant-free int8 serving for the transformer zoo (the r13
        carry): the patchify conv runs via ``dynamic_int8_conv``, each
        encoder block via the shared ``quantized_encoder_block``
        (models/transformer.py — int8 QKV/proj/FFN matmuls, f32
        LayerNorms), mirroring ``_ViT.__call__``'s depth-masked
        forward. A block the int8 path cannot take (MoE) or a kernel
        left f32 falls back per layer."""
        from ..model.jax_model import (dynamic_int8_conv,
                                       dynamic_int8_matmul)
        from .transformer import quantized_encoder_block

        module = self._module
        patch = module.patch
        k = "params/Conv_0/kernel"
        b = fvars["params/Conv_0/bias"].astype(jnp.float32)
        if k in qvars:
            h = dynamic_int8_conv(x, qvars[k], scales[k],
                                  strides=(patch, patch),
                                  padding="VALID") + b
        else:
            h = jax.lax.conv_general_dilated(
                x, fvars[k].astype(jnp.float32), (patch, patch),
                "VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC")) + b
        bsz = h.shape[0]
        h = h.reshape(bsz, -1, module.d_model)
        cls = fvars["params/cls"].astype(jnp.float32)
        h = jnp.concatenate([jnp.tile(cls, (bsz, 1, 1)), h], axis=1)
        h = h + fvars["params/pos_embed"].astype(jnp.float32)
        attn = default_attention(causal=False)
        depth = extra["depth"]
        for i in range(module.max_depth):
            y = quantized_encoder_block(
                qvars, scales, fvars, f"params/_EncoderBlock_{i}", h,
                attn, module.n_heads)
            if y is None:
                return None  # MoE block: generic fallback path
            gate = depth[i].astype(y.dtype)
            h = h + gate * (y - h)  # masked block == identity
        g = fvars["params/LayerNorm_0/scale"].astype(jnp.float32)
        bb = fvars["params/LayerNorm_0/bias"].astype(jnp.float32)
        hf = h[:, 0].astype(jnp.float32)
        m = hf.mean(-1, keepdims=True)
        v = ((hf - m) ** 2).mean(-1, keepdims=True)
        hf = (hf - m) * jax.lax.rsqrt(v + 1e-6) * g + bb
        k = "params/Dense_0/kernel"
        b = fvars["params/Dense_0/bias"].astype(jnp.float32)
        if k in qvars:
            return dynamic_int8_matmul(hf, qvars[k], scales[k]) + b
        return hf @ fvars[k].astype(jnp.float32) + b
