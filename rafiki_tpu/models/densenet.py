"""JaxDenseNet: parity model for the reference's ``PyDenseNet``.

Parity: SURVEY.md §2 "Example models" — DenseNet-121-style CIFAR-10
classifier (reference: PyTorch DenseNet-121, BASELINE.json configs[1]).
Torch in this image is CPU-only, so parity is a native flax DenseNet-BC
rather than torch-on-TPU (SURVEY.md §7 target stack note).

TPU-first design choices:
- bfloat16 convs/matmuls (MXU path), float32 BatchNorm statistics.
- NHWC layout throughout — XLA's native conv layout on TPU.
- Depth is expressed as (blocks, layers-per-block) Python constants at
  trace time, so the whole network is one static XLA graph; the dense
  connectivity is plain ``jnp.concatenate`` on the channel axis, which XLA
  fuses into the conv input windows.
- Host-side augmentation (pad-crop + horizontal flip) mirrors the
  reference recipe for CIFAR-scale training.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from ..model import CategoricalKnob, FixedKnob, FloatKnob, IntegerKnob, PolicyKnob
from ..model.jax_model import JaxModel, pad_crop_flip_graph


class _DenseLayer(nn.Module):
    """BN-ReLU-Conv1x1 (bottleneck) -> BN-ReLU-Conv3x3, emits growth_rate."""
    growth_rate: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        h = nn.BatchNorm(use_running_average=not train, momentum=0.9, dtype=jnp.float32)(x)
        h = nn.relu(h)
        h = nn.Conv(4 * self.growth_rate, (1, 1), use_bias=False,
                    dtype=self.dtype)(h)
        h = nn.BatchNorm(use_running_average=not train, momentum=0.9, dtype=jnp.float32)(h)
        h = nn.relu(h)
        h = nn.Conv(self.growth_rate, (3, 3), padding=1, use_bias=False,
                    dtype=self.dtype)(h)
        return jnp.concatenate([x, h.astype(x.dtype)], axis=-1)


class _Transition(nn.Module):
    """BN-ReLU-Conv1x1 (compression) + 2x2 average pool."""
    out_channels: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        h = nn.BatchNorm(use_running_average=not train, momentum=0.9, dtype=jnp.float32)(x)
        h = nn.relu(h)
        h = nn.Conv(self.out_channels, (1, 1), use_bias=False,
                    dtype=self.dtype)(h)
        return nn.avg_pool(h, (2, 2), strides=(2, 2))


class _DenseNet(nn.Module):
    """DenseNet-BC. block_config=(6,12,24,16) & growth=32 ≈ DenseNet-121."""
    block_config: Tuple[int, ...]
    growth_rate: int
    n_classes: int
    compression: float = 0.5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        ch = 2 * self.growth_rate
        # CIFAR-scale stem: single 3x3 conv, no maxpool (inputs are 32x32,
        # not 224x224 — the ImageNet stem would destroy resolution).
        x = nn.Conv(ch, (3, 3), padding=1, use_bias=False, dtype=self.dtype)(x)
        for i, n_layers in enumerate(self.block_config):
            for _ in range(n_layers):
                x = _DenseLayer(self.growth_rate, dtype=self.dtype)(x, train)
                ch += self.growth_rate
            if i != len(self.block_config) - 1:
                ch = int(ch * self.compression)
                x = _Transition(ch, dtype=self.dtype)(x, train)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9, dtype=jnp.float32)(x)
        x = nn.relu(x)
        x = x.mean(axis=(1, 2))  # global average pool
        return nn.Dense(self.n_classes, dtype=self.dtype)(x)


# Named depth presets: DenseNet-121 is the reference's architecture; the
# smaller presets keep trials cheap during search and tests fast.
_BLOCK_CONFIGS = {
    "densenet_tiny": (2, 2, 2),
    "densenet_small": (4, 4, 4),
    "densenet_121": (6, 12, 24, 16),
}


class JaxDenseNet(JaxModel):
    """DenseNet-BC image classifier (CIFAR-10 parity model)."""

    # lr and wd are continuous search knobs: traced as optimizer
    # hyperparameters so trials recompile only when the architecture
    # (arch / growth_rate) actually changes shape.
    traced_knobs = frozenset({"learning_rate", "weight_decay"})
    traced_knob_defaults = {"learning_rate": 0.1, "weight_decay": 1e-4}

    @staticmethod
    def get_knob_config():
        return {
            "arch": CategoricalKnob(
                ["densenet_tiny", "densenet_small", "densenet_121"]),
            "growth_rate": IntegerKnob(8, 32),
            "learning_rate": FloatKnob(1e-3, 3e-1, is_exp=True),
            "batch_size": CategoricalKnob([64, 128, 256]),
            "weight_decay": FloatKnob(1e-5, 1e-3, is_exp=True),
            "max_epochs": IntegerKnob(6, 60),
            "early_stop_epochs": FixedKnob(5),
            "quick_train": PolicyKnob("QUICK_TRAIN"),
        }

    def create_module(self, n_classes: int, image_shape: Sequence[int]):
        return _DenseNet(
            block_config=_BLOCK_CONFIGS[str(self.knobs.get(
                "arch", "densenet_121"))],
            growth_rate=int(self.knobs.get("growth_rate", 32)),
            n_classes=n_classes,
        )

    def create_optimizer(self, steps_per_epoch: int,
                         max_epochs: int) -> optax.GradientTransformation:
        # SGD + momentum + warmup-cosine: the reference DenseNet recipe,
        # with lr/wd as traced hyperparameters (see traced_knobs).
        return self.traced_hyperparam_optimizer(
            steps_per_epoch, max_epochs, opt="sgdm", warmup=True,
            weight_decay=True)

    def augment_in_graph(self, x, rng):
        return pad_crop_flip_graph(x, rng)
