"""JaxPosTagger: sequence tagging (POS) parity model.

Parity: SURVEY.md §2 — upstream supports the POS_TAGGING task with a
BiLSTM model over corpus datasets. TPU-first shape discipline: sentences
are padded/truncated to a fixed ``max_len`` so the whole train step is
ONE static XLA graph (no per-length retraces); loss and accuracy are
masked over real tokens. Tokens map to embedding rows via a hashing
vocabulary (crc32 mod vocab_size) — no host-side vocab fitting, identical
across processes, so dump/load needs no vocab artifact.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import traverse_util

from ..model import CategoricalKnob, FixedKnob, FloatKnob, IntegerKnob
from ..model.base import BaseModel, Params
from ..model.dataset import (PAD_ID, hash_token_ids,  # noqa: F401
                             load_corpus_dataset)
from ..model.jax_model import (_step_cache_get, _step_cache_put,
                               step_cache_key)
from ..model.logger import logger
from ..model.loop_ckpt import LoopCheckpointer, epoch_rng, schedule_epochs
from ..parallel import (batch_sharding, build_mesh, device_get_tree,
                        replicated)
from ..parallel.chips import ChipGroup


class _BiLstm(nn.Module):
    vocab_size: int
    embed_dim: int
    hidden: int
    n_tags: int

    @nn.compact
    def __call__(self, ids, lengths, train: bool = False):
        x = nn.Embed(self.vocab_size, self.embed_dim)(ids)
        fwd = nn.RNN(nn.OptimizedLSTMCell(self.hidden))(
            x, seq_lengths=lengths)
        bwd = nn.RNN(nn.OptimizedLSTMCell(self.hidden), reverse=True,
                     keep_order=True)(x, seq_lengths=lengths)
        h = jnp.concatenate([fwd, bwd], axis=-1)
        return nn.Dense(self.n_tags)(h)  # (batch, max_len, n_tags)


class JaxPosTagger(BaseModel):
    """BiLSTM token tagger over corpus datasets (fixed-length graphs)."""

    @staticmethod
    def get_knob_config():
        return {
            "embed_dim": IntegerKnob(16, 128),
            "hidden": IntegerKnob(16, 128),
            "learning_rate": FloatKnob(1e-3, 3e-2, is_exp=True),
            "batch_size": CategoricalKnob([16, 32, 64]),
            "max_epochs": IntegerKnob(3, 20),
            "max_len": FixedKnob(64),
            "vocab_size": FixedKnob(16384),
            # Deployment knob: pins init + per-epoch data order (and
            # therefore checkpoint-resume step identity) for
            # reproducibility tests and re-runs.
            "seed": FixedKnob(0),
        }

    def __init__(self, **knobs: Any):
        super().__init__(**knobs)
        self._variables = None
        self._module: Optional[_BiLstm] = None
        self._meta: Dict[str, Any] = {}
        self._mesh = None
        self._predict_fn = None
        self._vars_dev = None

    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = build_mesh(ChipGroup.current().devices())
        return self._mesh

    def _ensure_module(self, n_tags: int) -> None:
        if self._module is None:
            self._module = _BiLstm(
                vocab_size=int(self.knobs.get("vocab_size", 16384)),
                embed_dim=int(self.knobs.get("embed_dim", 64)),
                hidden=int(self.knobs.get("hidden", 64)),
                n_tags=n_tags)

    def _encode(self, sentences: List[List[str]]):
        max_len = int(self.knobs.get("max_len", 64))
        vocab = int(self.knobs.get("vocab_size", 16384))
        ids = np.stack([hash_token_ids(s, vocab, max_len)
                        for s in sentences])
        lengths = np.asarray([min(len(s), max_len) for s in sentences],
                             np.int32)
        return ids, lengths

    # --- BaseModel ---

    def train(self, dataset_path: str, *,
              shared_params: Optional[Params] = None, **kwargs: Any) -> None:
        ds = load_corpus_dataset(dataset_path)
        n_tags = len(ds.tag_names)
        self._ensure_module(n_tags)
        self._meta = {"tag_names": list(ds.tag_names)}
        mesh = self.mesh
        dp = mesh.shape["dp"]
        max_len = int(self.knobs.get("max_len", 64))

        ids, lengths = self._encode(ds.sentences)
        tags = np.zeros((ds.size, max_len), np.int32)
        for i, t in enumerate(ds.tags):
            tags[i, :min(len(t), max_len)] = t[:max_len]

        batch_size = min(int(self.knobs.get("batch_size", 32)), ds.size)
        batch_size = max(dp, (batch_size // dp) * dp)
        max_epochs = int(self.knobs.get("max_epochs", 10))
        if self.knobs.get("quick_train", False):
            max_epochs = min(max_epochs,
                             int(self.knobs.get("trial_epochs", 1)))
        steps = max(1, ds.size // batch_size)

        rng = jax.random.key(int(self.knobs.get("seed", 0)))
        # Jitted init: one device dispatch instead of per-op round trips
        # (see JaxModel.train).
        variables = jax.jit(self._module.init)(
            rng, jnp.zeros((1, max_len), jnp.int32),
            jnp.ones((1,), jnp.int32))
        if shared_params is not None:
            flat = traverse_util.flatten_dict(variables, sep="/")
            for k, v in shared_params.items():
                if k in flat and tuple(flat[k].shape) == tuple(v.shape):
                    flat[k] = jnp.asarray(v)
            variables = traverse_util.unflatten_dict(flat, sep="/")
        params = jax.device_put(variables["params"], replicated(mesh))

        # Reuse the jitted step AND its optax tx across repeat trials with
        # identical static config (same process-level cache JaxModel uses;
        # a fresh tx per trial would defeat jit's cache).
        sched_epochs = schedule_epochs(kwargs, max_epochs)
        cache_key = step_cache_key(self, "train", mesh, steps, sched_epochs)
        cached = _step_cache_get(cache_key)
        if cached is not None:
            tx, train_step = cached["tx"], cached["step"]
        else:
            lr = float(self.knobs.get("learning_rate", 1e-2))
            tx = optax.adam(optax.cosine_decay_schedule(
                lr, decay_steps=max(1, steps * sched_epochs), alpha=0.01))
            module = self._module

            @jax.jit
            def train_step(params, opt_state, ids, lengths, tags):
                def loss_fn(p):
                    logits = module.apply({"params": p}, ids, lengths)
                    mask = (jnp.arange(logits.shape[1])[None, :]
                            < lengths[:, None]).astype(jnp.float32)
                    losses = optax.softmax_cross_entropy_with_integer_labels(
                        logits, tags)
                    loss = (losses * mask).sum() / jnp.maximum(mask.sum(), 1)
                    correct = ((logits.argmax(-1) == tags) * mask).sum() \
                        / jnp.maximum(mask.sum(), 1)
                    return loss, correct
                (loss, acc), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                updates, opt_state = tx.update(grads, opt_state, params)
                return (optax.apply_updates(params, updates), opt_state,
                        loss, acc)

            _step_cache_put(cache_key, {"tx": tx, "step": train_step})

        opt_state = tx.init(params)
        logger.define_plot("Training", ["loss", "token_acc"], x_axis="epoch")
        x_shard = batch_sharding(mesh)
        ckpt = LoopCheckpointer(kwargs)
        (params, opt_state), start_epoch = ckpt.restore((params, opt_state))
        seed = int(self.knobs.get("seed", 0))
        last_epoch = None
        for epoch in range(start_epoch, max_epochs):
            order = epoch_rng(seed, epoch).permutation(ds.size)
            ep_loss = ep_acc = 0.0
            for s in range(steps):
                sel = order[s * batch_size:(s + 1) * batch_size]
                if len(sel) < batch_size:
                    sel = np.resize(order, batch_size)
                params, opt_state, loss, acc = train_step(
                    params, opt_state,
                    jax.device_put(ids[sel], x_shard),
                    jax.device_put(lengths[sel], x_shard),
                    jax.device_put(tags[sel], x_shard))
                ep_loss += float(loss)
                ep_acc += float(acc)
            logger.log(epoch=epoch, loss=ep_loss / steps,
                       token_acc=ep_acc / steps)
            last_epoch = epoch
            ckpt.after_epoch(epoch, (params, opt_state), max_epochs)
        ckpt.after_loop(last_epoch, (params, opt_state))

        self._variables = {"params": device_get_tree(params)}
        self._invalidate_compiled()

    def evaluate(self, dataset_path: str) -> float:
        assert self._variables is not None
        ds = load_corpus_dataset(dataset_path)
        max_len = int(self.knobs.get("max_len", 64))
        probs = self._predict_probs(ds.sentences)
        n_correct = n_total = 0
        for i, gold in enumerate(ds.tags):
            length = min(len(gold), max_len)
            pred = probs[i, :length].argmax(-1)
            n_correct += int((pred == np.asarray(gold[:length])).sum())
            n_total += length
        return n_correct / max(n_total, 1)

    def predict(self, queries: List[Any]) -> List[Any]:
        """Queries are token lists; returns, per query, a list of per-token
        tag-probability distributions — the classification contract the
        Predictor's ensemble averaging expects (elementwise mean across
        workers stays a valid distribution; raw tag ids would not)."""
        assert self._variables is not None
        if not queries:
            return []
        sentences = [list(q) for q in queries]
        probs = self._predict_probs(sentences)
        max_len = int(self.knobs.get("max_len", 64))
        return [probs[i, :min(len(s), max_len)].tolist()
                for i, s in enumerate(sentences)]

    def _predict_probs(self, sentences: List[List[str]]) -> np.ndarray:
        """(n, max_len, n_tags) probabilities; batch bucketed to powers of
        two so variable serving load hits a handful of compiled shapes, and
        parameters are device-put once per loaded checkpoint."""
        self._ensure_module(len(self._meta["tag_names"]))
        if self._vars_dev is None:
            self._vars_dev = jax.device_put(
                self._variables, replicated(self.mesh))
        if self._predict_fn is None:
            module = self._module
            self._predict_fn = jax.jit(
                lambda v, ids, lengths: jax.nn.softmax(
                    module.apply(v, ids, lengths).astype(jnp.float32), -1))
        ids, lengths = self._encode(sentences)
        n = len(sentences)
        bucket = 1
        while bucket < n:
            bucket *= 2
        if n < bucket:
            pad = bucket - n
            ids = np.concatenate([ids, np.zeros((pad, ids.shape[1]),
                                                ids.dtype)])
            lengths = np.concatenate([lengths, np.ones((pad,),
                                                       lengths.dtype)])
        out = np.asarray(self._predict_fn(self._vars_dev, ids, lengths))
        return out[:n]

    def dump_parameters(self) -> Params:
        assert self._variables is not None
        flat = traverse_util.flatten_dict(self._variables, sep="/")
        out: Params = {k: np.asarray(v) for k, v in flat.items()}
        out["_meta/tag_names_json"] = np.frombuffer(
            json.dumps(self._meta["tag_names"]).encode(), np.uint8)
        return out

    def load_parameters(self, params: Params) -> None:
        blob = params.get("_meta/tag_names_json")
        assert blob is not None, "params missing _meta/tag_names_json"
        self._meta = {"tag_names": json.loads(
            np.asarray(blob).tobytes().decode())}
        flat = {k: np.asarray(v) for k, v in params.items()
                if not k.startswith("_meta/")}
        self._variables = traverse_util.unflatten_dict(flat, sep="/")
        self._module = None
        self._invalidate_compiled()
        self._ensure_module(len(self._meta["tag_names"]))

    def _invalidate_compiled(self) -> None:
        self._predict_fn = None
        self._vars_dev = None

    def destroy(self) -> None:
        self._invalidate_compiled()
        self._variables = None
        self._module = None
