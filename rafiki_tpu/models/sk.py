"""SkDt / SkSvm: parity models for the reference's sklearn zoo entries.

Parity: SURVEY.md §2 "Example models" — upstream bundles a decision tree
(``SkDt``) and an SVM (``SkSvm``) for image classification over flattened
pixels. They fill two platform roles: cheap CPU trials while JAX models
hold the chips, and classifier diversity for the Predictor's ensemble.
"""

from __future__ import annotations

from ..model import CategoricalKnob, FixedKnob, FloatKnob, IntegerKnob
from ..model.sklearn_model import SklearnModel


class SkDt(SklearnModel):
    """Decision-tree classifier on flattened pixels."""

    @staticmethod
    def get_knob_config():
        return {
            "max_depth": IntegerKnob(2, 16),
            "criterion": CategoricalKnob(["gini", "entropy"]),
            "min_samples_leaf": IntegerKnob(1, 8),
        }

    def create_estimator(self):
        from sklearn.tree import DecisionTreeClassifier
        return DecisionTreeClassifier(
            max_depth=int(self.knobs["max_depth"]),
            criterion=str(self.knobs["criterion"]),
            min_samples_leaf=int(self.knobs["min_samples_leaf"]),
            random_state=0,
        )


class SkSvm(SklearnModel):
    """Linear-kernel SVM with probability calibration."""

    @staticmethod
    def get_knob_config():
        return {
            "C": FloatKnob(1e-2, 1e2, is_exp=True),
            "kernel": CategoricalKnob(["linear", "rbf"]),
            "max_iter": FixedKnob(1000),
        }

    def create_estimator(self):
        from sklearn.calibration import CalibratedClassifierCV
        from sklearn.svm import SVC
        svc = SVC(
            C=float(self.knobs["C"]),
            kernel=str(self.knobs["kernel"]),
            max_iter=int(self.knobs["max_iter"]),
            random_state=0,
        )
        # sklearn 1.9 emits a FutureWarning that SVC(probability=True)
        # will be removed in 1.11 and points here instead.
        return CalibratedClassifierCV(svc, cv=3, ensemble=False)
