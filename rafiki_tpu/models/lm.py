"""JaxTransformerLM: flagship causal-LM — the compute-density proof.

Beyond-parity zoo model (upstream Rafiki has no language-modeling task
— SURVEY.md §2 "Example models" lists image/POS/tabular only). It
exists for a platform reason as much as a product one: the BASELINE
north star demands ≥90% chip utilization during training, and every
parity model (28×28/32×32 images, 2.4k-token corpora) is far too small
to put meaningful load on a 197-TFLOP/s MXU. This model is the zoo's
compute-dense citizen — the shape the ``roofline`` bench config drives
to high sustained MFU on one chip (r4 verdict item 1).

TPU-first design choices, all measured on a v5e-1 (2026-07-31):

- **Pallas flash attention, both passes** (``rafiki_tpu.ops``): the
  blockwise-XLA backward ran at ~5 TFLOP/s and dominated the step; the
  kernel backward moved the d_model=2048 step from 0.335 to 0.538
  spec-peak MFU.
- **Layers as a ``lax.scan`` over stacked params**: one compiled block
  regardless of depth — compile time stays ~10 s where an unrolled
  12-layer graph takes minutes.
- **Selective remat** (``remat`` knob): ``"dots"`` saves matmul
  outputs and recomputes elementwise ops in the backward —
  measurably better than full remat (0.538 vs 0.517 MFU) and 8×
  lighter than no remat (which OOMs 16 GB HBM at flagship shape).
- **K optimizer steps per dispatch** (``lax.scan`` in the train chunk,
  donated carry): amortizes per-dispatch host latency exactly like
  ``JaxModel``'s chunk dispatch (model/jax_model.py).
- **bf16 compute, f32 master params + Adam state**; logits and
  cross-entropy in f32.
- **Analytic MFU metering**: XLA's post-compile cost analysis cannot
  see through Pallas custom calls (it reported 0.63 of the real
  ~15 TFLOP/step at flagship shape), so ``chip_util`` uses the
  standard analytic count — ``6·N·tokens`` for the dense path plus
  the causal attention term — fed to the shared ``MfuMeter``.

Dataset: the packed token stream (``load_token_dataset``); queries are
token-id lists scored by mean next-token log-probability (a working
LM-scoring service through the ordinary Predictor path).
"""

from __future__ import annotations

import functools
import math
import os
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..model import (CategoricalKnob, FixedKnob, FloatKnob, IntegerKnob,
                     PolicyKnob)
from ..model.base import BaseModel, Params
from ..model.dataset import load_token_dataset
from ..model.jax_model import (_stage_cache_budget, _step_cache_get,
                               _step_cache_put, staged_token_ids,
                               step_cache_key)
from ..model.logger import logger
from ..model.loop_ckpt import epoch_rng
from ..observe import MfuMeter
from ..ops import flash_attention
from ..parallel import DP_AXIS, batch_sharding, build_mesh, replicated
from ..parallel.chips import ChipGroup
from .transformer import _sinusoidal


@functools.lru_cache(maxsize=8)
def _jitted_param_init(v, d, L):
    """One jitted device-side initializer per shape (lru-cached: a
    fresh jit per model instance would re-trace ~2 s every bench
    window / AutoML trial)."""
    shapes = {
        "embed": ((v, d), 0.02),
        "qkv": ((L, d, 3 * d), None),
        "proj": ((L, d, d), None),
        "w1": ((L, d, 4 * d), None),
        "w2": ((L, 4 * d, d), None),
    }

    @jax.jit
    def init(key):
        out = {}
        for i, (name, (shape, scale)) in enumerate(shapes.items()):
            if scale is None:
                scale = 1.0 / math.sqrt(shape[-2])
            out[name] = scale * jax.random.normal(
                jax.random.fold_in(key, i), shape, jnp.float32)
        return out

    return init


def _layer_norm(x, g):
    xf = x.astype(jnp.float32)
    m = xf.mean(-1, keepdims=True)
    v = ((xf - m) ** 2).mean(-1, keepdims=True)
    return (xf - m) * jax.lax.rsqrt(v + 1e-6) * g


class JaxTransformerLM(BaseModel):
    """Decoder-only causal transformer LM on the flash kernels."""

    @staticmethod
    def get_knob_config():
        return {
            # Flagship default shape: the smallest d_model whose
            # matmuls reach the chip's efficient regime (the measured
            # matmul roofline rises steeply with size on v5e).
            "d_model": CategoricalKnob([256, 512, 1024, 2048]),
            "n_layers": IntegerKnob(2, 16),
            "seq_len": CategoricalKnob([256, 512, 1024, 2048, 4096]),
            "batch_size": CategoricalKnob([2, 4, 8, 16]),
            "learning_rate": FloatKnob(1e-4, 1e-2, is_exp=True),
            # Optimizer steps, not epochs: an LM pass is windows over a
            # stream, so the budget is steps.
            "train_steps": IntegerKnob(20, 20000),
            "vocab_size": CategoricalKnob([512, 4096, 16384, 32768]),
            # Backward-pass memory policy: "dots" (save matmul outputs,
            # recompute elementwise — the measured best), "full"
            # (checkpoint whole blocks — smallest memory), "none"
            # (save everything — fastest when it fits).
            "remat": FixedKnob("dots"),
            # Optimizer steps fused into one device dispatch.
            "steps_per_dispatch": FixedKnob(8),
            # AutoML trial policy: the platform grants QUICK_TRAIN to
            # search trials, capping the budget at trial_steps.
            "quick_train": PolicyKnob("QUICK_TRAIN"),
            "trial_steps": FixedKnob(30),
            "seed": FixedKnob(0),
        }

    def __init__(self, **knobs: Any):
        super().__init__(**knobs)
        self._params = None  # f32 pytree (device-resident after train)
        self._predict_fn = None
        self._params_dev = None
        self._mesh = None
        self._module = None          # step_cache_key convention slot

    # --- shape plumbing ---

    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = build_mesh(ChipGroup.current().devices())
        return self._mesh

    def _dims(self):
        d = int(self.knobs.get("d_model", 1024))
        return dict(
            d=d,
            h=max(1, d // 128),
            layers=int(self.knobs.get("n_layers", 8)),
            t=int(self.knobs.get("seq_len", 1024)),
            v=int(self.knobs.get("vocab_size", 32768)),
        )

    def _init_params(self) -> Dict[str, Any]:
        """Initialize ON DEVICE (jit + jax.random): host-RNG init of a
        flagship model is ~470M float64 draws (~20 s of host time) plus
        a ~1.9 GB host→device upload that a tunneled chip pays at
        first-use (~3 min measured) — device-side init costs
        milliseconds and transfers nothing."""
        s = self._dims()
        L, d = s["layers"], s["d"]
        init = _jitted_param_init(s["v"], d, L)
        mats = init(jax.random.key(int(self.knobs.get("seed", 0))))
        return {
            "embed": mats["embed"],
            "layers": {
                "qkv": mats["qkv"],
                "proj": mats["proj"],
                "w1": mats["w1"],
                "w2": mats["w2"],
                "ln1": jnp.ones((L, d), jnp.float32),
                "ln2": jnp.ones((L, d), jnp.float32),
            },
            "lnf": jnp.ones((d,), jnp.float32),
        }

    def _block(self, x, lp, h_heads):
        d = x.shape[-1]
        h = _layer_norm(x, lp["ln1"]).astype(jnp.bfloat16)
        qkv = h @ lp["qkv"].astype(jnp.bfloat16)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(a):
            b, t, _ = a.shape
            return a.reshape(b, t, h_heads,
                             d // h_heads).transpose(0, 2, 1, 3)

        o = flash_attention(heads(q), heads(k), heads(v), causal=True)
        b, nh, t, dh = o.shape
        o = o.transpose(0, 2, 1, 3).reshape(b, t, nh * dh)
        x = x + (o @ lp["proj"].astype(jnp.bfloat16)).astype(x.dtype)
        h = _layer_norm(x, lp["ln2"]).astype(jnp.bfloat16)
        h = jax.nn.gelu(h @ lp["w1"].astype(jnp.bfloat16))
        return x + (h @ lp["w2"].astype(jnp.bfloat16)).astype(x.dtype)

    def _forward(self, params, ids):
        s = self._dims()
        # ×√d (Vaswani et al. §3.4): 0.02-scale embedding rows against
        # unit-scale sinusoidal PE would leave the token signal at ~2%
        # of the stream — below useful bf16 resolution after the first
        # residual add.
        x = params["embed"].astype(jnp.bfloat16)[ids] \
            * jnp.bfloat16(math.sqrt(s["d"]))
        pos = _sinusoidal(s["t"], s["d"])
        x = x + jnp.asarray(pos)[None, :ids.shape[1]].astype(x.dtype)

        body = functools.partial(self._block, h_heads=s["h"])
        remat = str(self.knobs.get("remat", "dots"))
        if remat == "full":
            body = jax.checkpoint(body)
        elif remat == "dots":
            body = jax.checkpoint(
                body, policy=jax.checkpoint_policies
                .dots_with_no_batch_dims_saveable)

        def scan_body(x, lp):
            return body(x, lp), None

        x, _ = jax.lax.scan(scan_body, x, params["layers"])
        x = _layer_norm(x, params["lnf"]).astype(jnp.bfloat16)
        # Tied unembedding: logits in f32 for a stable softmax.
        return (x @ params["embed"].astype(jnp.bfloat16).T
                ).astype(jnp.float32)

    def _flops_per_step(self, b: int) -> float:
        """Analytic train-step FLOPs (fwd+bwd): 6·N·tokens for matmul
        params (the standard estimate; embedding GATHER excluded, tied
        unembed matmul included) plus the causal attention term. Used
        instead of XLA cost analysis, which cannot count inside the
        Pallas custom calls. ``b`` is the ACTUAL (dp-rounded) batch the
        step runs, not the raw knob."""
        s = self._dims()
        tokens = b * s["t"]
        n_mat = 12 * s["layers"] * s["d"] ** 2 + s["v"] * s["d"]
        attn = (2 * 2 * 3 * b * s["h"] * s["t"] ** 2
                * (s["d"] // s["h"]) * s["layers"] / 2)
        return 6 * n_mat * tokens + attn

    # --- BaseModel ---

    def train(self, dataset_path: str, **kwargs: Any) -> None:
        ds = load_token_dataset(dataset_path)
        s = self._dims()
        assert ds.vocab_size <= s["v"], (
            f"dataset vocab {ds.vocab_size} exceeds model vocab {s['v']}")
        t_need = int(self.knobs.get("seq_len", 1024)) + 2
        if ds.size < t_need:
            raise ValueError(
                f"token dataset has {ds.size} ids but seq_len="
                f"{t_need - 2} needs at least {t_need} (one full "
                f"input+target window)")
        mesh = self.mesh
        dp = mesh.shape[DP_AXIS]
        b = max(dp, (int(self.knobs.get("batch_size", 8)) // dp) * dp)
        t = s["t"]
        steps = int(self.knobs.get("train_steps", 100))
        if self.knobs.get("quick_train", False):
            steps = min(steps, int(self.knobs.get("trial_steps", 30)))
        k_disp = max(1, int(self.knobs.get("steps_per_dispatch", 8)))

        params = jax.device_put(self._params or self._init_params(),
                                replicated(mesh))
        # Compiled-step cache, shared convention with the whole zoo
        # (model/jax_model.py): repeated trials of one config — the
        # bench's adaptive windows, an AutoML search over lr — reuse
        # ONE executable instead of re-paying the ~10 s flagship
        # compile per train() call.
        cache_key = step_cache_key(self, "train", mesh, steps, b, k_disp)
        cached = _step_cache_get(cache_key)
        lr = float(self.knobs.get("learning_rate", 3e-4))
        total = max(1, steps)
        if cached is not None:
            tx, train_chunk = cached["tx"], cached["step"]
            init_opt = cached["init_opt"]
        else:
            tx = optax.adamw(optax.warmup_cosine_decay_schedule(
                init_value=lr * 0.1, peak_value=lr,
                warmup_steps=max(1, total // 10), decay_steps=total,
                end_value=lr * 0.1))
            # Jitted optimizer-state init, cached with the step: eager
            # tx.init on 470M params re-traces ~3.5 s per trial.
            init_opt = jax.jit(tx.init)
        opt_state = jax.device_put(init_opt(params), replicated(mesh))

        # Windows are cut on the HOST and shipped per dispatch:
        # (K, B, t+1) int32 is ~¼ MB at flagship shape — negligible
        # next to the step's compute — whereas gathering the windows
        # in-graph from a device-resident stream lowers to a scalar
        # gather that runs ~35× slower than the whole train step on
        # TPU (measured: 8.1 s/step vs 0.23). The image zoo's
        # device-resident staging exists to avoid shipping megabytes of
        # pixels; a token stream has no such problem.
        x_shard = batch_sharding(mesh)
        forward = self._forward

        if cached is None:
            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def train_chunk(params, opt_state, wins):
                def one(carry, win):
                    params, opt_state = carry
                    # win (B, t+1): input/target are shifted views.
                    win = jax.lax.with_sharding_constraint(win, x_shard)

                    def loss_fn(p):
                        logits = forward(p, win[:, :-1])
                        loss = \
                            optax.softmax_cross_entropy_with_integer_labels(
                                logits, win[:, 1:]).mean()
                        acc = (logits.argmax(-1) == win[:, 1:]).mean()
                        return loss, acc

                    (loss, acc), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(params)
                    updates, opt_state = tx.update(grads, opt_state,
                                                   params)
                    return (optax.apply_updates(params, updates),
                            opt_state), (loss, acc)

                (params, opt_state), (losses, accs) = jax.lax.scan(
                    one, (params, opt_state), wins)
                return params, opt_state, jnp.stack(
                    [losses.mean(), accs.mean()])

            _step_cache_put(cache_key, {"tx": tx, "step": train_chunk,
                                        "init_opt": init_opt})

        logger.define_plot("Training", ["loss", "token_acc", "chip_util"],
                           x_axis="step")
        meter = MfuMeter(self._flops_per_step(b), n_devices=mesh.size)
        rng = epoch_rng(int(self.knobs.get("seed", 0)), 0)
        hi = max(1, ds.size - (t + 1))
        done = 0
        first_dispatch = True
        while done < steps:
            k = min(k_disp, steps - done)
            starts = rng.integers(0, hi, size=k * b)
            wins = np.stack([ds.ids[s:s + t + 1] for s in starts])
            params, opt_state, metrics = train_chunk(
                params, opt_state,
                jax.device_put(wins.reshape(k, b, t + 1),
                               replicated(mesh)))
            done += k
            loss_acc = np.asarray(metrics)  # one D2H per chunk; this
            # sync must land BEFORE any meter.reset(): the dispatch
            # returns while the chunk is still executing, and a reset
            # taken then would start the fresh window mid-chunk with
            # zero steps credited (~4% systematic under-report).
            meter.tick(k)
            if first_dispatch or k != k_disp:
                # Dispatches that paid an XLA compile (first chunk, tail
                # chunk) are excluded from the sustained-MFU window.
                first_dispatch = False
                meter.reset()
            util = ({"chip_util": round(meter.mfu, 6)}
                    if meter.mfu is not None else {})
            if meter.mfu is not None:
                from ..observe import metrics as _obs_metrics

                # rta: disable=RTA301 bound trial= labels; TrialRunner removes them at trial end (worker/runner.py)
                _obs_metrics.registry().gauge(
                    "rafiki_tpu_train_mfu_ratio",
                    "Model-FLOPs-utilization of the trial's chip group "
                    "(published per epoch)").set(
                        meter.mfu, **_obs_metrics.bound_labels())
            logger.log(step=done, loss=float(loss_acc[0]),
                       token_acc=float(loss_acc[1]), **util)
        # Params stay DEVICE-RESIDENT: pulling 1.9 GB back to the host
        # here would cost ~2 min on a tunneled chip per trial;
        # dump_parameters materializes bytes only when something (param
        # store, checkpoint) actually needs them.
        self._params = params
        self._invalidate_compiled()

    def evaluate(self, dataset_path: str) -> float:
        """Mean next-token accuracy over contiguous validation
        windows.

        The token stream rides the cross-trial device staging cache
        (``staged_token_ids``): eval windows are gathered in-graph from
        the resident int32 stream by DEVICE-COMPUTED iota indices, so
        eval 2..N of a sub-train-job ships zero token bytes host->
        device (the r9 zero-H2D contract, extended to the LM path —
        shipping an index matrix from the host would be pointless here:
        int32 indices are exactly as many bytes as the int32 windows
        themselves). Streams over the staging budget keep the legacy
        host ``np.stack`` path."""
        ds = load_token_dataset(dataset_path)
        t = self._dims()["t"]
        n_win = max(1, min(16, (ds.size - 1) // t))
        fn = self._ensure_predict_fn()
        stage_bytes = int(os.environ.get("RAFIKI_TPU_STAGE_BYTES",
                                         2 << 30))
        # Gated on the stream being CACHEABLE, not just stageable: with
        # the cross-trial cache disabled (or the stream over its
        # budget), staging would device_put the WHOLE stream uncached
        # on every eval — strictly worse than shipping 16 windows.
        cache_budget = _stage_cache_budget()
        if 0 < int(ds.ids.nbytes) <= min(stage_bytes, cache_budget) \
                and ds.size >= n_win * t + 1:
            ids_dev = staged_token_ids(dataset_path, ds, self.mesh)
            sel = (jnp.arange(n_win, dtype=jnp.int32)[:, None] * t
                   + jnp.arange(t + 1, dtype=jnp.int32)[None, :])
            wins = jnp.take(ids_dev, sel, axis=0)  # (n_win, t+1) on device
            logits = np.asarray(fn(self._params_dev, wins[:, :-1]))
            targets = np.asarray(wins[:, 1:])
        else:
            ids = np.stack([ds.ids[i * t:i * t + t + 1]
                            for i in range(n_win)])
            logits = np.asarray(fn(self._params_dev,
                                   jnp.asarray(ids[:, :-1], jnp.int32)))
            targets = ids[:, 1:]
        return float((logits.argmax(-1) == targets).mean())

    def predict(self, queries: List[Any]) -> List[Any]:
        """Scores token-id sequences: mean next-token log-probability
        per query (the LM-scoring service contract)."""
        if not queries:
            return []
        t = self._dims()["t"]
        fn = self._ensure_predict_fn()
        out = []
        for q in queries:
            ids = np.asarray(list(q), np.int32)[:t + 1]
            if ids.size < 2:
                out.append(0.0)
                continue
            pad = np.zeros((t + 1,), np.int32)
            pad[:ids.size] = ids
            logits = np.asarray(fn(
                self._params_dev,
                jnp.asarray(pad[None, :-1], jnp.int32)))[0]
            lp = jax.nn.log_softmax(jnp.asarray(logits), -1)
            n = ids.size - 1
            out.append(float(jnp.take_along_axis(
                lp[:n], jnp.asarray(ids[1:, None]), axis=-1).mean()))
        return out

    def make_generator(self, **cfg: Any):
        """Token-level generation engine over this model's trained
        params: paged KV cache, AOT prefill/decode split, per-step
        admission. See :mod:`rafiki_tpu.models.lm_generate` — the
        serving plane (worker decode scheduler) is the intended
        caller; ``cfg`` passes through to :class:`LMGenerator`
        (``page_size``, ``n_pages``, ``decode_batch``, ...)."""
        from .lm_generate import LMGenerator
        assert self._params is not None, \
            "train() or load_parameters() first"
        return LMGenerator(self, **cfg)

    def _ensure_predict_fn(self):
        assert self._params is not None, "train() or load_parameters() first"
        if self._params_dev is None:
            self._params_dev = jax.device_put(self._params,
                                              replicated(self.mesh))
        if self._predict_fn is None:
            self._predict_fn = jax.jit(self._forward)
        return self._predict_fn

    def dump_parameters(self) -> Params:
        assert self._params is not None
        out: Params = {}
        out["embed"] = np.asarray(self._params["embed"])
        out["lnf"] = np.asarray(self._params["lnf"])
        for kk, vv in self._params["layers"].items():
            out[f"layers/{kk}"] = np.asarray(vv)
        return out

    def load_parameters(self, params: Params) -> None:
        layers = {kk.split("/", 1)[1]: jnp.asarray(vv)
                  for kk, vv in params.items()
                  if kk.startswith("layers/")}
        self._params = {"embed": jnp.asarray(params["embed"]),
                        "lnf": jnp.asarray(params["lnf"]),
                        "layers": layers}
        self._invalidate_compiled()

    def _invalidate_compiled(self) -> None:
        self._predict_fn = None
        self._params_dev = None

    def destroy(self) -> None:
        self._invalidate_compiled()
        self._params = None

