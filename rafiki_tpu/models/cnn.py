"""JaxCnn: VGG-style convolutional image classifier.

Parity: SURVEY.md §2 "Example models" — upstream's example zoo includes
plain deep CNNs (e.g. a VGG-16 template) between the tiny dense net and
the DenseNet flagship. This is that middle ground, TPU-first: NHWC
bfloat16 convs (MXU path), norm-free like the original VGG (the module
stays purely functional), and the same one-executable search design as
JaxFeedForward: the width knob is a traced channel mask over a
fixed-width supernet (masked channels feed zeros forward, so function
and gradients equal the exact narrower net) and lr/wd ride the optimizer
state (``traced_knobs``) — trials recompile only per batch-size bucket.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from ..model import CategoricalKnob, FixedKnob, FloatKnob, IntegerKnob
from ..model.jax_model import (JaxModel, dynamic_int8_conv,
                               dynamic_int8_matmul)

MAX_WIDTH = 64   # stage-0 channels; stage i uses MAX_WIDTH * 2**i
N_STAGES = 3


class _Cnn(nn.Module):
    """(conv-relu) x2 + 2x2 pool per stage, then flatten + FC head — the
    classic norm-free VGG recipe (normalisation layers stall this depth
    badly on small data).

    ``width_16ths`` (traced, a (16,) 0/1 mask) zeroes the trailing
    fraction of every stage's channels. Masked activations feed zeros
    forward and receive zero gradients, so the function and its
    gradients equal the exact narrower net while every trial shares ONE
    executable.
    """
    n_classes: int
    base_width: int = MAX_WIDTH
    n_stages: int = N_STAGES
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False, width_16ths=None):
        x = x.astype(self.dtype)
        for stage in range(self.n_stages):
            ch = self.base_width * (2 ** stage)  # multiple of 16
            mask = None if width_16ths is None else \
                jnp.repeat(width_16ths, ch // 16).astype(self.dtype)
            for _ in range(2):
                x = nn.Conv(ch, (3, 3), padding=1, dtype=self.dtype)(x)
                x = nn.relu(x)
                if mask is not None:
                    x = x * mask
            if min(x.shape[1], x.shape[2]) >= 2:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
        # Classic VGG head: flatten + FC (position-preserving, unlike a
        # global average pool).
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(256, dtype=self.dtype)(x))
        return nn.Dense(self.n_classes, dtype=jnp.float32)(
            x.astype(jnp.float32))


class JaxCnn(JaxModel):
    """VGG-style CNN; width searched via a traced channel mask."""

    traced_knobs = frozenset({"learning_rate", "weight_decay"})
    traced_knob_defaults = {"learning_rate": 3e-3, "weight_decay": 1e-4}

    @staticmethod
    def get_knob_config():
        return {
            # Fraction of the supernet width actually used, searched in
            # sixteenths: 16/16 ..= 4/16. Traced -> no recompiles.
            "width_16ths": IntegerKnob(4, 16),
            "learning_rate": FloatKnob(3e-4, 3e-2, is_exp=True),
            "batch_size": CategoricalKnob([64, 128, 256]),
            "weight_decay": FloatKnob(1e-5, 1e-3, is_exp=True),
            "max_epochs": IntegerKnob(3, 40),
            "early_stop_epochs": FixedKnob(5),
        }

    def create_module(self, n_classes: int, image_shape: Sequence[int]):
        return _Cnn(n_classes=n_classes)

    def create_optimizer(self, steps_per_epoch: int, max_epochs: int):
        return self.traced_hyperparam_optimizer(
            steps_per_epoch, max_epochs, opt="adam", weight_decay=True)

    def extra_apply_inputs(self) -> Dict[str, np.ndarray]:
        # Keyed by the KNOB name: that's what excludes width_16ths from
        # the compiled-step cache key (see JaxModel._step_cache_key).
        sixteenths = int(self.knobs.get("width_16ths", 16))
        return {"width_16ths":
                (np.arange(16) < sixteenths).astype(np.float32)}

    def stack_signature(self):
        # Congruence metadata for vmap-stacked serving: the supernet
        # constants pin the family (module dataclass equality already
        # carries n_classes/base_width; the explicit tuple keeps the
        # contract stated even if the module grows non-compared state).
        return (*super().stack_signature(), MAX_WIDTH, N_STAGES)

    def quantized_apply(self, qvars, scales, fvars, x, extra):
        """Dequant-free int8 serving path for the conv zoo (the r13
        carry): every stage conv runs int8 x int8 -> int32 via
        ``dynamic_int8_conv`` (4-D kernels carry per-output-channel
        scales since r16) and the head Denses via
        ``dynamic_int8_matmul``, mirroring ``_Cnn.__call__``'s
        masked-supernet forward exactly — the ``bench.py --quant
        int8`` accuracy-delta gate is the regression net. A kernel
        the quantizer left in f32 falls back per layer, as the wire
        contract promises."""
        mask16 = extra["width_16ths"]
        h = x
        conv_i = 0
        for stage in range(N_STAGES):
            ch = MAX_WIDTH * (2 ** stage)
            mask = jnp.repeat(mask16, ch // 16)
            for _ in range(2):
                k = f"params/Conv_{conv_i}/kernel"
                b = fvars[f"params/Conv_{conv_i}/bias"] \
                    .astype(jnp.float32)
                if k in qvars:
                    h = dynamic_int8_conv(
                        h, qvars[k], scales[k],
                        padding=((1, 1), (1, 1))) + b
                else:  # per-layer f32 fallback
                    import jax

                    h = jax.lax.conv_general_dilated(
                        h, fvars[k].astype(jnp.float32), (1, 1),
                        ((1, 1), (1, 1)),
                        dimension_numbers=("NHWC", "HWIO", "NHWC")) + b
                h = jnp.maximum(h, 0.0)
                h = h * mask
                conv_i += 1
            if min(h.shape[1], h.shape[2]) >= 2:
                h = nn.max_pool(h, (2, 2), strides=(2, 2))
        h = h.reshape((h.shape[0], -1))

        def dense(v, i):
            k = f"params/Dense_{i}/kernel"
            b = fvars[f"params/Dense_{i}/bias"].astype(jnp.float32)
            if k in qvars:
                return dynamic_int8_matmul(v, qvars[k], scales[k]) + b
            return v @ fvars[k].astype(jnp.float32) + b
        h = jnp.maximum(dense(h, 0), 0.0)
        return dense(h, 1)
