"""Bundled model zoo (SURVEY.md §2 "Example models")."""

from .cnn import JaxCnn
from .densenet import JaxDenseNet
from .enas import JaxEnas
from .feedforward import JaxFeedForward
from .lm import JaxTransformerLM
from .pos_tagger import JaxPosTagger
from .sk import SkDt, SkSvm
from .tabular import JaxTabMlpClf, JaxTabMlpReg
from .transformer import JaxTransformerTagger
from .vit import JaxViT

__all__ = ["JaxFeedForward", "JaxCnn", "JaxDenseNet", "JaxEnas", "JaxViT",
           "JaxPosTagger", "SkDt", "SkSvm", "JaxTabMlpClf",
           "JaxTabMlpReg", "JaxTransformerTagger", "JaxTransformerLM"]
