"""Bundled model zoo (SURVEY.md §2 "Example models")."""

from .densenet import JaxDenseNet
from .enas import JaxEnas
from .feedforward import JaxFeedForward

__all__ = ["JaxFeedForward", "JaxDenseNet", "JaxEnas"]
