"""Bundled model zoo (SURVEY.md §2 "Example models")."""

from .feedforward import JaxFeedForward

__all__ = ["JaxFeedForward"]
