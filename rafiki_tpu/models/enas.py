"""JaxEnas: parity model for the reference's ``TfEnas`` — TPU-first.

Parity: SURVEY.md §2 "Example models" / §3.5 — upstream ``TfEnas`` is a
cell-based ENAS architecture search over CIFAR-10 (the reference's largest
model file): an RNN controller proposes a cell wiring, child models train
briefly on *shared* weights, and the controller is updated with REINFORCE
(the controller itself lives in ``rafiki_tpu.advisor.enas``).

TPU-first redesign (SURVEY.md §7 "Hard parts: ENAS on XLA"): upstream
rebuilds a fresh TF graph per proposed architecture — on XLA that would
mean a full recompile per trial. Here the search phase runs a **masked
supernet**: every candidate op's weights exist in one static graph, and
the architecture encoding enters as a *traced int32 input* (one-hot input
selection + one-hot op mixing), so hundreds of proposed architectures
execute against ONE XLA executable (see ``JaxModel.extra_apply_inputs``).
Weight sharing falls out for free: the supernet parameter tree is
architecture-independent, so ``ParamStore`` GLOBAL_RECENT warm-starts
overlay every tensor. The final phase (advisor retrains the best
architecture from scratch) builds a single-path network with the same
parameter naming — compiled once, no masking overhead.

Structural choices vs. upstream ENAS, for static shapes:
- Cell output concatenates ALL block outputs (not just loose ends) through
  a 1x1 projection — loose-end detection is data-dependent and would
  force recompiles.
- Reduction happens in the cell's input calibration (stride-2 1x1 convs),
  so every in-cell candidate op is stride-1 and shape-uniform.
- GroupNorm instead of BatchNorm: the supernet stays purely functional
  (no mutable batch_stats), which keeps masked/single-path graphs and
  multi-chip sharding simple.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..model import ArchKnob, FixedKnob, PolicyKnob
from ..model.jax_model import JaxModel, pad_crop_flip_graph

N_OPS = 5  # identity, sep-conv 3x3, sep-conv 5x5, avg-pool 3x3, max-pool 3x3


def _gn_groups(c: int) -> int:
    g = 8
    while g > 1 and c % g:
        g //= 2
    return g


class _SepConv(nn.Module):
    """ReLU -> depthwise kxk -> pointwise 1x1 -> GroupNorm."""
    features: int
    kernel: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        x = nn.Conv(x.shape[-1], (self.kernel, self.kernel),
                    feature_group_count=x.shape[-1], use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.Conv(self.features, (1, 1), use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=_gn_groups(self.features),
                         dtype=jnp.float32)(x)
        return x.astype(self.dtype)


class _Calibrate(nn.Module):
    """ReLU -> strided 1x1 conv -> GroupNorm: aligns a cell input to the
    cell's channel count and spatial resolution."""
    features: int
    stride: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = nn.relu(x)
        x = nn.Conv(self.features, (1, 1),
                    strides=(self.stride, self.stride), use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=_gn_groups(self.features),
                         dtype=jnp.float32)(x)
        return x.astype(self.dtype)


class _EnasNet(nn.Module):
    """Cell-based network; masked supernet when ``fixed_arch`` is None.

    The architecture encoding has shape (2, n_blocks, 4): cell type
    (normal / reduction) x block x (input1, op1, input2, op2). Input
    indices address ``[s0, s1, block_0, ..., block_{b-1}]``; op indices
    address the N_OPS candidate set.
    """

    n_blocks: int
    n_cells: int
    channels: int
    n_classes: int
    fixed_arch: Optional[Tuple[int, ...]] = None
    dtype: Any = jnp.bfloat16

    def _op(self, ci: int, b: int, slot: int, op, x, masked: bool):
        """Apply (masked mix of) the candidate ops for one block slot."""
        c = x.shape[-1]
        name = f"c{ci}_b{b}_s{slot}"

        def branch(i: int):
            if i == 0:
                return x
            if i == 1:
                return _SepConv(c, 3, dtype=self.dtype,
                                name=f"{name}_sep3")(x)
            if i == 2:
                return _SepConv(c, 5, dtype=self.dtype,
                                name=f"{name}_sep5")(x)
            if i == 3:
                return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
            return nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")

        if not masked:
            return branch(int(op))
        outs = jnp.stack([branch(i) for i in range(N_OPS)])
        w = jax.nn.one_hot(op, N_OPS, dtype=outs.dtype)
        return jnp.einsum("s,snhwc->nhwc", w, outs)

    def _cell(self, ci: int, s0, s1, c: int, reduction: bool, spec,
              masked: bool):
        stride = 2 if reduction else 1
        s1p = _Calibrate(c, stride, dtype=self.dtype,
                         name=f"c{ci}_pre1")(s1)
        s0_stride = s0.shape[1] // s1p.shape[1]
        s0p = _Calibrate(c, max(1, s0_stride), dtype=self.dtype,
                         name=f"c{ci}_pre0")(s0)

        states = [s0p, s1p]
        for b in range(self.n_blocks):
            in1, op1, in2, op2 = (spec[b, 0], spec[b, 1],
                                  spec[b, 2], spec[b, 3])
            if masked:
                stacked = jnp.stack(states)  # (b+2, N, H, W, C)
                x1 = jnp.einsum("s,snhwc->nhwc",
                                jax.nn.one_hot(in1, len(states),
                                               dtype=stacked.dtype), stacked)
                x2 = jnp.einsum("s,snhwc->nhwc",
                                jax.nn.one_hot(in2, len(states),
                                               dtype=stacked.dtype), stacked)
            else:
                x1, x2 = states[int(in1)], states[int(in2)]
            y = (self._op(ci, b, 0, op1, x1, masked)
                 + self._op(ci, b, 1, op2, x2, masked))
            states.append(y)

        out = jnp.concatenate(states[2:], axis=-1)
        out = nn.Conv(c, (1, 1), use_bias=False, dtype=self.dtype,
                      name=f"c{ci}_out")(out)
        out = nn.GroupNorm(num_groups=_gn_groups(c), dtype=jnp.float32,
                           name=f"c{ci}_out_gn")(out)
        return out.astype(self.dtype)

    @nn.compact
    def __call__(self, x, arch=None, train: bool = False):
        masked = self.fixed_arch is None
        if masked:
            assert arch is not None, "supernet mode needs the arch input"
        else:
            arch = np.asarray(self.fixed_arch,
                              np.int32).reshape(2, self.n_blocks, 4)

        x = x.astype(self.dtype)
        c = self.channels
        x = nn.Conv(c, (3, 3), padding=1, use_bias=False, dtype=self.dtype,
                    name="stem_conv")(x)
        x = nn.GroupNorm(num_groups=_gn_groups(c), dtype=jnp.float32,
                         name="stem_gn")(x).astype(self.dtype)

        reduce_at = ({self.n_cells // 3, (2 * self.n_cells) // 3}
                     if self.n_cells >= 3 else set())
        s0 = s1 = x
        for ci in range(self.n_cells):
            reduction = ci in reduce_at
            if reduction:
                c *= 2
            spec = arch[1 if reduction else 0]
            s0, s1 = s1, self._cell(ci, s0, s1, c, reduction, spec, masked)

        h = nn.relu(s1)
        h = h.mean(axis=(1, 2))
        return nn.Dense(self.n_classes, dtype=self.dtype, name="head")(h)


class JaxEnas(JaxModel):
    """ENAS cell search over CIFAR-scale image classification.

    Drive with ``rafiki_tpu.advisor.enas.EnasAdvisor``: search-phase trials
    get SHARE_PARAMS / QUICK_TRAIN / DOWNSCALE policies (masked supernet,
    shared weights, proxy size, 1 epoch); final-phase trials train the
    controller's best architecture from scratch at full size.
    """

    # Class-level sizing so tests can subclass a tiny variant; the arch
    # knob's position count derives from n_blocks.
    n_blocks = 4
    full_cells, full_channels = 6, 32
    search_cells, search_channels = 3, 16

    @classmethod
    def get_knob_config(cls):
        positions = []
        for _ct in range(2):
            for b in range(cls.n_blocks):
                positions += [list(range(b + 2)), list(range(N_OPS)),
                              list(range(b + 2)), list(range(N_OPS))]
        return {
            "arch": ArchKnob(positions),
            "batch_size": FixedKnob(128),
            "learning_rate": FixedKnob(0.05),
            "max_epochs": FixedKnob(10),
            "trial_epochs": FixedKnob(1),
            "share_params": PolicyKnob("SHARE_PARAMS"),
            "quick_train": PolicyKnob("QUICK_TRAIN"),
            "downscale": PolicyKnob("DOWNSCALE"),
        }

    # --- JaxModel hooks ---

    def _searching(self) -> bool:
        return bool(self.knobs.get("share_params", False))

    def create_module(self, n_classes: int, image_shape: Sequence[int]):
        cls = type(self)
        down = bool(self.knobs.get("downscale", False))
        return _EnasNet(
            n_blocks=cls.n_blocks,
            n_cells=cls.search_cells if down else cls.full_cells,
            channels=cls.search_channels if down else cls.full_channels,
            n_classes=n_classes,
            fixed_arch=(None if self._searching()
                        else tuple(int(v) for v in self.knobs["arch"])),
        )

    def extra_apply_inputs(self) -> Dict[str, np.ndarray]:
        if not self._searching():
            return {}
        arch = np.asarray([int(v) for v in self.knobs["arch"]], np.int32)
        return {"arch": arch.reshape(2, type(self).n_blocks, 4)}

    def create_optimizer(self, steps_per_epoch: int,
                         max_epochs: int) -> optax.GradientTransformation:
        # Child-model recipe: SGD momentum + cosine decay (ENAS paper).
        lr = float(self.knobs.get("learning_rate", 0.05))
        total = max(1, steps_per_epoch * max_epochs)
        sched = optax.cosine_decay_schedule(lr, decay_steps=total,
                                            alpha=1e-3)
        return optax.chain(
            optax.add_decayed_weights(1e-4),
            optax.sgd(sched, momentum=0.9, nesterov=True),
        )

    def augment_in_graph(self, x, rng):
        return pad_crop_flip_graph(x, rng)
