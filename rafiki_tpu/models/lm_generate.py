"""Paged-KV generative engine for :class:`JaxTransformerLM`.

``JaxTransformerLM.predict`` is one-shot: it recomputes the FULL
forward pass per call, so serving generation through it would cost
O(T²) recompute per emitted token and serialize every request behind
the longest sequence in its batch. This module is the token-level
split (Orca-style iteration scheduling over vLLM-style paged KV):

- **Page pool.** One preallocated device slab per projection —
  ``(L, n_pages·page_size, d)`` bf16 — plus a host-side allocator
  (:class:`PagePool`). Pages are an ALLOCATOR concept only: the device
  sees a flat token slab and every program indexes it by
  ``page·page_size + slot``, so alloc/free never move bytes. Physical
  page 0 is reserved scratch — padded/inactive lanes write there, so
  one fixed-shape program needs no masking on its stores.
- **Prefill program** (AOT, bucketed prompt lengths): the existing
  causal flash kernel over the whole prompt, K/V scattered into the
  sequence's pages, last-position logits out. Compiled once per
  bucket via the shared step cache.
- **Decode program** (ONE compiled shape): a single-token forward for
  a fixed batch width ``B`` reading K/V through a fixed-shape gather
  of ``P`` page slots per lane — any mix of sequence lengths runs the
  same executable, which is what makes per-step admission free.
  Sampling (greedy / gumbel-temperature, per-lane seed folded with
  position for batch-composition-independent draws) happens in-graph
  so resident tokens never leave the device between steps.
- **Prefix reuse.** Prompt pages are read-only after prefill (decode
  appends into LATER slots), so sequences sharing a prompt share its
  full pages by refcount; only a partially-filled tail page is copied
  (one on-device page copy). Keyed by the same content-address digest
  the r12 edge cache uses (``predictor.edge_cache.query_key``), so a
  shared system prompt skips prefill entirely.

The engine is single-threaded by contract: the worker's decode
scheduler (``worker/decode_scheduler.py``) is the only caller, from
its own loop thread. Nothing here touches metrics or the bus — the
scheduler layers those on.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..model.jax_model import (_step_cache_get, _step_cache_put,
                               step_cache_key)
from ..parallel import replicated
from ..predictor.edge_cache import query_key
from .transformer import _sinusoidal

NEG_INF = -1e30

#: Prompt-length buckets: each distinct bucket is one prefill compile,
#: so the ladder is geometric (the r16 megabatch lesson — a handful of
#: executables cover every shape).
PREFILL_BUCKETS = (32, 64, 128, 256, 512, 1024, 2048, 4096)


class PoolExhausted(RuntimeError):
    """No free page and nothing evictable — the admission gate."""


class PagePool:
    """Host-side refcounted page allocator over the device slab.

    Page 0 is reserved scratch (never handed out): fixed-shape
    programs direct padded/inactive writes there. ``retain`` is the
    prefix-sharing hook — a page is recycled only when its LAST
    holder frees it, so shared prompt pages survive any one
    sequence's exit. Single-page granularity means external
    fragmentation cannot exist: any free page serves any request.
    """

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is scratch)")
        self.n_pages = n_pages
        self._free = list(range(n_pages - 1, 0, -1))  # pop() -> low first
        self._ref: Dict[int, int] = {}

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return (self.n_pages - 1) - len(self._free)

    def alloc(self) -> int:
        """One free page (refcount 1). Raises :class:`PoolExhausted`
        when none is left — callers gate admission or evict first."""
        if not self._free:
            raise PoolExhausted("page pool exhausted")
        page = self._free.pop()
        self._ref[page] = 1
        return page

    def retain(self, page: int) -> None:
        if page not in self._ref:
            raise ValueError(f"retain of unallocated page {page}")
        self._ref[page] += 1

    def free(self, page: int) -> None:
        n = self._ref.get(page)
        if n is None:
            raise ValueError(f"free of unallocated page {page}")
        if n == 1:
            del self._ref[page]
            self._free.append(page)
        else:
            self._ref[page] = n - 1

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)


class _Seq:
    """One resident sequence's host-side state."""

    __slots__ = ("seq_id", "lane", "pages", "length", "prompt_len",
                 "last_token", "n_new", "max_new", "temperature",
                 "seed", "eos", "order", "tokens")

    def __init__(self, seq_id, lane, pages, length, prompt_len,
                 last_token, max_new, temperature, seed, eos, order,
                 tokens):
        self.seq_id = seq_id
        self.lane = lane              # decode-batch row
        self.pages = pages            # physical pages, logical order
        self.length = length          # tokens whose K/V are in the slab
        self.prompt_len = prompt_len
        self.last_token = last_token  # next decode input
        self.n_new = 1                # generated count (incl. last_token)
        self.max_new = max_new
        self.temperature = temperature
        self.seed = seed
        self.eos = eos
        self.order = order            # admission order (eviction picks max)
        self.tokens = tokens          # prompt + generated (for preemption)


def prefix_digest(tokens) -> str:
    """Content address of a token prefix — the same digest family the
    r12 edge cache uses, applied to the token ids themselves."""
    return query_key(list(int(t) for t in tokens))


class LMGenerator:
    """Continuous-batching generation engine over one trained
    :class:`JaxTransformerLM`.

    Fixed shapes: ``decode_batch`` lanes × ``pages_per_seq`` page
    slots; one compiled decode program serves any mix of lengths.
    ``admit`` prefs a prompt into freshly-allocated pages (or reuses
    a cached prefix) and returns the first sampled token;
    ``step`` advances every resident sequence one token. ``step``
    auto-evicts the YOUNGEST resident sequence when a mid-step page
    allocation fails and reports it, so the scheduler can re-queue
    the preempted request (its tokens so far become the new prompt).
    """

    def __init__(self, model, *, page_size: int = 16,
                 n_pages: int = 128, decode_batch: int = 4,
                 max_new_cap: int = 256,
                 prefix_cache_entries: int = 16,
                 stager: Optional[Callable[[np.ndarray], Any]] = None):
        if page_size < 1 or decode_batch < 1:
            raise ValueError("page_size and decode_batch must be >= 1")
        self._model = model
        self._dims = model._dims()
        self.page_size = page_size
        self.n_pages = n_pages
        self.decode_batch = decode_batch
        self.max_new_cap = max_new_cap
        # Per-lane page-slot budget: enough for a full-length prompt
        # plus the generation cap, rounded up to pages.
        self.pages_per_seq = max(
            1, -(-(self._dims["t"] + max_new_cap) // page_size))
        self.max_tokens = self.pages_per_seq * page_size
        self.pool = PagePool(n_pages)
        self._rep = replicated(model.mesh)
        self._stager = stager or (
            lambda ids: jax.device_put(ids, self._rep))
        self._params = jax.device_put(model._params, self._rep)
        s = self._dims
        slab = n_pages * page_size
        self._k_pool = jax.device_put(
            jnp.zeros((s["layers"], slab, s["d"]), jnp.bfloat16),
            self._rep)
        self._v_pool = jax.device_put(
            jnp.zeros((s["layers"], slab, s["d"]), jnp.bfloat16),
            self._rep)
        self._seqs: Dict[Any, _Seq] = {}
        self._lanes: List[Optional[Any]] = [None] * decode_batch
        self._order = 0
        #: digest -> (pages, n_full, prompt_len, first_logits np)
        self._prefix: "Dict[str, Tuple[List[int], int, int, np.ndarray]]" = {}
        self._prefix_lru: List[str] = []
        self._prefix_cap = max(0, prefix_cache_entries)
        # Counters (host ints; the scheduler exports the interesting
        # ones through the gated observe.lm family).
        self.prefills_total = 0
        self.prefill_skipped_total = 0
        self.decode_steps_total = 0
        self.tokens_total = 0
        self.evictions_total = 0
        self.last_logits: Dict[Any, np.ndarray] = {}
        # AOT: the decode executable is the per-token hot path — pay
        # its compile at construction, not under the first request.
        self._decode = self._decode_fn()
        self._decode_aot = None
        self._warm_decode()

    # ---- compiled programs (shared step cache) ----

    def _decode_fn(self):
        m = self._model
        key = step_cache_key(m, "paged_decode", m.mesh,
                             self.decode_batch, self.pages_per_seq,
                             self.page_size, self.n_pages)
        cached = _step_cache_get(key)
        if cached is not None:
            return cached["fn"]
        fn = _build_decode(self._dims, self.page_size,
                           self.pages_per_seq, self.decode_batch)
        _step_cache_put(key, {"fn": fn})
        return fn

    def _warm_decode(self) -> None:
        """Lower+compile the decode program ahead of traffic (AOT).
        Donated-buffer warmup would consume the live pool, so compile
        against abstract shapes only."""
        B, P = self.decode_batch, self.pages_per_seq
        sd = jax.ShapeDtypeStruct

        def like(a):  # keep the live arrays' sharding in the AOT trace
            return sd(a.shape, a.dtype, sharding=a.sharding)

        rep = self._rep
        args = (jax.tree.map(like, self._params),
                like(self._k_pool), like(self._v_pool),
                sd((B,), jnp.int32, sharding=rep),
                sd((B, P), jnp.int32, sharding=rep),
                sd((B,), jnp.int32, sharding=rep),
                sd((B,), jnp.float32, sharding=rep),
                sd((B,), jnp.int32, sharding=rep))
        self._decode_aot = self._decode.lower(*args).compile()

    def _prefill_fn(self, bucket: int):
        m = self._model
        key = step_cache_key(m, "paged_prefill", m.mesh, bucket,
                             self.page_size, self.n_pages)
        cached = _step_cache_get(key)
        if cached is not None:
            return cached["fn"]
        fn = _build_prefill(self._dims, bucket, m._block)
        _step_cache_put(key, {"fn": fn})
        return fn

    def _copy_page_fn(self):
        m = self._model
        key = step_cache_key(m, "paged_copy", m.mesh, self.page_size,
                             self.n_pages)
        cached = _step_cache_get(key)
        if cached is not None:
            return cached["fn"]
        ps = self.page_size
        s = self._dims

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def copy_page(k_pool, v_pool, src, dst):
            ksrc = jax.lax.dynamic_slice(
                k_pool, (0, src * ps, 0), (s["layers"], ps, s["d"]))
            vsrc = jax.lax.dynamic_slice(
                v_pool, (0, src * ps, 0), (s["layers"], ps, s["d"]))
            k_pool = jax.lax.dynamic_update_slice(
                k_pool, ksrc, (0, dst * ps, 0))
            v_pool = jax.lax.dynamic_update_slice(
                v_pool, vsrc, (0, dst * ps, 0))
            return k_pool, v_pool

        _step_cache_put(key, {"fn": copy_page})
        return copy_page

    # ---- admission ----

    def resident(self) -> int:
        return len(self._seqs)

    def pool_used_ratio(self) -> float:
        usable = self.pool.n_pages - 1
        return self.pool.used_pages / usable if usable else 0.0

    def resident_tokens(self) -> int:
        """Tokens whose K/V is live in the paged cache right now."""
        return sum(s.length for s in self._seqs.values())

    def _pages_needed(self, prompt_len: int) -> int:
        return -(-max(1, prompt_len + 1) // self.page_size)

    def can_admit(self, prompt_len: int) -> bool:
        """Admission gate: a free lane AND enough pages for the prompt
        plus the first generated token (prefix-cache hits need fewer,
        but the gate stays conservative — a hit only helps). Reclaims
        cache-held prefix pages (LRU) when short: LIVE sequences
        always outrank cached prefixes for pool space."""
        if len(self._seqs) >= self.decode_batch:
            return False
        need = self._pages_needed(prompt_len)
        if self.pool.free_pages < need:
            self._reclaim_prefix(need)
        return self.pool.free_pages >= need

    def _alloc_page(self) -> int:
        """Pool alloc that spills the prefix cache before failing."""
        try:
            return self.pool.alloc()
        except PoolExhausted:
            self._reclaim_prefix(1)
            return self.pool.alloc()

    def _reclaim_prefix(self, want_pages: int) -> None:
        """Drop LRU prefix-cache entries until ``want_pages`` pages
        are free (or the cache is empty). Shared pages only lose the
        cache's reference — sequences still decoding over them are
        untouched."""
        while self.pool.free_pages < want_pages and self._prefix_lru:
            digest = self._prefix_lru.pop(0)
            pages, _nf, _pl, _lg = self._prefix.pop(digest)
            for p in pages:
                self.pool.free(p)

    def admit(self, tokens: List[int], *, max_new: int,
              temperature: float = 0.0, seed: int = 0,
              eos: Optional[int] = None, seq_id: Any = None
              ) -> Tuple[Any, int]:
        """Prefill (or prefix-reuse) one prompt and return
        ``(seq_id, first_token)``. Raises :class:`PoolExhausted` when
        ``can_admit`` would be False — callers gate first."""
        tokens = [int(t) for t in tokens]
        if not tokens:
            raise ValueError("empty prompt")
        if len(tokens) + 1 > self.max_tokens:
            tokens = tokens[-(self.max_tokens - max(1, max_new)):]
        max_new = max(1, min(int(max_new), self.max_new_cap,
                             self.max_tokens - len(tokens)))
        lane = next((i for i, s in enumerate(self._lanes)
                     if s is None), None)
        if lane is None or not self.can_admit(len(tokens)):
            raise PoolExhausted("no lane/pages for admission")
        digest = prefix_digest(tokens)
        hit = self._prefix.get(digest)
        if hit is not None:
            pages, first_logits = self._adopt_prefix(hit)
            self.prefill_skipped_total += 1
        else:
            pages, first_logits = self._prefill(tokens)
            self._insert_prefix(digest, pages, len(tokens),
                                first_logits)
        first = self._sample_host(first_logits, temperature, seed,
                                  len(tokens))
        if seq_id is None:
            seq_id = f"seq-{self._order}"
        seq = _Seq(seq_id, lane, pages, len(tokens), len(tokens),
                   first, max_new, float(temperature), int(seed), eos,
                   self._order, tokens + [first])
        self._order += 1
        self._lanes[lane] = seq_id
        self._seqs[seq_id] = seq
        self.last_logits[seq_id] = first_logits
        self.tokens_total += 1
        return seq_id, first

    def _prefill(self, tokens: List[int]
                 ) -> Tuple[List[int], np.ndarray]:
        n = len(tokens)
        pages = [self._alloc_page()
                 for _ in range(self._pages_needed(n))]
        bucket = next((b for b in PREFILL_BUCKETS if b >= n),
                      PREFILL_BUCKETS[-1])
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :n] = tokens
        pos = np.zeros((bucket,), np.int32)  # padding -> scratch page 0
        for i in range(n):
            pos[i] = pages[i // self.page_size] * self.page_size \
                + i % self.page_size
        fn = self._prefill_fn(bucket)
        logits, self._k_pool, self._v_pool = fn(
            self._params, self._k_pool, self._v_pool,
            jnp.asarray(ids), jnp.asarray(pos),
            jnp.int32(n - 1))
        self.prefills_total += 1
        return pages, np.asarray(logits)

    # ---- prefix cache ----

    def _insert_prefix(self, digest: str, pages: List[int],
                       prompt_len: int, logits: np.ndarray) -> None:
        if self._prefix_cap <= 0 or digest in self._prefix:
            return
        for p in pages:
            self.pool.retain(p)  # the cache's own reference
        n_full = prompt_len // self.page_size
        self._prefix[digest] = (list(pages), n_full, prompt_len,
                                logits)
        self._prefix_lru.append(digest)
        while len(self._prefix_lru) > self._prefix_cap:
            old = self._prefix_lru.pop(0)
            old_pages, _nf, _pl, _lg = self._prefix.pop(old)
            for p in old_pages:
                self.pool.free(p)

    def _adopt_prefix(self, hit) -> Tuple[List[int], np.ndarray]:
        """Share the hit's full pages by refcount; copy a partial tail
        page (decode will append INTO it). Device copy is one fused
        dynamic-slice program per adoption."""
        pages, n_full, _prompt_len, logits = hit
        out: List[int] = []
        for p in pages[:n_full]:
            self.pool.retain(p)
            out.append(p)
        for p in pages[n_full:]:  # at most one partial tail page
            dst = self._alloc_page()
            self._k_pool, self._v_pool = self._copy_page_fn()(
                self._k_pool, self._v_pool, jnp.int32(p),
                jnp.int32(dst))
            out.append(dst)
        return out, logits

    # ---- decode ----

    def _ensure_page(self, seq: _Seq) -> bool:
        """Make sure the slot for position ``seq.length`` exists.
        False = allocation failed (pool pressure)."""
        need = seq.length // self.page_size
        if need < len(seq.pages):
            return True
        try:
            seq.pages.append(self._alloc_page())
            return True
        except PoolExhausted:
            return False

    def evict_youngest(self) -> Optional[Dict[str, Any]]:
        """Preempt the most recently admitted resident sequence: free
        its pages and return enough state to re-queue it (tokens so
        far become the new prompt; generated count carries so the
        budget is honored across the preemption)."""
        if not self._seqs:
            return None
        seq = max(self._seqs.values(), key=lambda s: s.order)
        self._release(seq)
        self.evictions_total += 1
        return {"seq_id": seq.seq_id, "tokens": list(seq.tokens),
                "n_done": seq.n_new, "max_new": seq.max_new,
                "temperature": seq.temperature, "seed": seq.seed,
                "eos": seq.eos}

    def finish(self, seq_id: Any) -> None:
        seq = self._seqs.get(seq_id)
        if seq is not None:
            self._release(seq)

    def _release(self, seq: _Seq) -> None:
        for p in seq.pages:
            self.pool.free(p)
        self._lanes[seq.lane] = None
        del self._seqs[seq.seq_id]
        # last_logits deliberately survives release: the finishing
        # step's logits are read AFTER the sequence is gone (parity
        # checks, the scheduler's final frame); pruned in step().

    def step(self) -> Tuple[List[Tuple[Any, int, Optional[str]]],
                            List[Dict[str, Any]]]:
        """One decode step for every resident sequence.

        Returns ``(results, evicted)``: results are
        ``(seq_id, token, finish)`` triples — ``finish`` is ``None``
        (still going), ``"eos"`` or ``"length"`` — and ``evicted``
        lists preempted-sequence states (pool pressure made room for
        the sequences that DID step).
        """
        evicted: List[Dict[str, Any]] = []
        # Page pressure: every stepping sequence needs its write slot;
        # evict youngest-first until the remaining set fits.
        while True:
            ordered = sorted(self._seqs.values(), key=lambda s: s.order)
            if all(self._ensure_page(s) for s in ordered):
                break
            ev = self.evict_youngest()
            if ev is None:
                break
            evicted.append(ev)
        if not self._seqs:
            return [], evicted
        B, P = self.decode_batch, self.pages_per_seq
        ids = np.zeros((B,), np.int32)
        slots = np.zeros((B, P), np.int32)
        lengths = np.zeros((B,), np.int32)
        temps = np.zeros((B,), np.float32)
        seeds = np.zeros((B,), np.int32)
        for seq in self._seqs.values():
            ids[seq.lane] = seq.last_token
            slots[seq.lane, :len(seq.pages)] = seq.pages
            lengths[seq.lane] = seq.length
            temps[seq.lane] = seq.temperature
            seeds[seq.lane] = seq.seed
        # The per-step token H2D hop rides the pinned stager when the
        # runtime has one (worker registration records which).
        ids_dev = self._stager(ids)
        put = functools.partial(jax.device_put, device=self._rep)
        next_ids, logits, self._k_pool, self._v_pool = \
            self._decode_aot(self._params, self._k_pool, self._v_pool,
                             ids_dev, put(slots), put(lengths),
                             put(temps), put(seeds))
        self.decode_steps_total += 1
        next_host = np.asarray(next_ids)
        logits_host = None  # fetched lazily, only if a caller asks
        results: List[Tuple[Any, int, Optional[str]]] = []
        for seq in list(self._seqs.values()):
            tok = int(next_host[seq.lane])
            seq.length += 1          # last_token's K/V is now in-slab
            seq.last_token = tok
            seq.n_new += 1
            seq.tokens.append(tok)
            self.tokens_total += 1
            if logits_host is None:
                logits_host = np.asarray(logits)
            self.last_logits[seq.seq_id] = logits_host[seq.lane]
            finish = None
            if seq.eos is not None and tok == seq.eos:
                finish = "eos"
            elif seq.n_new >= seq.max_new:
                finish = "length"
            results.append((seq.seq_id, tok, finish))
            if finish is not None:
                self._release(seq)
        while len(self.last_logits) > 8 * self.decode_batch:
            self.last_logits.pop(next(iter(self.last_logits)))
        return results, evicted

    # ---- host sampling (first token, from prefill logits) ----

    @staticmethod
    def _sample_host(logits: np.ndarray, temperature: float,
                     seed: int, position: int) -> int:
        if temperature <= 0:
            return int(np.argmax(logits))
        rng = np.random.default_rng((int(seed) << 20) ^ position)
        g = rng.gumbel(size=logits.shape)
        return int(np.argmax(logits / max(temperature, 1e-6) + g))

    def close(self) -> None:
        for seq_id in list(self._seqs):
            self.finish(seq_id)
        for digest in list(self._prefix_lru):
            pages, _nf, _pl, _lg = self._prefix.pop(digest)
            for p in pages:
                self.pool.free(p)
        self._prefix_lru.clear()
        self._k_pool = self._v_pool = None


# ---- program builders -------------------------------------------------


def _layer_norm(x, g):
    xf = x.astype(jnp.float32)
    m = xf.mean(-1, keepdims=True)
    v = ((xf - m) ** 2).mean(-1, keepdims=True)
    return (xf - m) * jax.lax.rsqrt(v + 1e-6) * g


def _build_decode(dims, page_size: int, pages_per_seq: int,
                  batch: int):
    """The ONE decode executable: fixed ``(B, P)`` shapes, any mix of
    sequence lengths. Pools are donated — the step updates in place."""
    d, h, L, v = dims["d"], dims["h"], dims["layers"], dims["v"]
    dh = d // h
    ps, P, B = page_size, pages_per_seq, batch
    T = P * ps
    pe = _sinusoidal(T, d)

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def decode_step(params, k_pool, v_pool, ids, slots, lengths,
                    temps, seeds):
        emb = params["embed"].astype(jnp.bfloat16)
        pos = jnp.asarray(pe)
        x = emb[ids] * jnp.bfloat16(math.sqrt(d)) \
            + pos[lengths].astype(jnp.bfloat16)          # (B, d)
        # Store slot for the incoming token; gather map for the whole
        # logical sequence. Lengths of 0 (idle lanes) write/read the
        # scratch page — finite garbage the mask keeps out of real
        # lanes and idle lanes' outputs are discarded on the host.
        write_pos = slots[jnp.arange(B), lengths // ps] * ps \
            + lengths % ps                               # (B,)
        gather = (slots[:, :, None] * ps
                  + jnp.arange(ps)[None, None, :]).reshape(B, T)
        kv_mask = jnp.arange(T)[None, :] <= lengths[:, None]

        def one_layer(x, layer):
            lp, kp, vp = layer
            hid = _layer_norm(x, lp["ln1"]).astype(jnp.bfloat16)
            qkv = hid @ lp["qkv"].astype(jnp.bfloat16)   # (B, 3d)
            q, k_new, v_new = jnp.split(qkv, 3, axis=-1)
            kp = kp.at[write_pos].set(k_new)
            vp = vp.at[write_pos].set(v_new)
            kh = kp[gather].reshape(B, T, h, dh).transpose(0, 2, 1, 3)
            vh = vp[gather].reshape(B, T, h, dh).transpose(0, 2, 1, 3)
            qh = q.reshape(B, h, dh)
            s = jnp.einsum("bhd,bhtd->bht", qh, kh
                           ).astype(jnp.float32) / math.sqrt(dh)
            s = jnp.where(kv_mask[:, None, :], s, NEG_INF)
            w = jax.nn.softmax(s, axis=-1).astype(jnp.bfloat16)
            o = jnp.einsum("bht,bhtd->bhd", w, vh).reshape(B, d)
            x = x + (o @ lp["proj"].astype(jnp.bfloat16)
                     ).astype(x.dtype)
            hid = _layer_norm(x, lp["ln2"]).astype(jnp.bfloat16)
            hid = jax.nn.gelu(hid @ lp["w1"].astype(jnp.bfloat16))
            return x + (hid @ lp["w2"].astype(jnp.bfloat16)
                        ).astype(x.dtype), (kp, vp)

        x, (k_pool, v_pool) = jax.lax.scan(
            one_layer, x, (params["layers"], k_pool, v_pool))
        x = _layer_norm(x, params["lnf"]).astype(jnp.bfloat16)
        logits = (x @ emb.T).astype(jnp.float32)         # (B, v)
        greedy = jnp.argmax(logits, -1)
        # Seed folded with the POSITION, not the lane: the same
        # (seed, position) draws the same gumbel noise no matter how
        # admission packed the batch — sampling is reproducible under
        # continuous batching by construction.
        base = jax.random.key(0)
        keys = jax.vmap(lambda s_, l_: jax.random.fold_in(
            jax.random.fold_in(base, s_), l_))(seeds, lengths)
        gum = jax.vmap(
            lambda k_: jax.random.gumbel(k_, (v,), jnp.float32))(keys)
        temp = jnp.maximum(temps, 1e-6)[:, None]
        sampled = jnp.argmax(logits / temp + gum, -1)
        next_ids = jnp.where(temps > 0.0, sampled,
                             greedy).astype(jnp.int32)
        return next_ids, logits, k_pool, v_pool

    return decode_step


def _build_prefill(dims, bucket: int, block_fn):
    """One prefill executable per prompt-length bucket: the existing
    causal flash block over the padded prompt, K/V captured per layer
    and scattered into the sequence's pages (padding lands on the
    scratch page), last-valid-position logits out. ``block_fn`` is the
    model's ``_block`` — prefill shares the training block's math (and
    its flash kernel) verbatim; only the K/V capture is new."""
    d, h, L = dims["d"], dims["h"], dims["layers"]
    Tb = bucket
    pe = _sinusoidal(Tb, d)

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def prefill(params, k_pool, v_pool, ids, pos_idx, last):
        emb = params["embed"].astype(jnp.bfloat16)
        x = emb[ids] * jnp.bfloat16(math.sqrt(d))
        x = x + jnp.asarray(pe)[None].astype(x.dtype)

        def one_layer(x, lp):
            # Same block as training/forward, but capture K/V: redo
            # the qkv projection on the normalized input (cheap next
            # to attention) so block_fn itself stays untouched.
            hid = _layer_norm(x, lp["ln1"]).astype(jnp.bfloat16)
            qkv = hid @ lp["qkv"].astype(jnp.bfloat16)
            _q, k, v = jnp.split(qkv, 3, axis=-1)
            return block_fn(x, lp, h), (k[0], v[0])

        x, (ks, vs) = jax.lax.scan(one_layer, x, params["layers"])
        # ks (L, Tb, d) -> scatter into the slab rows pos_idx.
        k_pool = k_pool.at[:, pos_idx].set(ks)
        v_pool = v_pool.at[:, pos_idx].set(vs)
        x = _layer_norm(x, params["lnf"]).astype(jnp.bfloat16)
        xlast = jax.lax.dynamic_index_in_dim(x[0], last, 0,
                                             keepdims=False)
        logits = (xlast @ emb.T).astype(jnp.float32)     # (v,)
        return logits, k_pool, v_pool

    return prefill
